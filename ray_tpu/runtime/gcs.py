"""GCS: the cluster control plane (head-node service).

Reference analog: ``src/ray/gcs/gcs_server/`` — node registry + health
(``GcsNodeManager``, ``GcsHealthCheckManager`` gcs_health_check_manager.h:39),
actor registry and scheduling (``GcsActorManager`` gcs_actor_manager.cc:246,
632, restart logic :1100), KV store (``GcsKvManager``), object directory
(owner-based in the reference; centralized here), pubsub
(``gcs_server/pubsub_handler.cc``), placement groups
(``GcsPlacementGroupManager`` — 2-phase reserve/commit), and the cluster
resource view (``GcsResourceManager`` fed by the ray_syncer).

One process/thread, guarded by a single lock — the control plane is
low-rate; the data plane (objects) never flows through here.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from ray_tpu.runtime import fault_injection as _fi
from ray_tpu.runtime.rpc import RpcServer, send_msg

# Pubsub channels (reference: pubsub.proto:28 channel enum).
CH_NODE = "node"            # node added/dead
CH_ACTOR = "actor"          # actor state transitions
CH_OBJECT = "object"        # object location added (get() wakeups)
CH_ERROR = "error"          # error broadcast to drivers
CH_LOGS = "logs"            # captured log lines (log plane fan-out)
CH_METRICS = "metrics"      # rolled metric-window summaries (dashboards)


@dataclass
class NodeInfo:
    node_id: str
    address: tuple          # raylet RPC address
    store_name: str         # shm segment name (same-host attach fast path)
    resources: dict         # total
    available: dict
    labels: dict = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    # versioned resource view (reference: ray_syncer.h:86) — the last
    # applied RESOURCE_VIEW version; -1 = never synced (ask the raylet
    # for a full push on its next heartbeat)
    resource_version: int = -1
    # ready-queue depth from the versioned view (placement tiebreak)
    load: int = 0
    # latest reporter sample from the node (cpu/mem/spill-disk)
    host_stats: dict = field(default_factory=dict)
    # per-node dashboard agent RPC address (reference: dashboard/agent.py
    # — observability decoupled from the raylet data plane)
    agent_addr: tuple | None = None


@dataclass
class ActorInfo:
    actor_id: str
    name: str | None
    state: str              # PENDING | ALIVE | RESTARTING | DEAD
    # logical namespace scoping the name (reference: worker.py:1157 —
    # named actors are unique PER NAMESPACE, not cluster-global)
    namespace: str = "default"
    # owner-scoped lifetime (reference: gcs_actor_manager.cc:632 — a
    # non-detached actor dies with its owner; lifetime="detached" opts
    # out, actor.py:524). owner_id is the creating client; None (e.g.
    # external-language clients) means detached.
    owner_id: str | None = None
    detached: bool = True
    node_id: str | None = None
    creation_spec: bytes | None = None   # pickled wire spec (for restart)
    resources: dict = field(default_factory=dict)
    max_restarts: int = 0
    num_restarts: int = 0
    death_reason: str = ""
    # placement constraint recorded so restart honors it
    pg_id: str | None = None
    # the live worker's owner-facing push port (direct actor submission);
    # None until ready, reset on restart (stale addrs must not be dialed)
    push_addr: tuple | None = None


@dataclass
class PlacementGroupInfo:
    pg_id: str
    strategy: str                       # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    bundles: list                       # list[dict resource -> amount]
    state: str = "PENDING"              # PENDING | CREATED | REMOVED
    bundle_nodes: list = field(default_factory=list)  # node_id per bundle


class GcsPersistence:
    """File-backed store client (reference: ``StoreClient`` behind the
    GCS — ``store_client/redis_store_client.h:33`` — plus restart reload
    via ``gcs_init_data.cc``; Redis is not in this image, so the durable
    medium is the session directory).

    Layout: ``snapshot.pkl`` (periodic full-state dump, atomic rename)
    + ``wal.bin`` (length-prefixed pickled mutation records appended
    between snapshots and truncated by each snapshot). Restart = load
    snapshot, replay WAL."""

    def __init__(self, path: str):
        import os

        os.makedirs(path, exist_ok=True)
        self.snap_path = os.path.join(path, "snapshot.pkl")
        self.wal_path = os.path.join(path, "wal.bin")
        self._wal_f = None
        self._io_lock = threading.Lock()

    def append(self, record: tuple):
        import pickle
        import struct

        blob = pickle.dumps(record, protocol=5)
        with self._io_lock:
            if self._wal_f is None:
                self._wal_f = open(self.wal_path, "ab")
            self._wal_f.write(struct.pack(">I", len(blob)) + blob)
            self._wal_f.flush()

    def rotate_wal(self):
        """Move the live WAL aside (cheap, lock-held by the caller along
        with the state capture). Records in the rotated file stay
        replayable until ``commit_snapshot`` lands the state that
        contains them — a crash in between loses nothing."""
        import os

        with self._io_lock:
            if self._wal_f is not None:
                self._wal_f.close()
                self._wal_f = None
            if os.path.exists(self.wal_path):
                os.replace(self.wal_path, self.wal_path + ".rotated")

    def commit_snapshot(self, state: dict):
        """Write the snapshot (slow disk IO — caller holds NO state lock)
        and retire the rotated WAL it supersedes."""
        import os
        import pickle

        with self._io_lock:
            tmp = self.snap_path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(state, f, protocol=5)
            os.replace(tmp, self.snap_path)
            try:
                os.remove(self.wal_path + ".rotated")
            except OSError:
                pass

    def snapshot(self, state: dict):
        """Atomic capture-and-fold (small states / shutdown path)."""
        self.rotate_wal()
        self.commit_snapshot(state)

    def load(self) -> tuple[dict | None, list]:
        import os
        import pickle
        import struct

        state = None
        if os.path.exists(self.snap_path):
            try:
                with open(self.snap_path, "rb") as f:
                    state = pickle.load(f)
            except Exception:  # noqa: BLE001 - torn snapshot: WAL only
                state = None
        records = []
        # a .rotated WAL outlives a crash between rotation and snapshot
        # commit — replay it FIRST (its records predate the live WAL's)
        for path in (self.wal_path + ".rotated", self.wal_path):
            if not os.path.exists(path):
                continue
            try:
                with open(path, "rb") as f:
                    data = f.read()
                off = 0
                while off + 4 <= len(data):
                    (n,) = struct.unpack_from(">I", data, off)
                    off += 4
                    if off + n > len(data):
                        break   # torn tail record (crash mid-append)
                    records.append(pickle.loads(data[off:off + n]))
                    off += n
            except Exception:  # noqa: BLE001
                pass
        return state, records

    def close(self):
        with self._io_lock:
            if self._wal_f is not None:
                self._wal_f.close()
                self._wal_f = None


class GcsServer(RpcServer):
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_timeout_s: float = 5.0,
                 persistence_dir: str | None = None):
        super().__init__(host, port)
        self.fault_label = "gcs"   # fault-injection endpoint label
        _fi.maybe_init_from_config()
        self._lock = threading.RLock()
        self._nodes: dict[str, NodeInfo] = {}
        self._actors: dict[str, ActorInfo] = {}
        self._named_actors: dict[str, str] = {}
        self._kv: dict[str, dict[str, bytes]] = {}
        self._object_dir: dict[str, set[str]] = {}   # oid -> node ids
        self._object_meta: dict[str, int] = {}       # oid -> size (for ref)
        # objects whose LAST location died (known-then-lost tombstones):
        # distinguishes "task hasn't produced it yet" from "needs lineage
        # reconstruction" for owners (reference: the owner learns loss via
        # object-eviction pubsub + ObjectDirectory). Bounded: a dict in
        # insertion order, oldest dropped past the cap — a tombstone only
        # matters while some owner still wants the object.
        self._lost_objects: dict[str, None] = {}
        self._max_lost_objects = 100_000
        self._pgs: dict[str, PlacementGroupInfo] = {}
        self._jobs: dict[str, dict] = {}
        # cached host_actors channels, one per raylet (see _place_batch)
        self._placement_clients: dict[tuple, Any] = {}
        self._placement_lock = threading.Lock()
        # Bounded placement executor (reference: GcsActorScheduler's
        # shared io_context — NOT thread-per-actor): host_actors batches
        # queue here; at most gcs_placement_pool_size workers drain it.
        from ray_tpu.utils.config import get_config as _gcfg
        _pcfg = _gcfg()
        self._place_pool_size = max(1, _pcfg.gcs_placement_pool_size)
        self._place_batch_cap = max(1, _pcfg.gcs_placement_batch_size)
        self._place_queue: deque = deque()
        self._place_cv = threading.Condition()
        self._place_threads: list[threading.Thread] = []
        # pubsub: channel -> list of (conn, send_lock)
        self._subs: dict[str, list] = {}
        # CH_ACTOR per-subscriber coalescing: actor events buffer per
        # held conn and a flusher ships ONE framed batch per subscriber
        # per window — rpc_actor_ready no longer pays an inline send_msg
        # per actor per subscriber under a creation flood.
        self._pub_flush_s = _pcfg.actor_pubsub_flush_s
        self._pub_buf: dict[int, tuple] = {}   # id(conn) -> (conn, lock, [msgs])
        self._pub_cv = threading.Condition()
        # creation-phase decomposition (register -> place -> ready),
        # cumulative; actor_id -> (t_register, t_placed) while in flight
        self._plane = {
            "register_batches": 0, "register_actors": 0,
            "register_batch_max": 0, "host_batches": 0, "host_actors": 0,
            "host_batch_max": 0, "ready_batches": 0, "ready_actors": 0,
            "place_s": 0.0, "placed": 0, "ready_s": 0.0, "ready": 0,
        }
        self._plane_t: dict[str, list] = {}
        # actor-plane stage durations ALSO land in plane histograms so
        # the metrics plane can answer p99 place/ready queries (the
        # counters above stay — bench decomposition reads them)
        from ray_tpu.util import metrics as _metrics
        self._plane_hist = _metrics.histogram(
            "ray_tpu_actor_stage_s",
            "actor control-plane stage latency", tag_keys=("stage",))
        # --- cluster metrics plane: ring-buffer time-series store fed
        # by rpc_push_metrics; rolled windows fan out on CH_METRICS ---
        from ray_tpu.runtime.metrics_plane import MetricsStore
        self._metrics_store = MetricsStore(
            window_s=_pcfg.metrics_window_s,
            windows=_pcfg.metrics_windows,
            on_roll=self._publish_metrics_window)
        self._metrics_push_interval = _pcfg.metrics_push_interval_s
        self._metrics_stop = threading.Event()
        # --- distributed tracing plane: cluster span ring fed by
        # rpc_push_spans (spans ride the metrics pusher ticks) ---
        from ray_tpu.util.tracing import TraceStore
        self._trace_store = TraceStore(
            max_traces=_pcfg.trace_store_traces,
            max_spans=_pcfg.trace_store_spans,
            sample_n=_pcfg.trace_sample_n,
            slow_s=_pcfg.trace_slow_s)
        # --- cluster log plane: bounded per-proc line rings + error
        # groups, fed by rpc_push_logs; accepted lines fan out on
        # CH_LOGS (runtime/log_plane.py) ---
        from ray_tpu.runtime.log_plane import LogStore
        self._log_store = LogStore(
            lines_per_proc=_pcfg.log_store_lines,
            error_lines=_pcfg.log_store_error_lines,
            error_groups=_pcfg.log_store_error_groups)
        self._hb_timeout = heartbeat_timeout_s
        # --- distributed refcounting (reference: reference_count.h:61;
        # centralized here to match the centralized object directory).
        # count(oid) = holders + task pins + contains edges; a decrement
        # to zero releases every registered copy cluster-wide. ---
        from ray_tpu.utils.config import get_config as _get_config
        _cfg = _get_config()
        self._client_timeout = _cfg.client_timeout_s
        self._ref_grace = _cfg.ref_release_grace_s
        self._clients: dict[str, dict] = {}        # id -> kind/last_seen/alive
        self._ref_holders: dict[str, set] = {}     # oid -> holder client ids
        self._ref_pins: dict[str, tuple] = {}      # task_id -> (client, oids)
        self._ref_pin_count: dict[str, int] = {}   # oid -> pin contributions
        self._pin_released: dict[str, None] = {}   # early-release tombstones
        self._ref_contains: dict[str, list] = {}   # outer oid -> inner oids
        self._ref_contained: dict[str, int] = {}   # inner oid -> edge count
        self._ref_released: dict[str, None] = {}   # freed oids (tombstones)
        self._pending_release: dict[str, set] = {} # node -> oids to free
        self._deferred_contains: list = []         # (due, [inner oids])
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True)
        self._task_events: list[dict] = []           # bounded task event sink
        self._pending_demand: dict[str, list] = {}   # node -> unmet demands
        self._max_task_events = 10000
        # --- persistence (GCS fault tolerance) ---
        self._persist = (GcsPersistence(persistence_dir)
                         if persistence_dir else None)
        self._dirty = False
        if self._persist is not None:
            self._restore()

    # ------------------------------------------------------------------
    # persistence (reference: StoreClient-backed tables + GcsInitData
    # restart reload; critical mutations WAL'd, full state snapshotted)
    # ------------------------------------------------------------------

    def _log(self, kind: str, key, payload):
        """WAL one mutation (entity upsert/delete, last-writer-wins on
        replay). No-op without persistence."""
        persist = self._persist   # may be nulled by a chaos kill
        if persist is None:
            return
        try:
            persist.append((kind, key, payload))
        except (OSError, ValueError):
            pass
        self._dirty = True

    def _state_dict(self) -> dict:
        from dataclasses import asdict

        with self._lock:
            return {
                "actors": {k: asdict(a) for k, a in self._actors.items()},
                "named_actors": dict(self._named_actors),
                "kv": {ns: dict(kv) for ns, kv in self._kv.items()},
                "pgs": {k: asdict(p) for k, p in self._pgs.items()},
                "jobs": {k: dict(j) for k, j in self._jobs.items()},
                "object_dir": {o: sorted(ls)
                               for o, ls in self._object_dir.items()},
                "object_meta": dict(self._object_meta),
                "lost_objects": list(self._lost_objects),
                # refcount state rides the snapshot (not the WAL — the
                # mutation rate is too high); a crash loses at most one
                # snapshot period of deltas
                "ref": {
                    "clients": {cid: c["kind"]
                                for cid, c in self._clients.items()
                                if c["alive"]},
                    "holders": {o: sorted(hs)
                                for o, hs in self._ref_holders.items()},
                    "pins": {t: (c, list(os_))
                             for t, (c, os_) in self._ref_pins.items()},
                    "contains": {o: list(i)
                                 for o, i in self._ref_contains.items()},
                    "released": list(self._ref_released),
                    "pending_release": {n: sorted(s) for n, s in
                                        self._pending_release.items()},
                },
            }

    def _apply_record(self, kind: str, key, payload):
        if kind == "actor":
            if payload is None:
                self._actors.pop(key, None)
            else:
                self._actors[key] = ActorInfo(**payload)
        elif kind == "actors":
            # one record per registration/ready BATCH (the batched plane
            # appends one WAL record for N actors, not N records)
            for actor in payload.get("actors", ()):
                self._actors[actor["actor_id"]] = ActorInfo(**actor)
            for nkey, aid in payload.get("named", {}).items():
                self._named_actors[nkey] = aid
        elif kind == "named":
            if payload is None:
                self._named_actors.pop(key, None)
            else:
                self._named_actors[key] = payload
        elif kind == "kv":
            ns, k = key
            if payload is None:
                self._kv.get(ns, {}).pop(k, None)
            else:
                self._kv.setdefault(ns, {})[k] = payload
        elif kind == "pg":
            if payload is None:
                self._pgs.pop(key, None)
            else:
                self._pgs[key] = PlacementGroupInfo(**payload)
        elif kind == "job":
            self._jobs[key] = payload

    def _restore(self):
        """Reload snapshot + WAL; nodes are NOT restored — live raylets
        re-register within one heartbeat (their reconnecting clients get
        ``reregister`` on the first post-restart heartbeat), and their
        location reconciliation re-populates dead entries' truth."""
        state, records = self._persist.load()
        if state:
            self._actors = {k: ActorInfo(**v)
                            for k, v in state["actors"].items()}
            self._named_actors = dict(state["named_actors"])
            self._kv = {ns: dict(kv) for ns, kv in state["kv"].items()}
            self._pgs = {k: PlacementGroupInfo(**v)
                         for k, v in state["pgs"].items()}
            self._jobs = dict(state["jobs"])
            self._object_dir = {o: set(ls)
                                for o, ls in state["object_dir"].items()}
            self._object_meta = dict(state["object_meta"])
            self._lost_objects = dict.fromkeys(state["lost_objects"])
            ref = state.get("ref")
            if ref:
                # client last_seen is process-local monotonic time:
                # reset to "now" so live clients get a full timeout
                # window to resume heartbeating after the restart
                now = time.monotonic()
                self._clients = {cid: {"kind": k, "last_seen": now,
                                       "alive": True}
                                 for cid, k in ref["clients"].items()}
                self._ref_holders = {o: set(hs)
                                     for o, hs in ref["holders"].items()}
                self._ref_pins = {t: (c, list(os_))
                                  for t, (c, os_) in ref["pins"].items()}
                self._ref_pin_count = {}
                for _, (_, os_) in self._ref_pins.items():
                    for o in os_:
                        self._ref_pin_count[o] = \
                            self._ref_pin_count.get(o, 0) + 1
                self._ref_contains = {o: list(i)
                                      for o, i in ref["contains"].items()}
                self._ref_contained = {}
                for inners in self._ref_contains.values():
                    for o in inners:
                        self._ref_contained[o] = \
                            self._ref_contained.get(o, 0) + 1
                self._ref_released = dict.fromkeys(ref["released"])
                self._pending_release = {n: set(s) for n, s in
                                         ref["pending_release"].items()}
        for kind, key, payload in records:
            try:
                self._apply_record(kind, key, payload)
            except Exception:  # noqa: BLE001 - skip torn/stale records
                pass

    def _snapshot_loop(self):
        while not self._stopping:
            time.sleep(2.0)
            persist = self._persist   # may be nulled by a chaos kill
            if self._dirty and persist is not None:
                self._dirty = False
                try:
                    # capture + WAL rotation under the GCS lock (cheap —
                    # no record can land between them and be discarded);
                    # the snapshot's DISK write runs outside the lock so
                    # control-plane RPCs never stall behind file IO
                    with self._lock:
                        state = self._state_dict()
                        persist.rotate_wal()
                    persist.commit_snapshot(state)
                except OSError:
                    self._dirty = True

    def _log_actor(self, actor: "ActorInfo"):
        from dataclasses import asdict

        self._log("actor", actor.actor_id, asdict(actor))

    def _log_actors(self, actors: list, named: dict | None = None):
        """One WAL record per BATCH of actor upserts (the batched
        registration/ready paths must not pay one append+flush per
        actor)."""
        from dataclasses import asdict

        if not actors and not named:
            return
        if len(actors) == 1 and not named:
            self._log_actor(actors[0])
            return
        self._log("actors", None, {
            "actors": [asdict(a) for a in actors],
            "named": dict(named or {})})

    def _restore_reconcile(self):
        """Post-restart reconciliation (reference: GcsInitData load then
        reconcile against re-registering raylets): give live raylets one
        re-registration window, then (a) reschedule actors stuck in
        PENDING/RESTARTING (their placement RPC died with the old
        process) and (b) run the failure path for ALIVE actors whose
        node never came back."""
        deadline = time.monotonic() + max(self._hb_timeout, 2.0)
        while time.monotonic() < deadline and not self._stopping:
            with self._lock:
                if self._nodes:
                    break
            time.sleep(0.1)
        time.sleep(0.5)   # let the rest of the fleet re-register too
        if self._stopping:
            return
        with self._lock:
            stuck = [a.actor_id for a in self._actors.values()
                     if a.state in ("PENDING", "RESTARTING")]
            orphaned = [a for a in self._actors.values()
                        if a.state == "ALIVE" and (
                            a.node_id not in self._nodes
                            or not self._nodes[a.node_id].alive)]
        for actor_id in stuck:
            self._schedule_actor(actor_id)
        for actor in orphaned:
            self._on_actor_failure(
                actor, "node lost while the control plane was down")

    def start(self):
        super().start()
        self._health_thread.start()
        threading.Thread(target=self._pub_flush_loop, daemon=True,
                         name="gcs-pub-flusher").start()
        from ray_tpu.util import metrics as _metrics
        if _metrics.enabled():
            threading.Thread(target=self._metrics_self_loop, daemon=True,
                             name="gcs-metrics-self").start()
        if self._persist is not None:
            threading.Thread(target=self._snapshot_loop,
                             daemon=True).start()
            with self._lock:
                needs_reconcile = bool(self._actors)
            if needs_reconcile:
                threading.Thread(target=self._restore_reconcile,
                                 daemon=True).start()
        return self

    def stop(self):
        super().stop()
        self._metrics_stop.set()
        # release the process-wide pusher claim the self-loop may hold:
        # a later runtime in this process (test clusters churn them)
        # must be able to claim, or its annex/metric frames never ship
        from ray_tpu.runtime import metrics_plane as _mp
        _mp.release_pusher(f"gcs:{self.address[1]}")
        with self._place_cv:
            self._place_cv.notify_all()   # placement workers exit
        with self._pub_cv:
            self._pub_cv.notify_all()     # pub flusher exits
        with self._placement_lock:
            clients, self._placement_clients = \
                dict(self._placement_clients), {}
        for client in clients.values():
            try:
                client.close()
            except OSError:
                pass
        if self._persist is not None:
            try:
                self._persist.snapshot(self._state_dict())
            except OSError:
                pass
            self._persist.close()

    # ------------------------------------------------------------------
    # pubsub (reference: src/ray/pubsub/ publisher.h)
    # ------------------------------------------------------------------

    def rpc_subscribe(self, conn, send_lock, *, channels: list):
        with self._lock:
            for ch in channels:
                subs = self._subs.setdefault(ch, [])
                # dedupe per (conn, channel): a re-subscribe after a
                # redial races the old entry's cleanup on the SAME held
                # conn — appending unconditionally double-delivered
                # every message to that subscriber
                if not any(c is conn for c, _ in subs):
                    subs.append((conn, send_lock))
        send_msg(conn, {"subscribed": channels}, send_lock)
        return RpcServer.HELD

    def rpc_push_logs(self, conn, send_lock, *, node_id: str,
                      entries: list):
        """Raylet log monitors ship captured line batches here. Ingest
        is idempotent per (proc, file@epoch, offset) watermark — a
        chaos-duplicated frame (or a monitor retry after a lost ack)
        neither double-stores nor double-echoes; only the ACCEPTED lines
        fan out to CH_LOGS subscribers (drivers echoing worker output —
        reference: log_monitor.py -> GCS pubsub -> driver stdout)."""
        self._ingest_logs(node_id, entries)
        return {"ok": True}

    def _ingest_logs(self, node_id: str, entries: list):
        accepted = self._log_store.ingest(node_id, entries or [])
        for entry in accepted:
            self.publish(CH_LOGS, {"node_id": node_id, "entry": entry})
        return accepted

    def publish(self, channel: str, message: dict):
        message = {"channel": channel, **message}
        with self._lock:
            subs = list(self._subs.get(channel, []))
        if not subs:
            return
        if channel in (CH_ACTOR, CH_METRICS, CH_LOGS) and \
                self._pub_flush_s > 0:
            # coalesce: buffer per (subscriber, channel), flusher ships
            # one framed batch per window — the publisher (often
            # rpc_actor_ready under the creation flood, or a metrics
            # window roll) never blocks on N sockets
            with self._pub_cv:
                for conn, send_lock in subs:
                    ent = self._pub_buf.get((id(conn), channel))
                    if ent is None:
                        self._pub_buf[(id(conn), channel)] = (
                            conn, send_lock, channel, [message])
                    else:
                        ent[3].append(message)
                self._pub_cv.notify_all()
            return
        self._send_to_subs([(conn, lk, message) for conn, lk in subs])

    def _pub_flush_loop(self):
        while not self._stopping:
            with self._pub_cv:
                while not self._pub_buf and not self._stopping:
                    self._pub_cv.wait(0.5)
                if self._stopping:
                    return
            time.sleep(self._pub_flush_s)   # coalesce the burst
            with self._pub_cv:
                buf, self._pub_buf = self._pub_buf, {}
            sends = []
            for conn, send_lock, channel, msgs in buf.values():
                if len(msgs) == 1:
                    sends.append((conn, send_lock, msgs[0]))
                else:
                    sends.append((conn, send_lock,
                                  {"channel": channel, "batch": msgs}))
            self._send_to_subs(sends)

    def _send_to_subs(self, sends: list):
        """Deliver one message per (conn, send_lock, message) triple;
        dead conns are stripped from every channel and released."""
        dead = []
        for conn, send_lock, message in sends:
            try:
                send_msg(conn, message, send_lock)
            except OSError:
                dead.append((conn, send_lock))
        if dead:
            with self._lock:
                # strip dead conns from EVERY channel (multi-channel
                # subscribers leave stale entries otherwise)
                for subs in self._subs.values():
                    for item in dead:
                        try:
                            subs.remove(item)
                        except ValueError:
                            pass
            for conn, _ in dead:
                self.release_conn(conn)   # held channel finished

    # ------------------------------------------------------------------
    # nodes + health (reference: GcsNodeManager / GcsHealthCheckManager)
    # ------------------------------------------------------------------

    def rpc_register_node(self, conn, send_lock, *, node_id, address,
                          store_name, resources, labels=None):
        with self._lock:
            self._nodes[node_id] = NodeInfo(
                node_id=node_id, address=tuple(address),
                store_name=store_name, resources=dict(resources),
                available=dict(resources), labels=labels or {},
            )
        self.publish(CH_NODE, {"event": "added", "node_id": node_id,
                               "address": tuple(address)})
        return {"ok": True}

    def rpc_register_agent(self, conn, send_lock, *, node_id, address):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return {"ok": False}
            node.agent_addr = tuple(address)
        return {"ok": True}

    def rpc_resource_update(self, conn, send_lock, *, node_id, version,
                            available, load=0):
        """Versioned RESOURCE_VIEW push (reference: ray_syncer.cc:325
        BroadcastRaySyncMessage): applied only when newer than the
        stored version, so a slow push can never roll back a fresher
        view. This — not the heartbeat — is how the scheduling view
        tracks node state, at RPC latency. ``load`` = ready-queue depth
        (placement prefers shallow queues when every node is busy)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return {"ok": False, "reregister": True}
            if version > node.resource_version:
                node.resource_version = version
                node.available = dict(available)
                node.load = int(load)
        return {"ok": True}

    def rpc_heartbeat(self, conn, send_lock, *, node_id, available=None,
                      load=None, host_stats=None, freed_acks=None,
                      resource_version=None):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return {"ok": False, "reregister": True}
            node.last_heartbeat = time.monotonic()
            # liveness beat carries only the VERSION (payload O(1));
            # `available` still accepted for legacy/snapshot callers.
            # A version mismatch means the event-driven push stream and
            # this view diverged (lost push, GCS restart): ask for one
            # full resync push.
            need_resources = False
            if available is not None:
                node.available = dict(available)
                if resource_version is not None:
                    node.resource_version = resource_version
            elif resource_version is not None and \
                    node.resource_version < resource_version:
                # the raylet DELIVERED a version we never applied (GCS
                # restart / lost state): ask for a full resync. The
                # one-sided check matters: applied-ahead-of-acked is the
                # normal in-flight-ack case, not a loss.
                need_resources = True
            if host_stats:
                node.host_stats = dict(host_stats)
            # refcount release delivery is piggybacked on the heartbeat:
            # at-least-once (re-sent until the node acks on its next
            # beat; release is idempotent on the raylet side)
            if freed_acks:
                pend = self._pending_release.get(node_id)
                if pend is not None:
                    pend.difference_update(freed_acks)
                    if not pend:
                        del self._pending_release[node_id]
            pend = self._pending_release.get(node_id)
            release = sorted(pend)[:5000] if pend else None
        reply = {"ok": True}
        if release:
            reply["release_oids"] = release
        if need_resources:
            reply["need_resources"] = True
        return reply

    def rpc_get_nodes(self, conn, send_lock, *, alive_only: bool = True):
        with self._lock:
            return [
                {"node_id": n.node_id, "address": n.address,
                 "store_name": n.store_name, "resources": n.resources,
                 "available": n.available, "alive": n.alive,
                 "labels": n.labels, "host_stats": n.host_stats,
                 "agent_addr": n.agent_addr}
                for n in self._nodes.values()
                if n.alive or not alive_only
            ]

    def rpc_drain_node(self, conn, send_lock, *, node_id):
        self._mark_node_dead(node_id, reason="drained")
        return {"ok": True}

    def _health_loop(self):
        while not self._stopping:
            time.sleep(self._hb_timeout / 4)
            now = time.monotonic()
            with self._lock:
                dead = [n.node_id for n in self._nodes.values()
                        if n.alive and now - n.last_heartbeat > self._hb_timeout]
            for node_id in dead:
                self._mark_node_dead(node_id, reason="heartbeat timeout")
            try:
                self._process_deferred_contains()
                self._reap_stale_clients()
            except Exception:  # noqa: BLE001 - next tick retries
                pass

    def _mark_node_dead(self, node_id: str, reason: str):
        with self._lock:
            # a dead node's parked demand must not drive the autoscaler
            # forever
            self._pending_demand.pop(node_id, None)
            self._pending_release.pop(node_id, None)
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return
            node.alive = False
            # drop object locations on that node; tombstone objects whose
            # last copy just vanished so owners can trigger reconstruction
            for oid, locs in list(self._object_dir.items()):
                locs.discard(node_id)
                if not locs:
                    del self._object_dir[oid]
                    self._tombstone(oid, f"node_dead:{node_id[:8]}")
            doomed_actors = [a for a in self._actors.values()
                            if a.node_id == node_id
                            and a.state in ("ALIVE", "PENDING", "RESTARTING")]
        # retire the dead node's cached placement channel — raylet
        # restarts land on fresh ports, so entries left behind would
        # accumulate one dead client per retired address forever
        addr = tuple(node.address) if node.address else None
        if addr is not None:
            with self._placement_lock:
                stale = self._placement_clients.pop(addr, None)
            if stale is not None:
                try:
                    stale.close()
                except OSError:
                    pass
        self.publish(CH_NODE, {"event": "dead", "node_id": node_id,
                               "reason": reason})
        for actor in doomed_actors:
            self._on_actor_failure(actor, f"node {node_id} died: {reason}")

    # ------------------------------------------------------------------
    # actors (reference: GcsActorManager + GcsActorScheduler)
    # ------------------------------------------------------------------

    def _register_one_locked(self, *, actor_id, name, creation_spec,
                             resources, max_restarts, pg_id=None,
                             namespace=None, owner_id=None,
                             lifetime=None):
        """Per-actor registration core (caller holds self._lock; caller
        logs). Returns (result_dict, created: ActorInfo | None,
        named_key: str | None)."""
        namespace = namespace or "default"
        # owner-scoped lifetime (reference: actor.py:524 + gcs_actor_
        # manager.cc:632): default actors die with their owner client;
        # lifetime="detached" (or an ownerless registration) opts out
        detached = (lifetime == "detached") or owner_id is None
        # idempotent by actor_id: a retried registration (the reply
        # was lost to a partition, or the delivery was duplicated)
        # acks the registration that already exists instead of
        # rejecting its own name as taken
        existing = self._actors.get(actor_id)
        if existing is not None and existing.state != "DEAD":
            return ({"ok": True, "node_id": existing.node_id},
                    None, None)
        named_key = None
        if name is not None:
            key = _ns_key(namespace, name)
            if self._named_actors.get(key, actor_id) != actor_id:
                return ({"ok": False,
                         "error": f"Actor name {name!r} already taken "
                                  f"in namespace {namespace!r}"},
                        None, None)
            self._named_actors[key] = actor_id
            named_key = key
        actor = ActorInfo(
            actor_id=actor_id, name=name, namespace=namespace,
            state="PENDING",
            creation_spec=creation_spec, resources=dict(resources),
            max_restarts=max_restarts, pg_id=pg_id,
            owner_id=owner_id, detached=detached,
        )
        self._actors[actor_id] = actor
        self._plane_t[actor_id] = [time.monotonic(), 0.0]
        return ({"ok": True}, actor, named_key)

    def rpc_register_actor(self, conn, send_lock, *, actor_id, name,
                           creation_spec, resources, max_restarts,
                           pg_id=None, namespace=None, owner_id=None,
                           lifetime=None):
        with self._lock:
            result, created, named_key = self._register_one_locked(
                actor_id=actor_id, name=name,
                creation_spec=creation_spec, resources=resources,
                max_restarts=max_restarts, pg_id=pg_id,
                namespace=namespace, owner_id=owner_id,
                lifetime=lifetime)
            if created is not None:
                self._log_actor(created)
            if named_key is not None:
                self._log("named", named_key, actor_id)
        if created is not None or named_key is not None:
            _fi.maybe_crash("gcs.after_wal_append")
        if not result["ok"]:
            raise ValueError(result["error"])
        if created is None:
            return result
        node_id = self._schedule_actor(actor_id)
        return {"ok": True, "node_id": node_id}

    def rpc_register_actors(self, conn, send_lock, *, actors: list):
        """Batched registration (the driver-side coalescer's frame): ONE
        lock hold and ONE WAL record for the whole batch, per-actor
        idempotency/name-conflict results so one bad entry cannot fail
        its neighbors, then batch scheduling."""
        results = []
        to_schedule = []
        with self._lock:
            created_infos, named = [], {}
            for ent in actors:
                result, created, named_key = \
                    self._register_one_locked(**ent)
                results.append(result)
                if created is not None:
                    created_infos.append(created)
                    to_schedule.append(created.actor_id)
                if named_key is not None:
                    named[named_key] = ent["actor_id"]
            self._log_actors(created_infos, named)
            self._plane["register_batches"] += 1
            self._plane["register_actors"] += len(actors)
            self._plane["register_batch_max"] = max(
                self._plane["register_batch_max"], len(actors))
        # crash point: WAL record durable, client reply NOT sent — the
        # retried batch after restart must be absorbed by per-actor-id
        # idempotency, not double-registered (tests/test_gcs_ft.py)
        _fi.maybe_crash("gcs.after_wal_append")
        node_ids = self._schedule_actors(to_schedule)
        for result, ent in zip(results, actors):
            if result["ok"] and "node_id" not in result:
                result["node_id"] = node_ids.get(ent["actor_id"])
        return {"results": results}

    def _schedule_actor(self, actor_id: str) -> str | None:
        return self._schedule_actors([actor_id]).get(actor_id)

    def _schedule_actors(self, actor_ids: list) -> dict:
        """Pick nodes for a batch of actors under ONE lock hold, group
        host requests per target raylet, and hand the batches to the
        bounded placement executor (reference: GcsActorScheduler::
        Schedule, ScheduleByGcs — no thread-per-actor)."""
        if not actor_ids:
            return {}
        results: dict[str, str | None] = {}
        assigned: dict[tuple, list] = {}   # raylet addr -> [(id, spec, inc)]
        unschedulable: list[str] = []
        with self._lock:
            occupancy: dict[str, int] = {}
            for a in self._actors.values():
                if a.node_id and a.state in ("PENDING", "ALIVE",
                                             "RESTARTING"):
                    occupancy[a.node_id] = occupancy.get(a.node_id, 0) + 1
            dirty = []
            for actor_id in actor_ids:
                actor = self._actors.get(actor_id)
                if actor is None or actor.state == "DEAD":
                    results[actor_id] = None
                    continue
                pg = self._pgs.get(actor.pg_id) if actor.pg_id else None
                node_id = self._pick_node(actor.resources, pg=pg,
                                          occupancy=occupancy)
                if node_id is None:
                    actor.state = "DEAD"
                    actor.death_reason = (
                        f"no node can host actor resources "
                        f"{actor.resources}")
                    self._plane_t.pop(actor_id, None)
                    unschedulable.append(actor_id)
                    results[actor_id] = None
                else:
                    actor.node_id = node_id
                    occupancy[node_id] = occupancy.get(node_id, 0) + 1
                    addr = tuple(self._nodes[node_id].address)
                    assigned.setdefault(addr, []).append(
                        (actor_id, actor.creation_spec,
                         actor.num_restarts))
                    results[actor_id] = node_id
                dirty.append(actor)
            self._log_actors(dirty)
        for actor_id in unschedulable:
            self.publish(CH_ACTOR, {"event": "dead", "actor_id": actor_id,
                                    "reason": "unschedulable"})
        if assigned:
            with self._place_cv:
                for addr, batch in assigned.items():
                    for i in range(0, len(batch), self._place_batch_cap):
                        self._place_queue.append(
                            (addr, batch[i:i + self._place_batch_cap]))
                self._ensure_placement_workers_locked()
                self._place_cv.notify_all()
        return results

    def _ensure_placement_workers_locked(self):
        """Lazily grow the placement pool up to its cap (caller holds
        _place_cv). The pool is the ONLY source of host_actors RPCs —
        bounded by flag, asserted by test."""
        self._place_threads = [t for t in self._place_threads
                               if t.is_alive()]
        want = min(self._place_pool_size, len(self._place_queue))
        while len(self._place_threads) < want:
            t = threading.Thread(
                target=self._placement_worker, daemon=True,
                name=f"gcs-place-{len(self._place_threads)}")
            self._place_threads.append(t)
            t.start()

    def _placement_worker(self):
        while True:
            with self._place_cv:
                while not self._place_queue and not self._stopping:
                    self._place_cv.wait(0.5)
                if self._stopping:
                    return
                addr, batch = self._place_queue.popleft()
            try:
                self._place_batch(addr, batch)
            except Exception:  # noqa: BLE001 - worker must survive
                pass

    def _place_batch(self, addr: tuple, batch: list):
        """Ship one host_actors frame to one raylet over the cached
        placement channel; per-actor results feed the failure path. The
        client is CACHED per raylet address — a 2k-actor flood through
        fresh sockets (connect + reader thread each) made placement the
        GCS bottleneck at the envelope tier."""
        from ray_tpu.runtime.rpc import ConnectionLost
        wire = [{"actor_id": a, "spec": s, "incarnation": i}
                for a, s, i in batch]
        last_err: Exception | None = None
        reply = None
        for _attempt in (0, 1):
            client = None
            try:
                client = self._placement_client(addr)
                reply = client.call("host_actors", actors=wire)
                break
            except (OSError, ConnectionLost) as e:
                # transport death only: an APPLICATION error must not
                # close the SHARED channel under other in-flight
                # placements pipelined on it. One RST drains EVERY call
                # pipelined on the cached channel with ConnectionLost —
                # retry once on a fresh dial so a transient break
                # doesn't permanently kill all concurrent placements
                # (safe: host_actor dedups on (actor_id, incarnation)
                # raylet-side).
                last_err = e
                if client is not None:
                    # evict only OUR dead client: a concurrent retry
                    # may already have installed a healthy fresh
                    # channel at this address — popping that would
                    # kill its pipelined in-flight placements
                    with self._placement_lock:
                        if self._placement_clients.get(addr) is client:
                            self._placement_clients.pop(addr, None)
                    try:
                        client.close()
                    except OSError:
                        pass
            except Exception as e:  # noqa: BLE001
                last_err = e
                break
        if reply is None:
            for actor_id, _spec, _inc in batch:
                self._on_actor_failure_id(
                    actor_id, f"placement failed: {last_err!r}")
            return
        now = time.monotonic()
        with self._lock:
            self._plane["host_batches"] += 1
            self._plane["host_actors"] += len(batch)
            self._plane["host_batch_max"] = max(
                self._plane["host_batch_max"], len(batch))
            for actor_id, _spec, _inc in batch:
                t = self._plane_t.get(actor_id)
                if t is not None:
                    self._plane["place_s"] += now - t[0]
                    self._plane["placed"] += 1
                    self._plane_hist.observe(now - t[0],
                                             tags={"stage": "place"})
                    t[1] = now
        failed = []
        for (actor_id, _spec, _inc), res in zip(batch,
                                                reply.get("results", ())):
            if not res.get("ok"):
                failed.append((actor_id,
                               res.get("error", "host_actor failed")))
        for actor_id, err in failed:
            self._on_actor_failure_id(actor_id,
                                      f"placement failed: {err}")

    def _placement_client(self, addr: tuple):
        from ray_tpu.runtime.rpc import RpcClient
        with self._placement_lock:
            client = self._placement_clients.get(addr)
            if client is not None and not client._closed:
                return client
        fresh = RpcClient(addr, label="gcs")
        with self._placement_lock:
            current = self._placement_clients.get(addr)
            if current is not None and not current._closed:
                fresh.close()
                return current
            self._placement_clients[addr] = fresh
        return fresh

    def rpc_actor_ready(self, conn, send_lock, *, actor_id, node_id,
                        push_addr=None):
        reply = self.rpc_actors_ready(
            conn, send_lock, node_id=node_id,
            actors=[{"actor_id": actor_id, "push_addr": push_addr}])
        return reply["results"][0]

    def rpc_actors_ready(self, conn, send_lock, *, node_id, actors: list):
        """Batched ready acks from one raylet: one lock hold + one WAL
        record per batch; the alive events carry the full location
        (address/push_addr/incarnation) so a pubsub-driven driver never
        needs a get_actor round trip to resolve."""
        results = []
        events = []
        now = time.monotonic()
        with self._lock:
            node = self._nodes.get(node_id)
            node_addr = tuple(node.address) if node else None
            dirty = []
            for ent in actors:
                actor_id = ent["actor_id"]
                push_addr = ent.get("push_addr")
                actor = self._actors.get(actor_id)
                if actor is None:
                    results.append({"ok": False})
                    continue
                actor.state = "ALIVE"
                actor.node_id = node_id
                actor.push_addr = tuple(push_addr) if push_addr else None
                dirty.append(actor)
                results.append({"ok": True})
                t = self._plane_t.pop(actor_id, None)
                if t is not None:
                    self._plane["ready_s"] += now - (t[1] or t[0])
                    self._plane["ready"] += 1
                    self._plane_hist.observe(now - (t[1] or t[0]),
                                             tags={"stage": "ready"})
                events.append({"event": "alive", "actor_id": actor_id,
                               "node_id": node_id, "address": node_addr,
                               "push_addr": actor.push_addr,
                               "num_restarts": actor.num_restarts})
            self._log_actors(dirty)
            self._plane["ready_batches"] += 1
            self._plane["ready_actors"] += len(actors)
        for ev in events:
            self.publish(CH_ACTOR, ev)
        return {"results": results}

    def rpc_actor_plane_stats(self, conn, send_lock, *, reset=False):
        """Creation-plane counters + phase decomposition (cumulative
        seconds and counts for register->place and place->ready; the
        envelope probe divides for per-phase means)."""
        with self._lock:
            stats = dict(self._plane)
            stats["in_flight"] = len(self._plane_t)
            if reset:
                for k in self._plane:
                    self._plane[k] = 0.0 if isinstance(
                        self._plane[k], float) else 0
            return stats

    def rpc_actor_failed(self, conn, send_lock, *, actor_id, reason):
        with self._lock:
            actor = self._actors.get(actor_id)
        if actor is not None:
            self._on_actor_failure(actor, reason)
        return {"ok": True}

    def _on_actor_failure_id(self, actor_id: str, reason: str):
        with self._lock:
            actor = self._actors.get(actor_id)
        if actor is not None:
            self._on_actor_failure(actor, reason)

    def _on_actor_failure(self, actor: ActorInfo, reason: str):
        """Restart (reference: GcsActorManager::ReconstructActor,
        gcs_actor_manager.cc:1100, max_restarts budget :1117) or kill."""
        with self._lock:
            if actor.state == "DEAD":
                return
            if actor.num_restarts < actor.max_restarts:
                actor.num_restarts += 1
                actor.state = "RESTARTING"
                actor.node_id = None
                actor.push_addr = None
                restarting = True
            else:
                actor.state = "DEAD"
                actor.death_reason = reason
                self._plane_t.pop(actor.actor_id, None)
                if actor.name:
                    key = _ns_key(actor.namespace, actor.name)
                    self._named_actors.pop(key, None)
                    self._log("named", key, None)
                restarting = False
            self._log_actor(actor)
        if restarting:
            self.publish(CH_ACTOR, {"event": "restarting",
                                    "actor_id": actor.actor_id,
                                    "reason": reason})
            self._schedule_actor(actor.actor_id)
        else:
            self.publish(CH_ACTOR, {"event": "dead",
                                    "actor_id": actor.actor_id,
                                    "reason": reason})

    def rpc_get_actor(self, conn, send_lock, *, actor_id=None, name=None,
                      namespace=None):
        with self._lock:
            if actor_id is None:
                actor_id = self._named_actors.get(
                    _ns_key(namespace or "default", name))
                if actor_id is None:
                    return None
            actor = self._actors.get(actor_id)
            if actor is None:
                return None
            node = self._nodes.get(actor.node_id) if actor.node_id else None
            return {
                "actor_id": actor.actor_id, "name": actor.name,
                "state": actor.state, "node_id": actor.node_id,
                "address": node.address if node else None,
                "push_addr": actor.push_addr,
                "death_reason": actor.death_reason,
                "num_restarts": actor.num_restarts,
            }

    def rpc_kill_actor(self, conn, send_lock, *, actor_id, no_restart=True):
        from ray_tpu.runtime.rpc import RpcClient
        with self._lock:
            if actor_id not in self._actors:
                return {"ok": False}
        if no_restart:
            self._kill_actor(actor_id, "killed via ray_tpu.kill()")
            return {"ok": True}
        with self._lock:
            actor = self._actors.get(actor_id)
            node = self._nodes.get(actor.node_id) if actor.node_id else None
        if node is not None:
            try:
                client = RpcClient(node.address)
                client.call("kill_actor_worker", actor_id=actor_id)
                client.close()
            except Exception:  # noqa: BLE001 - node may be gone already
                pass
        self._on_actor_failure_id(actor_id, "killed via ray_tpu.kill()")
        return {"ok": True}

    def rpc_list_actors(self, conn, send_lock):
        with self._lock:
            return [
                {"actor_id": a.actor_id, "name": a.name, "state": a.state,
                 "node_id": a.node_id, "num_restarts": a.num_restarts}
                for a in self._actors.values()
            ]

    # ------------------------------------------------------------------
    # scheduling helpers (reference: HybridSchedulingPolicy — filter
    # feasible, prefer available, score by critical resource utilization)
    # ------------------------------------------------------------------

    def _pick_node(self, demand: dict, pg: PlacementGroupInfo | None = None,
                   exclude: set | None = None,
                   occupancy: dict | None = None) -> str | None:
        # zero-valued entries (num_cpus=0 actors arrive as {"CPU": 0.0})
        # are not demand: they must take the occupancy-spread path below,
        # not ride the resource-driven policy to node[0] forever
        demand = {k: v for k, v in demand.items() if v > 0}
        if pg is not None and pg.bundle_nodes:
            for nid in pg.bundle_nodes:
                n = self._nodes.get(nid)
                if n and n.alive and _fits(demand, n.available):
                    return nid
            for nid in pg.bundle_nodes:
                n = self._nodes.get(nid)
                if n and n.alive and _fits(demand, n.resources):
                    return nid
            return None
        # native hybrid policy (C++ fixed-point scoring —
        # src/scheduler/scheduling.cc) when built; Python fallback below
        # keeps source checkouts working without `make -C src`
        from ray_tpu._private import scheduling as _sched

        if demand and _sched.available():
            # resource-driven picks: the native hybrid policy. Empty
            # demands fall through to the Python score — they tie on
            # utilization, and only the Python path knows queue depth
            # and actor occupancy (the actual spread signals).
            nodes = list(self._nodes.values())
            return _sched.pick_node(
                [n.node_id for n in nodes],
                [n.resources for n in nodes],
                [n.available for n in nodes],
                [n.alive for n in nodes],
                exclude or set(), demand,
                spread_threshold=0.0, top_k=1)
        if occupancy is None:
            occupancy = {}
            if not demand:
                # zero-resource demands tie on utilization everywhere, so
                # live-actor occupancy is the spread signal (reference:
                # GcsActorScheduler spreads; without it an envelope flood
                # stacks all 2,000 actors on node[0]). Recomputed per pick
                # — drift-free vs incremental counts across the many death
                # paths, and only empty-demand picks pay the O(actors)
                # scan. Batch scheduling passes a precomputed dict it
                # maintains incrementally (one scan per BATCH, not per
                # actor — per-pick rescans are O(n^2) at the 40k tier).
                for a in self._actors.values():
                    if a.node_id and a.state in ("PENDING", "ALIVE",
                                                 "RESTARTING"):
                        occupancy[a.node_id] = \
                            occupancy.get(a.node_id, 0) + 1
        best, best_score = None, None
        feasible_busy, busy_load = None, None
        for n in self._nodes.values():
            if not n.alive or (exclude and n.node_id in exclude):
                continue
            if not _fits(demand, n.resources):
                continue
            if _fits(demand, n.available):
                # queue depth folds into the score: a node whose
                # `available` looks healthy because per-task
                # acquire/release averages out may still hold a deep
                # ready queue — placement must prefer shallow queues
                score = (_critical_utilization(demand, n)
                         + min(n.load, 1000) * 0.001
                         + min(occupancy.get(n.node_id, 0), 100_000)
                         * 1e-6)
                if best_score is None or score < best_score:
                    best, best_score = n.node_id, score
            elif busy_load is None or n.load < busy_load:
                feasible_busy, busy_load = n.node_id, n.load
        return best if best is not None else feasible_busy

    def rpc_pick_node(self, conn, send_lock, *, demand, exclude=None,
                      pg_id=None):
        with self._lock:
            pg = self._pgs.get(pg_id) if pg_id else None
            return self._pick_node(demand, pg=pg,
                                   exclude=set(exclude or ()))

    # ------------------------------------------------------------------
    # placement groups (reference: GcsPlacementGroupManager; bundle
    # placement is 2-phase prepare/commit — simplified to reserve-on-GCS
    # because the GCS resource view is authoritative here)
    # ------------------------------------------------------------------

    def rpc_create_placement_group(self, conn, send_lock, *, pg_id, bundles,
                                   strategy="PACK"):
        with self._lock:
            alive = [n for n in self._nodes.values() if n.alive]
            assignment = _place_bundles(bundles, strategy, alive)
            if assignment is None:
                self._pgs[pg_id] = PlacementGroupInfo(
                    pg_id=pg_id, strategy=strategy, bundles=bundles,
                    state="PENDING")
                from dataclasses import asdict as _asdict
                self._log("pg", pg_id, _asdict(self._pgs[pg_id]))
                return {"ok": False, "state": "PENDING"}
            # reserve: deduct from the GCS view AND the node totals so
            # regular tasks do not oversubscribe reserved capacity
            for bundle, nid in zip(bundles, assignment):
                node = self._nodes[nid]
                for k, v in bundle.items():
                    node.available[k] = node.available.get(k, 0.0) - v
            self._pgs[pg_id] = PlacementGroupInfo(
                pg_id=pg_id, strategy=strategy, bundles=bundles,
                state="CREATED", bundle_nodes=assignment)
            from dataclasses import asdict as _asdict
            self._log("pg", pg_id, _asdict(self._pgs[pg_id]))
        return {"ok": True, "state": "CREATED", "bundle_nodes": assignment}

    def rpc_get_placement_group(self, conn, send_lock, *, pg_id):
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None:
                return None
            return {"pg_id": pg.pg_id, "state": pg.state,
                    "strategy": pg.strategy, "bundles": pg.bundles,
                    "bundle_nodes": pg.bundle_nodes}

    def rpc_list_placement_groups(self, conn, send_lock):
        with self._lock:
            return [{"pg_id": pg.pg_id, "state": pg.state,
                     "strategy": pg.strategy,
                     "bundle_nodes": pg.bundle_nodes}
                    for pg in self._pgs.values()]

    def rpc_remove_placement_group(self, conn, send_lock, *, pg_id):
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
            if pg is not None:
                self._log("pg", pg_id, None)
            if pg is not None and pg.state == "CREATED":
                for bundle, nid in zip(pg.bundles, pg.bundle_nodes):
                    node = self._nodes.get(nid)
                    if node is not None:
                        for k, v in bundle.items():
                            node.available[k] = node.available.get(k, 0) + v
        return {"ok": True}

    # ------------------------------------------------------------------
    # object directory (centralized; reference is owner-based
    # OwnershipBasedObjectDirectory — see SURVEY §2a N7)
    # ------------------------------------------------------------------

    def rpc_add_object_location(self, conn, send_lock, *, oid, node_id,
                                size=0):
        with self._lock:
            if oid in self._ref_released:
                # free-on-arrival: every reference was dropped before the
                # object materialized (fire-and-forget task returns)
                self._pending_release.setdefault(node_id, set()).add(oid)
                return {"ok": True}
            self._object_dir.setdefault(oid, set()).add(node_id)
            self._lost_objects.pop(oid, None)  # re-created (reconstruction)
            if size:
                self._object_meta[oid] = size
        self.publish(CH_OBJECT, {"event": "added", "oid": oid,
                                 "node_id": node_id})
        return {"ok": True}

    def rpc_add_object_locations(self, conn, send_lock, *, node_id,
                                 entries):
        """Batched location registration (raylets buffer task-return
        locations and flush them together — one directory RPC per flush
        instead of per task; the hot-path win behind the reference's
        ownership-based directory being OFF the task critical path)."""
        live = []
        with self._lock:
            for oid, size in entries:
                if oid in self._ref_released:
                    self._pending_release.setdefault(node_id,
                                                     set()).add(oid)
                    continue
                self._object_dir.setdefault(oid, set()).add(node_id)
                self._lost_objects.pop(oid, None)
                if size:
                    self._object_meta[oid] = size
                live.append(oid)
        for oid in live:
            self.publish(CH_OBJECT, {"event": "added", "oid": oid,
                                     "node_id": node_id})
        return {"ok": True}

    def rpc_get_object_locations(self, conn, send_lock, *, oids):
        with self._lock:
            return {oid: sorted(self._object_dir.get(oid, ()))
                    for oid in oids}

    def _tombstone(self, oid: str, reason: str = "?"):
        """Record a lost object, dropping the oldest past the cap (caller
        holds the lock). The reason is diagnostic: which path removed
        the LAST copy matters when debugging scale runs."""
        self._lost_objects[oid] = reason
        while len(self._lost_objects) > self._max_lost_objects:
            self._lost_objects.pop(next(iter(self._lost_objects)))

    def rpc_get_lost_objects(self, conn, send_lock, *, oids):
        """Subset of ``oids`` that were known and whose every copy died
        with its node (lineage-reconstruction trigger)."""
        with self._lock:
            return [o for o in oids if o in self._lost_objects]

    def rpc_debug_counts(self, conn, send_lock):
        """Diagnostic sizes of the hot tables (scale-run hunts)."""
        with self._lock:
            return {"object_dir": len(self._object_dir),
                    "ref_holders": len(self._ref_holders),
                    "ref_released": len(self._ref_released),
                    "pending_release": sum(
                        len(v) for v in self._pending_release.values()),
                    "lost": len(self._lost_objects)}

    def rpc_get_lost_reasons(self, conn, send_lock, *, oids):
        """Diagnostic: tombstone reasons for lost oids."""
        with self._lock:
            return {o: self._lost_objects.get(o) for o in oids}

    def rpc_remove_object_location(self, conn, send_lock, *, oid, node_id):
        with self._lock:
            locs = self._object_dir.get(oid)
            if locs:
                locs.discard(node_id)
                if not locs:
                    # last copy gone (evicted secondary after the primary's
                    # node died, or explicit free): tombstone so owners can
                    # reconstruct from lineage
                    del self._object_dir[oid]
                    self._tombstone(oid, f"removed_by:{node_id[:8]}")
        return {"ok": True}

    # ------------------------------------------------------------------
    # distributed refcounting (reference: reference_count.h:61-115 — the
    # owner/borrower protocol, centralized: every client reports holder
    # transitions, task pins, and contains-edges; zero count => release)
    # ------------------------------------------------------------------

    @staticmethod
    def _trim(table: dict, cap: int):
        while len(table) > cap:
            table.pop(next(iter(table)))

    def _ref_count(self, oid: str) -> int:
        return (len(self._ref_holders.get(oid, ()))
                + self._ref_pin_count.get(oid, 0)
                + self._ref_contained.get(oid, 0))

    def _touch_client(self, client_id: str, kind: str | None = None) -> bool:
        """Refresh client liveness. Returns True when the client was
        previously reaped and is being resurrected — its holds were
        dropped, so the caller must tell it to re-sync its held set."""
        c = self._clients.get(client_id)
        if c is None:
            self._clients[client_id] = {"kind": kind or "unknown",
                                        "last_seen": time.monotonic(),
                                        "alive": True}
            return False
        c["last_seen"] = time.monotonic()
        if kind and c["kind"] == "unknown":
            c["kind"] = kind
        if not c["alive"]:
            # back from the dead (GC pause / partition outlived the
            # timeout): resurrect so its future holds are reclaimable,
            # and fence — it must re-register everything it still holds
            c["alive"] = True
            return True
        return False

    @staticmethod
    def _dec_counts(table: dict, oids, dec: set):
        """Decrement ``table[oid]`` for each oid, popping zeros into
        ``dec`` (the release-candidate set). Shared by the pin-release,
        owner-death, and contains-release paths."""
        for oid in oids:
            n = table.get(oid, 0) - 1
            if n <= 0:
                table.pop(oid, None)
                dec.add(oid)
            else:
                table[oid] = n

    def rpc_register_client(self, conn, send_lock, *, client_id,
                            kind="driver"):
        with self._lock:
            self._touch_client(client_id, kind)
        return {"ok": True}

    def rpc_unregister_client(self, conn, send_lock, *, client_id):
        """Clean client shutdown: drop its ref contributions now and
        reap its non-detached actors (reference: job/driver exit kills
        owned actors, gcs_actor_manager.cc:632)."""
        self._reap_client(client_id, "client disconnected")
        return {"ok": True}

    def rpc_ref_update(self, conn, send_lock, *, client_id, add=(),
                       remove=(), transient=(), pins=(), pin_releases=(),
                       contains=(), kind=None):
        """Batched per-client refcount deltas; doubles as the client
        liveness heartbeat. Adds/pins/contains are applied before
        removes so one batch carrying both orders correctly."""
        dec: set[str] = set()
        with self._lock:
            resync = self._touch_client(client_id, kind)
            for oid in add:
                self._ref_holders.setdefault(oid, set()).add(client_id)
            for task_id, oids in pins:
                if task_id in self._pin_released:
                    # the executor finished (and released) before the
                    # owner's pin landed: consume the tombstone
                    del self._pin_released[task_id]
                    continue
                if task_id in self._ref_pins:
                    continue
                self._ref_pins[task_id] = (client_id, list(oids))
                for oid in oids:
                    self._ref_pin_count[oid] = \
                        self._ref_pin_count.get(oid, 0) + 1
            for outer, inners in contains:
                if outer in self._ref_contains \
                        or outer in self._ref_released:
                    continue
                self._ref_contains[outer] = list(inners)
                for oid in inners:
                    self._ref_contained[oid] = \
                        self._ref_contained.get(oid, 0) + 1
            for task_id in pin_releases:
                entry = self._ref_pins.pop(task_id, None)
                if entry is None:
                    self._pin_released[task_id] = None
                    self._trim(self._pin_released, 200_000)
                    continue
                self._dec_counts(self._ref_pin_count, entry[1], dec)
            for oid in remove:
                holders = self._ref_holders.get(oid)
                if holders is not None:
                    holders.discard(client_id)
                    if not holders:
                        self._ref_holders.pop(oid, None)
                    dec.add(oid)
            # transient = held-and-dropped within one client flush window
            # (the hold never registered): a pure decrement event
            dec.update(transient)
            self._release_zeroed(dec)
        if resync:
            return {"ok": True, "resync": True}
        return {"ok": True}

    def _release_zeroed(self, oids):
        """Release objects whose count dropped to zero (lock held).
        Releases are triggered only by DECREMENTS — an object tracked
        but never held (e.g. a contains-edge reported before the owner's
        first flush) just waits."""
        for oid in oids:
            if oid not in self._ref_released and self._ref_count(oid) == 0:
                self._release_object(oid)

    def _release_object(self, oid: str):
        """Free one object's copies cluster-wide (lock held): pull it
        from the directory, queue a release on every node that holds a
        copy, and (after a grace) release anything it contained."""
        self._ref_released[oid] = None
        self._trim(self._ref_released, 500_000)
        locs = self._object_dir.pop(oid, None)
        self._object_meta.pop(oid, None)
        if locs:
            for node_id in locs:
                self._pending_release.setdefault(node_id, set()).add(oid)
        inners = self._ref_contains.pop(oid, None)
        if inners:
            # grace: a borrower that just deserialized the outer may have
            # increfs for the inners still in flight
            self._deferred_contains.append(
                (time.monotonic() + self._ref_grace, inners))
        self._ref_holders.pop(oid, None)
        self._ref_pin_count.pop(oid, None)
        self._ref_contained.pop(oid, None)

    def _process_deferred_contains(self):
        now = time.monotonic()
        with self._lock:
            due, keep = [], []
            for item in self._deferred_contains:
                (due if item[0] <= now else keep).append(item)
            self._deferred_contains = keep
            dec: set[str] = set()
            for _, inners in due:
                self._dec_counts(self._ref_contained, inners, dec)
            self._release_zeroed(dec)

    def _reap_stale_clients(self):
        now = time.monotonic()
        with self._lock:
            stale = [cid for cid, c in self._clients.items()
                     if c["alive"]
                     and now - c["last_seen"] > self._client_timeout]
            # prune long-dead entries: every driver session otherwise
            # leaves a permanent _clients row (the 60s linger keeps the
            # resurrection fence effective across brief outages)
            for cid in [cid for cid, c in self._clients.items()
                        if not c["alive"]
                        and now - c["last_seen"] > 60.0]:
                del self._clients[cid]
        for cid in stale:
            self._reap_client(cid, "client heartbeat timeout")

    def _reap_client(self, client_id: str, reason: str):
        """A driver/worker runtime died: drop every ref contribution it
        held and kill its non-detached actors (reference: owner-death
        handling in ReferenceCounter + GcsActorManager)."""
        with self._lock:
            c = self._clients.get(client_id)
            if c is not None and not c["alive"]:
                return
            if c is not None:
                c["alive"] = False
            dec: set[str] = set()
            for oid, holders in list(self._ref_holders.items()):
                if client_id in holders:
                    holders.discard(client_id)
                    if not holders:
                        self._ref_holders.pop(oid, None)
                    dec.add(oid)
            for task_id, (owner, oids) in list(self._ref_pins.items()):
                if owner != client_id:
                    continue
                del self._ref_pins[task_id]
                self._dec_counts(self._ref_pin_count, oids, dec)
            self._release_zeroed(dec)
            doomed = [a.actor_id for a in self._actors.values()
                      if a.owner_id == client_id and not a.detached
                      and a.state != "DEAD"]
        for actor_id in doomed:
            self._kill_actor(actor_id, f"owner {client_id[:8]} died: "
                                       f"{reason}")

    def _kill_actor(self, actor_id: str, reason: str):
        """Terminate an actor with no restart (shared by kill() and
        owner-death reaping)."""
        from ray_tpu.runtime.rpc import RpcClient
        with self._lock:
            actor = self._actors.get(actor_id)
            if actor is None:
                return
            actor.max_restarts = actor.num_restarts  # exhaust budget
            node = self._nodes.get(actor.node_id) if actor.node_id else None
        if node is not None:
            try:
                client = RpcClient(node.address)
                client.call("kill_actor_worker", actor_id=actor_id)
                client.close()
            except Exception:  # noqa: BLE001 - node may be gone already
                pass
        self._on_actor_failure_id(actor_id, reason)

    # ------------------------------------------------------------------
    # KV (reference: GcsKvManager / internal_kv)
    # ------------------------------------------------------------------

    def rpc_kv_put(self, conn, send_lock, *, ns, key, value,
                   overwrite=True):
        with self._lock:
            table = self._kv.setdefault(ns, {})
            if not overwrite and key in table:
                return {"ok": False}
            table[key] = value
            self._log("kv", (ns, key), value)
        # crash point BEFORE the fault-plan self-apply below: a plan
        # arriving through this very handler must not trip its own crash
        # rule on the write that installs it — only the NEXT WAL append
        # (e.g. the retried durable put) can fire
        _fi.maybe_crash("gcs.after_wal_append")
        if ns == _fi.KV_NS and key == _fi.KV_KEY:
            # the fault-plan switch key: other processes poll it, the
            # GCS applies it to its own plane at write time (outside the
            # KV lock — load_plan takes the plane's own lock)
            try:
                _fi.plane.load_plan(_fi.decode_plan(value))
            except Exception:  # noqa: BLE001 - bad plan must not break KV
                pass
        return {"ok": True}

    def rpc_kv_get(self, conn, send_lock, *, ns, key):
        with self._lock:
            return self._kv.get(ns, {}).get(key)

    def rpc_kv_del(self, conn, send_lock, *, ns, key):
        with self._lock:
            hit = self._kv.get(ns, {}).pop(key, None) is not None
            if hit:
                self._log("kv", (ns, key), None)
            return {"ok": hit}

    def rpc_kv_keys(self, conn, send_lock, *, ns, prefix=""):
        with self._lock:
            return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    # ------------------------------------------------------------------
    # jobs + task events (reference: GcsJobManager, GcsTaskManager)
    # ------------------------------------------------------------------

    def rpc_register_job(self, conn, send_lock, *, job_id, metadata=None):
        with self._lock:
            self._jobs[job_id] = {"job_id": job_id, "state": "RUNNING",
                                  "start_time": time.time(),
                                  "metadata": metadata or {}}
            self._log("job", job_id, dict(self._jobs[job_id]))
        return {"ok": True}

    def rpc_list_jobs(self, conn, send_lock):
        with self._lock:
            return list(self._jobs.values())

    def rpc_add_task_events(self, conn, send_lock, *, events):
        with self._lock:
            self._task_events.extend(events)
            if len(self._task_events) > self._max_task_events:
                del self._task_events[:-self._max_task_events]
        return {"ok": True}

    def rpc_report_demand(self, conn, send_lock, *, node_id, demands):
        """Per-node unmet resource demand (reference:
        GcsAutoscalerStateManager's cluster resource state feeding the
        autoscaler)."""
        with self._lock:
            if demands:
                self._pending_demand[node_id] = list(demands)
            else:
                self._pending_demand.pop(node_id, None)
        return True

    def rpc_get_pending_demand(self, conn, send_lock):
        with self._lock:
            return [d for ds in self._pending_demand.values() for d in ds]

    def rpc_get_task_events(self, conn, send_lock, *, limit=1000):
        with self._lock:
            return self._task_events[-limit:]

    # ------------------------------------------------------------------
    # cluster metrics plane (runtime/metrics_plane.py: delta frames in,
    # windowed time series out; reference analog: the node metrics
    # agents + Prometheus, centralized here like the object directory)
    # ------------------------------------------------------------------

    def _publish_metrics_window(self, window: dict):
        """A rolled aggregation window fans out to CH_METRICS
        subscribers (live dashboard views) through the same coalesced
        pushed-channel path CH_ACTOR uses. Best-effort by construction:
        publish() drops dead subscribers and never blocks ingest."""
        self.publish(CH_METRICS, {"event": "window",
                                  "start": window["start"],
                                  "end": window["end"],
                                  "data": window["data"]})

    def rpc_push_metrics(self, conn, send_lock, *, src, frame,
                         kind="worker", ts=None, annex=None):
        """Ingest one delta frame from a process's MetricsPusher.
        Duplicate delivery over-counts a window slightly (at-most-once
        is traded for never-blocking); the store is additive so the
        damage is bounded to the duplicated frame. ``annex`` is the
        pusher's piggybacked annex set (e.g. serve prefix-cache
        digests): latest-wins per (src, key), no windowing."""
        if frame:
            self._metrics_store.ingest(src, frame, ts)
        if annex is not None:
            self._metrics_store.put_annexes(src, annex)
        return {"ok": True}

    def rpc_query_metrics(self, conn, send_lock, *, name=None,
                          tags=None, last_s=None, group_by=(),
                          per_window=False):
        if name is None:
            return {"names": self._metrics_store.names()}
        return self._metrics_store.query(
            name, tags=tags, last_s=last_s, group_by=group_by,
            per_window=per_window)

    def rpc_query_metric_annexes(self, conn, send_lock, *, prefix="",
                                 max_age_s=None):
        return {"annexes": self._metrics_store.annexes(
            prefix, max_age_s=max_age_s)}

    # ------------------------------------------------------------------
    # cluster memory plane (reference: `ray memory` / memory_summary
    # aggregating every core worker's reference table plus plasma
    # occupancy). Ownership tables arrive as mem/owners/<proc> annexes
    # on metric frames; node occupancy as mem/node/<node> annexes; this
    # side joins them against the ref/pin/contains/directory tables.
    # ------------------------------------------------------------------

    def _mem_owner_annexes(self, max_age_s: float | None = 60.0) -> list:
        out = []
        for item in self._metrics_store.annexes("mem/owners/",
                                                max_age_s=max_age_s):
            p = item.get("payload")
            if isinstance(p, dict) and p.get("client_id"):
                p = dict(p)
                p["annex_ts"] = item["ts"]
                p["src"] = item["src"]
                out.append(p)
        return out

    def _mem_node_annexes(self, max_age_s: float | None = 60.0) -> list:
        out = []
        seen = set()
        for item in self._metrics_store.annexes("mem/node/",
                                                max_age_s=max_age_s):
            p = item.get("payload")
            if isinstance(p, dict) and p.get("node_id") \
                    and p["node_id"] not in seen:
                seen.add(p["node_id"])
                p = dict(p)
                p["annex_ts"] = item["ts"]
                out.append(p)
        return out

    def rpc_memory_table(self, conn, send_lock, *, oids=None,
                         limit=10_000):
        """Per-object reference view: size, holder clients, pin and
        contained-in contributions, directory locations — the join
        surface list_objects and memory_summary price owners with."""
        with self._lock:
            if oids is None:
                sel = list(self._object_dir)
                if len(sel) < limit:
                    sel.extend(o for o in self._ref_holders
                               if o not in self._object_dir)
                sel = sel[:limit]
            else:
                sel = list(oids)
            rows = {}
            for oid in sel:
                rows[oid] = {
                    "size": self._object_meta.get(oid, 0),
                    "holders": sorted(self._ref_holders.get(oid, ())),
                    "pins": self._ref_pin_count.get(oid, 0),
                    "contained": self._ref_contained.get(oid, 0),
                    "locations": sorted(self._object_dir.get(oid, ())),
                    "released": oid in self._ref_released,
                }
        return {"objects": rows}

    def rpc_memory_summary(self, conn, send_lock, *, top_n=20,
                           max_age_s=60.0):
        """Cluster-wide ownership-attributed memory summary: per-owner
        pinned/spilled/memstore bytes with top-N objects (state,
        borrower count, task pins, creation call site), per-callsite
        aggregation, per-node occupancy decomposition, and make-room
        pressure events attributed back to the owners whose pinned
        bytes were spilled. Totals reconcile owner bytes against node
        store stats (± in-flight transfers)."""
        now = time.time()
        owner_ann = self._mem_owner_annexes(max_age_s)
        nodes = self._mem_node_annexes(max_age_s)
        spilled_on: dict[str, str] = {}
        pulling_on: dict[str, str] = {}
        for nd in nodes:
            for o in nd.get("spilled_oids", ()):
                spilled_on[o] = nd["node_id"]
            for o in nd.get("being_pulled_oids", ()):
                pulling_on[o] = nd["node_id"]
        owners = []
        callsites: dict[str, dict] = {}
        oid_owner: dict[str, str] = {}
        with self._lock:
            for p in owner_ann:
                cid = p["client_id"]
                ents = []
                pinned_b = spilled_b = mem_b = joined_b = 0
                for ent in p.get("entries", ()):
                    oid, size, cs, created = ent[0], ent[1], ent[2], ent[3]
                    size = size or self._object_meta.get(oid, 0)
                    oid_owner[oid] = cid
                    holders = self._ref_holders.get(oid, ())
                    borrowers = max(
                        0, len(holders) - (1 if cid in holders else 0))
                    locs = self._object_dir.get(oid, ())
                    if oid in spilled_on:
                        state = "spilled"
                        spilled_b += size
                    elif oid in pulling_on:
                        state = "being_pulled"
                        pinned_b += size
                    elif locs:
                        # a directory location means a raylet-pinned
                        # primary in this runtime
                        state = "pinned"
                        pinned_b += size
                    else:
                        state = "in_memory"   # owner's in-process store
                        mem_b += size
                    joined_b += size
                    ents.append({
                        "object_id": oid, "size_bytes": size,
                        "callsite": cs,
                        "age_s": round(now - created, 1),
                        "state": state, "borrowers": borrowers,
                        "task_pins": self._ref_pin_count.get(oid, 0),
                        "locations": sorted(locs)})
                    if cs:
                        c = callsites.setdefault(
                            cs, {"callsite": cs, "count": 0, "bytes": 0})
                        c["count"] += 1
                        c["bytes"] += size
                ents.sort(key=lambda e: -e["size_bytes"])
                owners.append({
                    "owner": cid, "kind": p.get("kind"),
                    "owned": p.get("owned", len(ents)),
                    "owned_bytes": joined_b,
                    "pinned_bytes": pinned_b,
                    "spilled_bytes": spilled_b,
                    "memstore_bytes": mem_b,
                    "refs_held": p.get("refs_held", 0),
                    "last_activity": p.get("last_activity"),
                    "truncated": p.get("truncated", 0),
                    "pressure": p.get("pressure", []),
                    "top": ents[:top_n]})
        owners.sort(key=lambda o: -(o["pinned_bytes"]
                                    + o["spilled_bytes"]
                                    + o["memstore_bytes"]))
        pressure = []
        for nd in nodes:
            for ev in nd.get("pressure_events", ()):
                spilled_owners: dict[str, int] = {}
                for o in ev.get("spilled", ()):
                    own = oid_owner.get(o)
                    if own:
                        spilled_owners[own] = spilled_owners.get(own,
                                                                 0) + 1
                pressure.append({"node_id": nd["node_id"], **ev,
                                 "owners": spilled_owners})
        pressure.sort(key=lambda e: e.get("ts", 0))
        totals = {
            "num_owners": len(owners),
            "owned_bytes": sum(o["owned_bytes"] for o in owners),
            "pinned_bytes": sum(o["pinned_bytes"] for o in owners),
            "spilled_bytes": sum(o["spilled_bytes"] for o in owners),
            "memstore_bytes": sum(o["memstore_bytes"] for o in owners),
            "store_allocated_bytes": sum(
                nd.get("allocated_bytes", 0) for nd in nodes),
            "store_pinned_bytes": sum(
                nd.get("pinned_bytes", 0) for nd in nodes),
            "store_spilled_bytes": sum(
                nd.get("spilled_bytes", 0) for nd in nodes),
            "in_flight_bytes": sum(
                nd.get("being_pulled_bytes", 0) for nd in nodes),
        }
        cs_rows = sorted(callsites.values(), key=lambda c: -c["bytes"])
        return {"ts": now, "mode": "cluster", "owners": owners,
                "nodes": nodes, "callsites": cs_rows[:max(1, top_n)],
                "pressure": pressure[-32:], "totals": totals}

    def _detect_leaks(self, threshold_s=None, idle_s=None) -> list:
        """Refs held past the threshold with zero borrowers, zero task
        pins, zero contained-in edges, owned by an IDLE (but alive)
        process — flagged with their creation call site."""
        from ray_tpu.utils.config import get_config
        cfg = get_config()
        if threshold_s is None:
            threshold_s = cfg.memory_leak_threshold_s
        if idle_s is None:
            idle_s = cfg.memory_leak_idle_s
        now = time.time()
        leaks = []
        for p in self._mem_owner_annexes():
            cid = p.get("client_id")
            last_act = p.get("last_activity") or 0.0
            if now - last_act < idle_s:
                continue   # owner still churning refs: not a leak
            with self._lock:
                c = self._clients.get(cid)
                if c is None or not c.get("alive", True):
                    continue   # dead owners are reaped, not leaked
                for ent in p.get("entries", ()):
                    oid, size, cs, created = ent[0], ent[1], ent[2], ent[3]
                    if now - created < threshold_s:
                        continue
                    if oid in self._ref_released:
                        continue
                    holders = self._ref_holders.get(oid, set())
                    if holders - {cid}:
                        continue   # borrowed elsewhere: someone wants it
                    if self._ref_pin_count.get(oid, 0):
                        continue   # pinned by an in-flight task
                    if self._ref_contained.get(oid, 0):
                        continue   # reachable through an outer object
                    leaks.append({
                        "object_id": oid, "owner": cid,
                        "owner_kind": p.get("kind"),
                        "size_bytes": size or self._object_meta.get(oid,
                                                                    0),
                        "age_s": round(now - created, 1),
                        "owner_idle_s": round(now - last_act, 1),
                        "callsite": cs})
        leaks.sort(key=lambda lk: -lk["size_bytes"])
        return leaks

    def rpc_memory_leaks(self, conn, send_lock, *, threshold_s=None,
                         idle_s=None):
        return {"leaks": self._detect_leaks(threshold_s, idle_s)}

    # ------------------------------------------------------------------
    # distributed tracing plane
    # ------------------------------------------------------------------

    def rpc_push_spans(self, conn, send_lock, *, src, spans):
        """Ingest finished spans from a process's pusher tick. Same
        at-most-once trade as metric frames: a duplicated batch stores
        duplicate spans in the affected traces, never blocks."""
        accepted = self._trace_store.ingest(src, spans or [])
        return {"ok": True, "accepted": accepted}

    def rpc_get_trace(self, conn, send_lock, *, trace_id):
        return {"trace": self._trace_store.get(trace_id)}

    def rpc_list_traces(self, conn, send_lock, *, limit=50):
        return {"traces": self._trace_store.list(limit),
                "stats": self._trace_store.stats()}

    def rpc_stuck_calls(self, conn, send_lock, *, threshold_s=None):
        """The GCS's OWN in-flight registry (outbound RPCs it makes);
        per-node registries are collected by util.state.stuck_calls."""
        from ray_tpu.util import tracing as _tracing
        return {"calls": _tracing.local_stuck_calls(threshold_s)}

    def rpc_flight_record(self, conn, send_lock, *, last_s=None):
        from ray_tpu.util import tracing as _tracing
        return {"flight": _tracing.flight_snapshot(last_s)}

    # ------------------------------------------------------------------
    # cluster log plane queries (store: runtime/log_plane.LogStore)
    # ------------------------------------------------------------------

    def rpc_get_log(self, conn, send_lock, *, proc=None, task_id=None,
                    tail=100, after=None):
        """Recent lines of one process, or exactly one task's attributed
        segment. The task path resolves through the ``logs/segments/*``
        metric annexes (pushed by the emitting worker's MetricsPusher)
        to a (file@epoch, start, end) window, then filters interleaved
        neighbors by the per-line task stamp."""
        if task_id:
            seg = self._find_log_segment(task_id)
            if seg is None:
                return {"task": task_id, "lines": [],
                        "error": f"no log segment for task {task_id!r} "
                                 f"(annex not pushed yet, or the task "
                                 f"predates capture)"}
            out = self._log_store.segment(seg)
            # offsets bound the window; the per-line stamp is the
            # authority on WHOSE lines they are (concurrent async-actor
            # tasks interleave inside each other's offset windows)
            out["lines"] = [r for r in out["lines"]
                            if r.get("task") in (task_id, None)]
            return out
        if not proc:
            return {"lines": [], "error": "get_log needs proc or task_id"}
        return self._log_store.tail(
            proc, n=tail, after=tuple(after) if after else None)

    def _find_log_segment(self, task_id: str):
        from ray_tpu.runtime import log_plane as _log_plane
        for item in self._metrics_store.annexes(_log_plane.ANNEX_PREFIX):
            for seg in item["payload"] or []:
                if seg.get("task") == task_id:
                    return seg
        return None

    def rpc_list_logs(self, conn, send_lock):
        return self._log_store.list()

    def rpc_summarize_errors(self, conn, send_lock, *, last_s=None):
        groups = self._log_store.summarize_errors(last_s)
        try:
            leaks = self._detect_leaks()
        except Exception:
            leaks = []
        if leaks:
            now = time.time()
            by_site: dict[str, dict] = {}
            for lk in leaks:
                sig = "leaked object ref @ " + (lk["callsite"]
                                                or "unknown")
                g = by_site.setdefault(sig, {
                    "signature": sig, "kind": "leak",
                    "sample": (
                        f"{lk['object_id'][:16]} owned by "
                        f"{lk['owner'][:12]} held {lk['age_s']:.0f}s "
                        "with zero borrowers and an idle owner"),
                    "count": 0, "first_ts": now, "last_ts": now,
                    "procs": set(), "traces": [], "tasks": [],
                    "bytes": 0, "objects": []})
                g["count"] += 1
                g["bytes"] += lk["size_bytes"]
                g["first_ts"] = min(g["first_ts"], now - lk["age_s"])
                g["procs"].add(lk["owner"][:12])
                if len(g["objects"]) < 8:
                    g["objects"].append(lk["object_id"])
            for g in by_site.values():
                g["procs"] = sorted(g["procs"])
                groups.append(g)
        return {"groups": groups}

    def rpc_dump_stacks(self, conn, send_lock):
        """One-shot per-thread stack dump of the GCS process itself."""
        from ray_tpu.util.profiling import dump_stacks
        return {"stacks": dump_stacks()}

    def rpc_profile(self, conn, send_lock, *, duration_s=2.0, hz=100):
        """Sampling CPU profile of the GCS process (one leg of
        util.state.profile_cluster's fan-out). The RPC thread blocks for
        the window; the handler pool keeps serving other requests."""
        from ray_tpu.util.profiling import sample_profile
        from ray_tpu.utils.config import get_config
        return sample_profile(
            duration_s=min(float(duration_s),
                           float(get_config().profile_max_duration_s)),
            hz=hz)

    def _metrics_self_loop(self):
        """The GCS ingests its OWN registry (rpc handler timers, actor
        plane stage histograms) on the same delta protocol workers use —
        unless another runtime in this process already claimed the
        process-wide pusher (in-process GCS under a driver: the driver's
        pusher ships the shared registry)."""
        from ray_tpu.runtime import metrics_plane as _mp
        from ray_tpu.util import metrics as _metrics

        prev = None
        while not self._metrics_stop.wait(self._metrics_push_interval):
            # re-checked EVERY tick (claim_pusher is idempotent for the
            # holder): the span-ring drain below is destructive, so the
            # moment another pusher in this process takes the claim over
            # (forced hand-off) this loop must stop consuming the ring
            if not _mp.claim_pusher(f"gcs:{self.address[1]}"):
                continue
            try:
                frame, prev = _metrics.snapshot_delta(prev)
                if frame:
                    self._metrics_store.ingest("gcs", frame)
                ann = _mp.local_annexes()
                if ann:
                    self._metrics_store.put_annexes(
                        "gcs", {k: v[1] for k, v in ann.items()})
                # the GCS's own spans (rpc: server spans of handlers it
                # runs while traced) land in its store directly — no
                # network round trip to itself
                from ray_tpu.util import tracing as _tracing
                if _tracing.is_enabled():
                    spans = _tracing.drain_spans()
                    if spans:
                        self._trace_store.ingest("gcs", spans)
                # self-ingest captured log lines: no raylet monitor
                # tails the external GCS's files, so it drains its own
                # capture straight into the store
                from ray_tpu.runtime import log_plane as _log_plane
                cap = _log_plane.active_capture()
                if cap is not None:
                    recs = cap.drain_records()
                    if recs:
                        by_file: dict[str, dict] = {}
                        for r in recs:
                            e = by_file.setdefault(r["file"], {
                                "proc": cap.proc, "pid": r["pid"],
                                "file": r["file"], "lines": []})
                            e["lines"].append(
                                (r["offset"], r["ts"], r["stream"],
                                 r["line"], r["trace"], r["task"],
                                 r["name"], r["job"]))
                        self._ingest_logs("gcs", list(by_file.values()))
            except Exception:  # noqa: BLE001 - observability only
                pass

    # ------------------------------------------------------------------
    # cluster summary
    # ------------------------------------------------------------------

    def rpc_cluster_resources(self, conn, send_lock):
        total: dict[str, float] = {}
        avail: dict[str, float] = {}
        with self._lock:
            for n in self._nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources.items():
                    total[k] = total.get(k, 0.0) + v
                for k, v in n.available.items():
                    avail[k] = avail.get(k, 0.0) + v
        return {"total": total, "available": avail}


def main():
    """Run the GCS as a standalone process (reference:
    ``gcs_server_main.cc`` — the control plane is its own process).
    cluster_utils spawns this for ``Cluster(external_gcs=True)``."""
    import json
    import signal
    import sys

    # role stamp BEFORE construction: crash rules scoped proc="gcs" may
    # only ever kill a standalone control plane, never a driver-hosted
    # in-process GcsServer (whose process keeps the "driver" label)
    _fi.set_process_label("gcs")
    cfg = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
    server = GcsServer(
        host=cfg.get("host", "127.0.0.1"),
        port=cfg.get("port", 0),
        heartbeat_timeout_s=cfg.get("heartbeat_timeout_s", 5.0),
        persistence_dir=cfg.get("persistence_dir"),
    ).start()
    stop_ev = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_ev.set())
    signal.signal(signal.SIGINT, lambda *_: stop_ev.set())
    # flight recorder: dump recent spans/events before a SIGTERM death
    # (chains to the stop handler installed above)
    from ray_tpu.util import tracing as _tracing
    _tracing.install_crash_dump()
    print(json.dumps({"address": server.address}), flush=True)
    # capture AFTER the readiness line (the parent blocks reading the
    # JSON above from the real stdout pipe); the GCS self-ingests its
    # drain ring in _metrics_self_loop — no monitor tails these files
    import shutil
    import tempfile

    from ray_tpu.runtime import log_plane as _log_plane
    log_dir = tempfile.mkdtemp(prefix="raytpu-gcs-logs-")
    _log_plane.install_capture(f"gcs-{server.address[1]}",
                               log_dir=log_dir)
    try:
        stop_ev.wait()
    finally:
        _log_plane.uninstall_capture()
        server.stop()
        shutil.rmtree(log_dir, ignore_errors=True)


def _ns_key(namespace: str, name: str) -> str:
    """Registry key scoping a named actor to its namespace (the unit
    separator cannot appear in user-visible names by convention)."""
    return f"{namespace}\x1f{name}"


def _fits(demand: dict, supply: dict) -> bool:
    return all(supply.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def _critical_utilization(demand: dict, node: NodeInfo) -> float:
    """Score = max over demanded resources of (used+demand)/total; lower is
    better (reference: hybrid_scheduling_policy.cc:99-186)."""
    score = 0.0
    for k, v in demand.items():
        total = node.resources.get(k, 0.0)
        if total <= 0:
            continue
        used = total - node.available.get(k, 0.0)
        score = max(score, (used + v) / total)
    return score


def _place_bundles(bundles: list, strategy: str, nodes: list):
    """Greedy bundle placement. Returns node_id per bundle or None.

    ``SLICE_PACK`` (TPU twist, SURVEY §7): every bundle must land within
    ONE TPU slice (nodes sharing a ``tpu_slice`` label) so the group's
    collectives ride ICI, not DCN — wrong placement silently halves
    collective bandwidth. Slices are tried in descending free-TPU order;
    no single slice fitting ⇒ infeasible (strict by design)."""
    if strategy == "SLICE_PACK":
        slices: dict[str, list] = {}
        for n in nodes:
            key = n.labels.get("tpu_slice", f"__solo_{n.node_id}")
            slices.setdefault(key, []).append(n)

        def free_tpu(slice_nodes):
            return sum(n.available.get("TPU", 0.0) for n in slice_nodes)

        for _, slice_nodes in sorted(slices.items(),
                                     key=lambda kv: -free_tpu(kv[1])):
            res = _place_bundles(bundles, "PACK", slice_nodes)
            if res is not None:
                return res
        return None
    avail = {n.node_id: dict(n.available) for n in nodes}
    order = sorted(avail, key=lambda nid: -sum(avail[nid].values()))
    assignment = []
    if strategy in ("STRICT_PACK", "PACK"):
        # try single node first
        for nid in order:
            trial = dict(avail[nid])
            ok = True
            for b in bundles:
                if _fits(b, trial):
                    for k, v in b.items():
                        trial[k] -= v
                else:
                    ok = False
                    break
            if ok:
                return [nid] * len(bundles)
        if strategy == "STRICT_PACK":
            return None
    if strategy == "STRICT_SPREAD" and len(bundles) > len(nodes):
        return None
    used_nodes: set[str] = set()
    for b in bundles:
        placed = None
        if strategy == "PACK":
            # pack: fill nodes already in use before opening new ones —
            # preferring fresh nodes here fragments capacity and can
            # make a feasible packing spuriously infeasible
            candidates = ([nid for nid in order if nid in used_nodes]
                          + [nid for nid in order if nid not in used_nodes])
        else:
            # spread: prefer unused nodes; fall back to reuse
            candidates = ([nid for nid in order if nid not in used_nodes]
                          + [nid for nid in order if nid in used_nodes])
        if strategy == "STRICT_SPREAD":
            candidates = [nid for nid in order if nid not in used_nodes]
        for nid in candidates:
            if _fits(b, avail[nid]):
                for k, v in b.items():
                    avail[nid][k] -= v
                placed = nid
                used_nodes.add(nid)
                break
        if placed is None:
            return None
        assignment.append(placed)
    return assignment


if __name__ == "__main__":
    main()
