"""Worker process main loop.

Reference analog: ``python/ray/_private/workers/default_worker.py`` +
the execution callback ``execute_task`` in ``_raylet.pyx:1457``. The worker
registers with its raylet (handshake: ``worker_pool.cc``), then serves tasks
pushed over the registration channel:

- ``task``: a normal task — resolve args, run, store returns in shm.
- ``create_actor``: instantiate and pin the actor instance; subsequent
  ``actor_task`` messages run methods in per-caller submission order
  (reference: SequentialActorSubmitQueue + ActorSchedulingQueue).

Objects are read/written via direct shm attach (zero-copy); the raylet is
informed of each put so it can register locations with the GCS.
"""

from __future__ import annotations

import os
import threading
import traceback
from collections import defaultdict

import cloudpickle

from ray_tpu._private.shm_store import ShmObjectStore
from ray_tpu.runtime import object_codec
from ray_tpu.runtime.rpc import (
    ReconnectingRpcClient,
    RpcClient,
    RpcServer,
    recv_msg,
    send_msg,
)
from ray_tpu.utils import exceptions as exc


def _task_log_context(task: dict, job: str | None = None):
    """Log-plane execution bracket for ``task``: binds the ambient
    task_id and records the (file, start_offset, end_offset) segment in
    the offset annex so captured lines are attributable (reference: the
    task-log offsets the worker reports next to its log file)."""
    from ray_tpu.runtime import log_plane as _log_plane

    tc = task.get("trace_ctx") or {}
    return _log_plane.task_context(
        task.get("task_id"), task.get("name", "?"),
        job if job is not None else task.get("namespace"),
        tc.get("trace_id") if isinstance(tc, dict) else None)


class TaskPushServer(RpcServer):
    """Owner-facing task port (reference: the worker-side gRPC PushTask
    service the lease protocol pushes to, ``direct_task_transport.cc:234``).

    A lease IS a live connection here: the owner that holds the lease
    pushes tasks over it and gets the completion as the RPC reply; when
    the connection drops (owner returned the lease, or died), the worker
    tells its raylet so the lease's worker+resources return to the pool.
    """

    # push-reply replay cache bounds (entries AND payload bytes: direct
    # results ride replies, so cached replies hold real data)
    REPLY_CACHE_ENTRIES = 512
    REPLY_CACHE_BYTES = 8 << 20

    def __init__(self, worker: "Worker"):
        super().__init__("127.0.0.1", 0)
        self.fault_label = "worker"   # fault-injection endpoint label
        self._worker = worker
        # task push idempotency: a duplicated delivery (lost reply →
        # owner re-push, or an injected duplicate) must NOT re-execute —
        # and must return the FIRST reply VERBATIM, because direct
        # results ride the reply and exist nowhere else. key: task_id
        # (singular push) or tuple of task_ids (batched push).
        from collections import OrderedDict
        self._push_replies: OrderedDict = OrderedDict()
        self._push_reply_bytes = 0
        self._push_reply_lock = threading.Lock()

    def _cached_push_reply(self, key):
        if not key:
            return None
        with self._push_reply_lock:
            entry = self._push_replies.get(key)
        return entry[0] if entry is not None else None

    @staticmethod
    def _reply_nbytes(reply: dict) -> int:
        n = 256
        for v in (reply.get("results") or {}).values():
            try:
                n += len(v)
            except TypeError:
                n += 256
        return n

    def _remember_push_reply(self, key, reply: dict):
        if not key:
            return
        nbytes = self._reply_nbytes(reply)
        with self._push_reply_lock:
            self._push_replies[key] = (reply, nbytes)
            self._push_reply_bytes += nbytes
            while (len(self._push_replies) > self.REPLY_CACHE_ENTRIES
                   or self._push_reply_bytes > self.REPLY_CACHE_BYTES):
                _, (_, old) = self._push_replies.popitem(last=False)
                self._push_reply_bytes -= old

    def _run_one(self, task: dict):
        w = self._worker
        tid = task.get("task_id", "")
        if task.get("cancelled") or tid in w.cancelled_push_ids:
            return  # cancel error pre-stored by the raylet
        w.current_push_task_id = tid
        try:
            w._execute(task)
        finally:
            w.current_push_task_id = None

    def rpc_lease_attach(self, conn, send_lock):
        """Explicit lease handshake: the owner's FIRST request on a lease
        connection. Only connections tagged here (or by a task push, the
        fallback) count as lease channels — observability clients
        (stack dumps, profiles) and direct actor callers share this port,
        and their disconnects must NOT release the lease."""
        self._tag_lease_conn(conn)
        return {"ok": True}

    def _tag_lease_conn(self, conn):
        with self._worker._push_conn_lock:
            self._worker.lease_conns.add(conn)

    def rpc_push_task(self, conn, send_lock, *, task: dict):
        # expose the executing thread so the cancel path can interrupt
        # THIS thread — the main thread only runs the raylet-channel
        # recv loop
        self._tag_lease_conn(conn)
        cached = self._cached_push_reply(task.get("task_id"))
        if cached is not None:
            return cached
        self._worker.push_task_thread = threading.current_thread()
        # small returns ride the reply to the OWNER's store (reference:
        # in-process memory store for direct-call returns) — no shm
        # write, no pin report, no cross-node pull for tiny results
        sink: dict = {}
        task["_direct_sink"] = sink
        try:
            self._run_one(task)
        finally:
            self._worker.push_task_thread = None
        reply = {"ok": True, "task_id": task.get("task_id")}
        if sink:
            reply["results"] = sink
        self._remember_push_reply(task.get("task_id"), reply)
        return reply

    def rpc_push_tasks(self, conn, send_lock, *, tasks: list):
        """Batched push: one RPC carries several tasks, executed in
        order (the owner packs bursts of small same-shape tasks — one
        framed round trip instead of N)."""
        self._tag_lease_conn(conn)
        batch_key = tuple(t.get("task_id", "") for t in tasks)
        cached = self._cached_push_reply(batch_key)
        if cached is not None:
            return cached
        self._worker.push_task_thread = threading.current_thread()
        sink: dict = {}
        try:
            for task in tasks:
                task["_direct_sink"] = sink
                self._run_one(task)
        finally:
            self._worker.push_task_thread = None
        reply = {"ok": True}
        if sink:
            reply["results"] = sink
        self._remember_push_reply(batch_key, reply)
        return reply

    def rpc_submit_actor_task(self, conn, send_lock, *, task: dict):
        """DIRECT actor-task submission (owner → actor process, no raylet
        hop — reference: DirectActorTaskSubmitter pushing straight to the
        actor's gRPC queue). Same method name and semantics as the
        raylet-mediated path; the per-caller seq buffer keeps ordering
        across both."""
        w = self._worker
        if w.actor_id is None or task.get("actor_id") != w.actor_id:
            raise LookupError(
                f"actor {task.get('actor_id')} not hosted by this worker")
        if task.get("incarnation", 0) != w.actor_incarnation:
            # caller's numbering belongs to another incarnation — reject
            # so it refreshes (same contract as the raylet check)
            raise LookupError(
                f"actor {w.actor_id} incarnation mismatch "
                f"(task {task.get('incarnation')} != "
                f"{w.actor_incarnation})")
        # ack on ENQUEUE, execute on the actor-executor thread: the
        # raylet path acks pre-execution too, and an inline execution of
        # a self-terminating method (os._exit) would swallow the ack —
        # the owner would then RESEND the killer to the restarted
        # incarnation and burn its whole restart budget
        task["_direct"] = True   # no raylet bookkeeping: skip task_done
        w._enqueue_actor_task(task)
        return {"ok": True}

    def rpc_submit_actor_tasks(self, conn, send_lock, *, tasks: list):
        """Batched direct actor submission: the owner's flusher packs a
        burst of calls into one frame (one pickle+syscall per burst).
        Validation matches the singular path; a mismatch fails the whole
        frame and the owner resends task-by-task (worker-side seq dedup
        makes re-delivery of the already-enqueued prefix harmless)."""
        w = self._worker
        for task in tasks:
            if w.actor_id is None or task.get("actor_id") != w.actor_id:
                raise LookupError(
                    f"actor {task.get('actor_id')} not hosted by this worker")
            if task.get("incarnation", 0) != w.actor_incarnation:
                raise LookupError(
                    f"actor {w.actor_id} incarnation mismatch "
                    f"(task {task.get('incarnation')} != "
                    f"{w.actor_incarnation})")
            task["_direct"] = True   # no raylet bookkeeping: skip task_done
            w._enqueue_actor_task(task)
        return {"ok": True}

    def rpc_dump_stacks(self, conn, send_lock):
        """Per-thread stack dump (py-spy ``dump`` analog; reference:
        profile_manager.py) — the raylet proxies these for the dashboard."""
        from ray_tpu.util.profiling import dump_stacks

        return dump_stacks()

    def rpc_profile(self, conn, send_lock, *, duration_s: float = 2.0,
                    hz: int = 100):
        """Sampling CPU profile in collapsed-stack (flamegraph) format."""
        from ray_tpu.util.profiling import sample_profile

        return sample_profile(duration_s=min(duration_s, 30.0), hz=hz,
                              exclude_thread=threading.get_ident())

    def rpc_stuck_calls(self, conn, send_lock, *, threshold_s=None):
        """This worker's in-flight call registry (the raylet fans these
        out node-wide for util.state.stuck_calls)."""
        from ray_tpu.util import tracing as _tracing

        return {"calls": _tracing.local_stuck_calls(threshold_s)}

    def rpc_flight_record(self, conn, send_lock, *, last_s=None):
        """This worker's flight-recorder snapshot (recent spans + RPC
        events + in-flight calls), straight from local memory."""
        from ray_tpu.util import tracing as _tracing

        return {"flight": _tracing.flight_snapshot(last_s)}

    def on_disconnect(self, conn):
        # Release the lease only when the LAST lease-tagged connection
        # drops. A profiler or direct actor caller disconnecting from a
        # leased worker previously fired lease_closed too, flipping the
        # worker idle while the owner still held its channel — two tasks
        # could then run concurrently on a one-slot worker.
        with self._worker._push_conn_lock:
            was_lease = conn in self._worker.lease_conns
            self._worker.lease_conns.discard(conn)
            any_left = bool(self._worker.lease_conns)
        if not was_lease or any_left:
            return
        try:
            self._worker.ctrl.call("lease_closed",
                                   worker_id=self._worker.worker_id)
        except Exception:  # noqa: BLE001 - raylet is gone; worker will exit
            pass


class Worker:
    def __init__(self):
        # Apply this worker's runtime env BEFORE anything else: env_vars,
        # cached working_dir (chdir), py_modules on sys.path (reference:
        # the runtime-env agent prepares the context applied at worker
        # start — _private/runtime_env/agent/runtime_env_agent.py:281).
        renv_raw = os.environ.get("RAY_TPU_RUNTIME_ENV")
        if renv_raw:
            import json as _json

            from ray_tpu.runtime_env import apply_runtime_env, env_key

            renv = _json.loads(renv_raw)
            try:
                apply_runtime_env(renv)
            except Exception as e:  # noqa: BLE001
                # tell the raylet WHY before dying: otherwise the queued
                # task respawns a fresh worker that re-fails the same
                # install forever, and the error never leaves stderr
                try:
                    failer = RpcClient((os.environ["RAY_TPU_RAYLET_HOST"],
                                        int(os.environ["RAY_TPU_RAYLET_PORT"])))
                    failer.call("runtime_env_failed",
                                key=env_key(renv), error=repr(e))
                    failer.close()
                except Exception:  # noqa: BLE001
                    pass
                raise
        host = os.environ["RAY_TPU_RAYLET_HOST"]
        port = int(os.environ["RAY_TPU_RAYLET_PORT"])
        self.worker_id = os.environ["RAY_TPU_WORKER_ID"]
        self.node_id = os.environ["RAY_TPU_NODE_ID"]
        # log plane capture: stdout/stderr through the stamped tee into
        # a rotating <proc>.log the raylet's log monitor tails. Here
        # (not main()) so the zygote fork path — which re-enters
        # Worker() directly — is captured too. The Popen fd redirect to
        # .out/.err stays underneath for interpreter-level last words.
        from ray_tpu.runtime import log_plane as _log_plane
        _log_plane.install_capture(f"worker-{self.worker_id[:12]}")
        self.raylet_addr = (host, port)
        from ray_tpu.runtime import fault_injection as _fi
        _fi.maybe_init_from_config((os.environ["RAY_TPU_GCS_HOST"],
                                    int(os.environ["RAY_TPU_GCS_PORT"])),
                                   process_label="worker")
        self.store = ShmObjectStore(os.environ["RAY_TPU_STORE_NAME"])
        # control client: request/response to the raylet (ensure_local etc.)
        self.ctrl = RpcClient(self.raylet_addr, label="worker")
        # task-event reporting to the GCS sink (lazy buffer)
        self._gcs = ReconnectingRpcClient((os.environ["RAY_TPU_GCS_HOST"],
                               int(os.environ["RAY_TPU_GCS_PORT"])),
                               label="worker")
        self._event_buf: list[dict] = []
        self._event_lock = threading.Lock()
        self._last_flush = 0.0
        # periodic flusher: without it, the tail of a burst (<batch size)
        # strands in the buffer until the next task happens to run
        threading.Thread(target=self._flush_loop, daemon=True,
                         name="task-event-flusher").start()
        # actor state
        self.actor_instance = None
        self.actor_id = None
        self.actor_incarnation = 0
        self.actor_namespace = None
        # asyncio mode (reference: async actors run coroutine methods on
        # fibers — core_worker/fiber.h:17; here: one event loop thread,
        # concurrency bounded by an asyncio.Semaphore(max_concurrency))
        self._actor_loop = None
        self._actor_sem = None
        # ONE executor thread runs actor methods in arrival order no
        # matter which path delivered them (raylet channel or direct
        # owner push) — actor semantics are one method at a time
        import queue as _queue

        self._actor_exec_q: _queue.Queue = _queue.Queue()
        self._actor_exec_started = False
        self._seq_lock = threading.Lock()
        self._next_seq = defaultdict(int)       # caller -> next seq
        self._seq_buffer = defaultdict(dict)    # caller -> {seq: task}
        # cancel routing: SIGINT lands in the main thread; when a pushed
        # (leased) task is executing on a server thread, re-aim the
        # KeyboardInterrupt at that thread instead
        self.push_task_thread: threading.Thread | None = None
        # targeted cancel of leased tasks: ids to skip if not yet started,
        # and the id currently executing (so an interrupt only ever hits
        # the task it was aimed at — never a batchmate)
        self.current_push_task_id: str | None = None
        self.cancelled_push_ids: set[str] = set()
        self.lease_conns: set = set()   # open conns tagged as lease channels
        self._push_conn_lock = threading.Lock()
        self._lease_watch_gen = 0
        self._fn_cache: dict[int, tuple] = {}   # hash(blob) -> (blob, fn)
        self._fn_id_cache: dict[str, object] = {}   # fn_id -> fn
        self._report_buf: list[tuple[str, int]] = []
        self._report_cv = threading.Condition()
        threading.Thread(target=self._report_flush_loop, daemon=True,
                         name="report-flusher").start()
        # --- distributed refcounting (runtime/refcount.py): this worker
        # owns the process's ref flush channel — nested in-worker
        # runtimes piggyback on it (claim_flusher). It reports refs the
        # worker retains (actor state), releases task arg pins after
        # execution, and heartbeats client liveness. ---
        from ray_tpu.runtime import refcount as _refcount
        from ray_tpu.utils.config import get_config as _get_config
        _cfg = _get_config()
        self._refs = _refcount.global_counter
        self._ref_enabled = _cfg.ref_counting_enabled
        self._direct_limit = _cfg.max_direct_call_object_size
        self._fn_cache_cap = _cfg.worker_fn_cache_size
        self._event_batch = _cfg.task_event_batch_size
        self._event_flush_s = _cfg.task_event_flush_interval_s
        self._report_linger_s = _cfg.put_report_linger_s
        self._ref_send_lock = threading.Lock()
        if self._ref_enabled:
            _refcount.claim_flusher(self.worker_id)
            try:
                self._gcs.call("register_client",
                               client_id=self.worker_id, kind="worker")
            except Exception:  # noqa: BLE001 - reconnecting client
                pass
            threading.Thread(target=self._ref_flush_loop, daemon=True,
                             name="ref-flusher").start()
        # metrics plane: this worker process's registry (serve replica
        # gauges, engine histograms, prefix-digest annexes) pushes delta
        # frames to the GCS. The process-wide claim keeps it to ONE
        # pusher even when a nested in-worker runtime starts later.
        from ray_tpu.runtime.metrics_plane import MetricsPusher
        self._metrics_pusher = MetricsPusher(
            (os.environ["RAY_TPU_GCS_HOST"],
             int(os.environ["RAY_TPU_GCS_PORT"])),
            src=self.worker_id[:12], kind="worker").start()
        # memory plane: this process's ownership table (objects put by
        # the task code it runs, actor-held refs) rides the metric
        # frames as a live mem/owners annex. A nested in-worker runtime
        # registers the SAME key (client_id == worker_id), so the table
        # is never double-counted.
        from ray_tpu.runtime import metrics_plane as _mp

        def _mem_owners_annex():
            from ray_tpu.runtime import object_codec as _oc
            if not _refcount.is_active():
                return None
            snap = self._refs.ownership_snapshot(
                _get_config().memory_annex_max_entries)
            snap["client_id"] = self.worker_id
            snap["kind"] = "worker"
            snap["pressure"] = _oc.recent_pressure()
            return snap

        _mp.set_annex_provider(f"mem/owners/{self.worker_id[:12]}",
                               _mem_owners_annex)
        self._install_sigint_router()
        # Owner-facing push port, then registration — ALL execution state
        # above must exist first: the instant registration lands, the
        # raylet may lease this worker and an owner may push a task.
        self.push_server = TaskPushServer(self).start()
        import socket as _socket
        self.chan = _socket.create_connection(self.raylet_addr)
        self.chan.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self.chan_lock = threading.Lock()
        send_msg(self.chan, {"method": "register_worker",
                             "worker_id": self.worker_id,
                             "push_addr": list(self.push_server.address)})
        reply = recv_msg(self.chan)
        assert reply.get("registered"), reply

    def _arm_lease_watch(self):
        """The raylet granted a lease on this worker: if the owner never
        dials the push port (it died, or its dial failed after the
        grant), hand the lease back — otherwise this worker and its
        resources leak in 'leased' state forever. The check is on OPEN
        LEASE-TAGGED connections (not connection history, and not mere
        open connections — an observability probe must not mask an owner
        that never dialed), so an owner that attached before this message
        was processed is never falsely reclaimed; an owner that dialed
        and died is covered by on_disconnect."""
        import time as _time

        from ray_tpu.utils.config import get_config as _get_config

        timeout = _get_config().lease_never_dialed_timeout_s
        self._lease_watch_gen += 1
        gen = self._lease_watch_gen

        def watch():
            _time.sleep(timeout)
            with self._push_conn_lock:
                active = len(self.lease_conns)
            # the gen check keeps a STALE watch (armed for a previous
            # lease cycle) from reclaiming a newer grant
            if active == 0 and gen == self._lease_watch_gen:
                try:
                    self.ctrl.call("lease_closed", worker_id=self.worker_id)
                except Exception:  # noqa: BLE001
                    pass

        threading.Thread(target=watch, daemon=True,
                         name="lease-watch").start()

    def _cancel_push(self, task_id: str):
        """Cancel a lease-pushed task BY ID: interrupt only if it is the
        one currently executing; otherwise flag it so the push loop skips
        it. (A raw SIGINT would hit whatever batchmate happens to be
        running.)"""
        import ctypes

        self.cancelled_push_ids.add(task_id)
        while len(self.cancelled_push_ids) > 1024:
            self.cancelled_push_ids.pop()
        t = self.push_task_thread
        if (t is not None and t.is_alive()
                and self.current_push_task_id == task_id):
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_long(t.ident), ctypes.py_object(KeyboardInterrupt))

    def _install_sigint_router(self):
        import ctypes
        import signal

        def _route(signum, frame):
            t = self.push_task_thread
            if t is not None and t.is_alive():
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_long(t.ident),
                    ctypes.py_object(KeyboardInterrupt))
            else:
                raise KeyboardInterrupt

        try:
            signal.signal(signal.SIGINT, _route)
        except ValueError:
            pass  # not the main thread (embedded/test use): keep default

    # ------------------------------------------------------------------

    def run(self):
        while True:
            try:
                msg = recv_msg(self.chan)
            except KeyboardInterrupt:
                # a cancel SIGINT that raced past its task (the task
                # finished first): ignore — the worker stays in the pool
                continue
            except Exception:  # raylet gone -> exit
                return
            kind = msg.get("type")
            if kind == "task":
                self._execute(msg["task"])
                self._send({"type": "task_done",
                            "task_id": msg["task"].get("task_id")})
            elif kind == "create_actor":
                self._create_actor(msg["actor_id"], msg["task"],
                                   msg.get("incarnation", 0))
            elif kind == "actor_task":
                self._enqueue_actor_task(msg["task"])
            elif kind == "cancel_push":
                self._cancel_push(msg["task_id"])
            elif kind == "lease_granted":
                self._arm_lease_watch()
            elif kind == "exit":
                return

    def _send(self, msg: dict):
        try:
            send_msg(self.chan, msg, self.chan_lock)
        except OSError:
            os._exit(1)

    # ------------------------------------------------------------------
    # argument / result plumbing
    # ------------------------------------------------------------------

    _EMPTY_ARGS_BLOB = cloudpickle.dumps(([], {}), protocol=5)

    def _resolve_args(self, task: dict):
        if task["args_blob"] == self._EMPTY_ARGS_BLOB:
            # no-arg calls dominate microbench/fan-out loads: skip the
            # per-task unpickle (and the marker scan) entirely
            return [], {}
        epoch0 = (self._refs.created_epoch() if self._ref_enabled else 0)
        args, kwargs = cloudpickle.loads(task["args_blob"])
        dep_oids = [a[1] for a in _iter_markers(args, kwargs)]
        # Results of EARLIER tasks in the SAME pushed batch live only in
        # the batch's direct-return sink: the reply that publishes them
        # to the owner cannot be sent until this very task finishes, so
        # asking the raylet (ensure_local) for them deadlocks the whole
        # lease pipeline for the full dependency timeout. Resolve those
        # straight from the sink; pull everything else as usual.
        sink = task.get("_direct_sink") or {}
        values = {}
        pull = []
        for oid_hex in dep_oids:
            payload = sink.get(oid_hex)
            if payload is None:
                pull.append(oid_hex)
            elif oid_hex not in values:
                value, is_error = object_codec.decode_view(
                    memoryview(payload).cast("B"))
                if is_error:
                    raise value
                values[oid_hex] = value
        if pull:
            # bounded client wait: a lost reply on a live control channel
            # must not hang the worker forever
            try:
                missing = self.ctrl.call("ensure_local", oids=pull,
                                         timeout_s=60.0, timeout=65.0)
            except TimeoutError:
                missing = pull
            if missing:
                raise exc.ObjectLostError(missing[0], "dependency not found")
        for _, oid_hex in _iter_markers(args, kwargs):
            if oid_hex in values:
                continue
            value, is_error = object_codec.get_value(
                self.store, bytes.fromhex(oid_hex), timeout_ms=0)
            if is_error:
                raise value
            values[oid_hex] = value
        args = [values[a[1]] if _is_marker(a) else a for a in args]
        kwargs = {k: values[v[1]] if _is_marker(v) else v
                  for k, v in kwargs.items()}
        if self._ref_enabled and self._refs.created_epoch() != epoch0:
            # args carried nested ObjectRefs: register this process's
            # holds BEFORE execution so they are live at the GCS while
            # the submitter's task pin is still in place
            self._ref_flush_now()
        return args, kwargs

    def _ref_flush_loop(self):
        import time as _time

        from ray_tpu.utils.config import get_config

        period = get_config().ref_heartbeat_interval_s
        last_beat = _time.monotonic()
        while True:
            # event-driven: block until ref activity (or the client-
            # liveness heartbeat is due) instead of polling — thousands
            # of idle workers polling thrash the host scheduler
            remain = period - (_time.monotonic() - last_beat)
            if self._refs.wait_pending(max(remain, 0.05)):
                _time.sleep(0.1)    # coalesce a burst into one RPC
            now = _time.monotonic()
            beat = now - last_beat >= period
            if self._ref_flush_now(force_heartbeat=beat) or beat:
                last_beat = now

    def _ref_flush_now(self, force_heartbeat: bool = False) -> bool:
        from ray_tpu.runtime.refcount import flush_once

        with self._ref_send_lock:
            return flush_once(self._refs, self._gcs.call, self.worker_id,
                              "worker", force_heartbeat)

    def _release_task_pin(self, task: dict):
        """Execution finished: release the submitter's arg pins for this
        task (only when the owner actually registered some)."""
        if self._ref_enabled and task.get("pinned"):
            self._refs.release_task_pin(task.get("task_id", ""))

    def _store_returns(self, task: dict, result):
        if task.get("streaming"):
            # generator task: seal each yield at its derived oid AS IT IS
            # PRODUCED (consumers iterate while this loop still runs),
            # then the count object (= the declared return oid)
            from ray_tpu.runtime.streaming import store_stream

            store_stream(
                result, bytes.fromhex(task["task_id"]),
                lambda oid, v, er: self._put_and_report(oid.hex(), v,
                                                        is_error=er),
                lambda oid, n: self._put_and_report(oid.hex(), n))
            return
        return_oids = task["return_oids"]
        if len(return_oids) == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != len(return_oids):
                raise ValueError(
                    f"task declared {len(return_oids)} returns, got "
                    f"{len(values)}")
        sink = task.get("_direct_sink")
        for oid_hex, value in zip(return_oids, values):
            if sink is not None and self._try_direct_return(
                    sink, oid_hex, value):
                continue
            self._put_and_report(oid_hex, value)

    # returns at or under this encoded size ride the push reply to the
    # owner instead of the local shm store (reference:
    # max_direct_call_object_size — small objects live in the owner's
    # memory store, memory_store.h:43)
    def _try_direct_return(self, sink: dict, oid_hex: str, value,
                           is_error: bool = False) -> bool:
        limit = self._direct_limit
        try:
            payload, obj, caught = object_codec.encode_bytes(
                value, is_error=is_error, limit=limit)
        except Exception:  # noqa: BLE001 - unpicklable: store path errors
            return False
        if payload is None:
            # too large for the reply: shm store path, reusing the
            # serialized form (a 1 GiB return must not pickle twice)
            self._put_and_report(oid_hex, value, is_error=is_error,
                                 preserialized=obj, contained=caught)
            return True
        if caught:
            # the return value contains ObjectRefs: the contains-edges
            # anchor on the return oid (which will materialize at the
            # owner's store)
            self._refs.add_contains(oid_hex, caught)
        sink[oid_hex] = payload
        return True

    def _put_and_report(self, oid_hex: str, value, is_error: bool = False,
                        preserialized=None, contained=None):
        """Put with a held ref, then report so the raylet pins the primary
        copy. The seal-HOLD stays live until the (batched) report flush
        confirms the pin — never a window in which the sealed object is
        evictable (reference: plasma seal + raylet PinObjectIDs in the
        task-return handshake). Reports are BATCHED across task returns:
        one raylet RPC per flush instead of per return keeps the control
        round trip off the task hot path."""
        oid = bytes.fromhex(oid_hex)
        size = object_codec.put_value_durable(
            self.store, oid, value, is_error=is_error,
            request_space=self._request_space, hold=True,
            preserialized=preserialized, contained=contained)
        with self._report_cv:
            self._report_buf.append((oid_hex, size))
            self._report_cv.notify()

    def _report_flush_loop(self):
        import time as _time

        while True:
            with self._report_cv:
                while not self._report_buf:
                    self._report_cv.wait()
            _time.sleep(self._report_linger_s)  # coalesce return burst
            with self._report_cv:
                batch, self._report_buf = self._report_buf, []
            try:
                # one token per batch: if the reply is lost and a retry
                # layer redelivers, the raylet pins each object once
                import uuid as _uuid

                self.ctrl.call("report_objects",
                               entries=[(o, s) for o, s in batch],
                               token=_uuid.uuid4().hex)
            except Exception:  # noqa: BLE001 - raylet gone; exiting soon
                pass
            finally:
                for oid_hex, size in batch:
                    if size > 0:   # size 0 = lost first-write race: no hold
                        try:
                            self.store.release(bytes.fromhex(oid_hex))
                        except Exception:  # noqa: BLE001
                            pass

    def _request_space(self, nbytes: int):
        self.ctrl.call("request_space", nbytes=nbytes)

    def _store_error(self, task: dict, error: BaseException):
        # errors are sealed into TaskError objects (never printed here),
        # so a captured worker also emits the traceback into its log
        # file — that is what summarize_errors() aggregates; local-mode
        # (no capture) keeps the console quiet as before
        from ray_tpu.runtime import log_plane as _log_plane

        cap = _log_plane.active_capture()
        if cap is not None:
            try:
                tb = getattr(error, "remote_traceback", None) or "".join(
                    traceback.format_exception(
                        type(error), error, error.__traceback__))
                for ln in str(tb).splitlines():
                    cap.emit("e", ln)
            except Exception:  # noqa: BLE001 - logging must not mask
                pass
        sink = task.get("_direct_sink")
        for oid_hex in task["return_oids"]:
            oid = bytes.fromhex(oid_hex)
            if self.store.contains(oid):
                continue
            if sink is not None and self._try_direct_return(
                    sink, oid_hex, error, is_error=True):
                continue
            try:
                self._put_and_report(oid_hex, error, is_error=True)
            except Exception:  # noqa: BLE001 - unpicklable exception
                self._put_and_report(
                    oid_hex,
                    exc.TaskError(task.get("name", "?"),
                                  RuntimeError(repr(error))),
                    is_error=True)

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def _report_task_event(self, task: dict, start: float, ok: bool):
        """Buffered task-event reporting to the GCS sink (reference:
        task_event_buffer.cc -> gcs_task_manager.cc). Flushes every few
        events so the state API / dashboard / timeline see cluster tasks
        without a per-task RPC."""
        import os as _os
        import time as _time

        # start is this process's monotonic clock; wall_* re-anchors the
        # pair to wall time before the event leaves the process, so the
        # GCS sink holds cross-worker-comparable stamps (and the unified
        # chrome trace can overlay them with wall-clock tracing spans)
        end = _time.monotonic()
        wall_end = _time.time()
        with self._event_lock:
            self._event_buf.append({
                "task_id": task.get("task_id", ""),
                "name": task.get("name", "?"),
                "start": start,
                "end": end,
                "wall_start": wall_end - (end - start),
                "wall_end": wall_end,
                "pid": _os.getpid(),
                "state": "FINISHED" if ok else "FAILED",
                "thread": f"worker-{self.worker_id[:8]}",
            })
            # large batch threshold (flag task_event_batch_size): at
            # 10k+ calls/s a flush-per-8 means >1k GCS RPCs/s of pure
            # observability; the timer flusher bounds staleness for
            # sparse workloads
            full = len(self._event_buf) >= self._event_batch
        if full or _time.monotonic() - self._last_flush > \
                self._event_flush_s:
            self._flush_task_events()

    def _flush_loop(self):
        import time as _time

        while True:
            _time.sleep(1.0)
            self._flush_task_events()

    def _flush_task_events(self):
        import time as _time

        with self._event_lock:
            if not self._event_buf:
                return
            batch, self._event_buf = self._event_buf, []
        self._last_flush = _time.monotonic()
        try:
            self._gcs.call("add_task_events", events=batch)
        except (OSError, ConnectionError):
            pass  # observability only; never fail work for it

    def _load_function(self, blob: bytes):
        """Unpickle-once function cache (reference: executors fetch and
        register a function ONCE from the function table —
        ``fetch_and_register_remote_function``); repeated tasks of the
        same function skip the cloudpickle.loads."""
        key = hash(blob)
        hit = self._fn_cache.get(key)
        if hit is not None and hit[0] == blob:
            return hit[1]
        fn = cloudpickle.loads(blob)
        if len(self._fn_cache) > self._fn_cache_cap:
            self._fn_cache.clear()
        self._fn_cache[key] = (blob, fn)
        return fn

    def _load_function_id(self, fn_id: str):
        """Function-TABLE path: the task carries a 16-byte content id;
        the blob is fetched from the GCS table once per (worker,
        function) and cached by id (content-addressed — no blob compare
        needed on hits)."""
        hit = self._fn_id_cache.get(fn_id)
        if hit is not None:
            return hit
        blob = self._gcs.call("kv_get", ns="__functions__", key=fn_id)
        if blob is None:
            raise exc.TaskError(
                "?", RuntimeError(f"function {fn_id} not in the GCS "
                                  f"function table"))
        fn = cloudpickle.loads(blob)
        if len(self._fn_id_cache) > self._fn_cache_cap:
            self._fn_id_cache.clear()
        self._fn_id_cache[fn_id] = fn
        return fn

    def _execute(self, task: dict):
        from ray_tpu.runtime_context import (reset_task_namespace,
                                             set_task_namespace)

        ns_token = set_task_namespace(task.get("namespace"))
        try:
            # log-plane bracket: begin/end byte offsets around the WHOLE
            # execution (arg resolve through error sealing) so every
            # captured line — including the stored traceback — is
            # attributable to this task_id via the offset annex
            with _task_log_context(task):
                self._execute_inner(task)
        finally:
            reset_task_namespace(ns_token)
            self._release_task_pin(task)

    def _execute_inner(self, task: dict):
        import time as _time

        started = _time.monotonic()
        try:
            if "function_ref" in task:
                # cross-language task (C++/external client): the function
                # is a DESCRIPTOR resolved by import, args are plain data
                # already decoded from the msgpack frame (runtime/xlang.py
                # — reference: cross-language function descriptors)
                from ray_tpu.runtime.xlang import resolve_function_ref

                fn = resolve_function_ref(task["function_ref"])
                args = list(task.get("args") or [])
                kwargs = dict(task.get("kwargs") or {})
            elif "function_id" in task:
                fn = self._load_function_id(task["function_id"])
                args, kwargs = self._resolve_args(task)
            else:
                fn = self._load_function(task["function_blob"])
                args, kwargs = self._resolve_args(task)
        except BaseException as e:  # noqa: BLE001
            self._store_error(task, e)
            self._report_task_event(task, started, False)
            return
        def _call():
            from ray_tpu.runtime import fault_injection as _fi

            # crash point: args resolved, function loaded, mid-execution
            # — the owner's lease channel breaks with no reply and the
            # retry/typed-error path must cover it (chaos worker class)
            _fi.maybe_crash("worker.mid_task")
            result = fn(*args, **kwargs)
            if _iscoroutine(result):
                # async def remote function: drive it to completion
                # on a per-task loop (reference: async tasks run on
                # the worker's event loop)
                import asyncio

                result = asyncio.run(result)
            return result

        try:
            from ray_tpu.util import tracing as _tracing

            trace_ctx = task.get("trace_ctx")
            if trace_ctx is None:
                # tracing off (the default): no generator-contextmanager
                # frame on the per-task hot path (the in-flight entry is
                # always on — a hung task must be visible in stuck_calls
                # even when nobody enabled tracing beforehand)
                with _tracing.inflight("task", task.get("name", "?"),
                                       task.get("task_id")):
                    result = _call()
            else:
                # the coroutine drive stays INSIDE the span: an async
                # task's real execution happens in asyncio.run, not at
                # the call that returns the coroutine
                with _tracing.execution_span(task.get("name", "?"),
                                             trace_ctx), \
                        _tracing.inflight("task", task.get("name", "?"),
                                          task.get("task_id")):
                    result = _call()
        except BaseException as e:  # noqa: BLE001
            self._store_error(
                task, exc.TaskError(task.get("name", "?"), e,
                                    tb=traceback.format_exc()))
            self._report_task_event(task, started, False)
            return
        try:
            self._store_returns(task, result)
        except BaseException as e:  # noqa: BLE001
            self._store_error(task, e)
            self._report_task_event(task, started, False)
            return
        self._report_task_event(task, started, True)

    def _create_actor(self, actor_id: str, task: dict,
                      incarnation: int = 0):
        try:
            cls = cloudpickle.loads(task["function_blob"])
            # the actor lives in its creator's namespace: every method
            # execution (and nested get_actor/create_actor from methods)
            # resolves names there
            self.actor_namespace = task.get("namespace")
            from ray_tpu.runtime_context import set_task_namespace

            set_task_namespace(self.actor_namespace)
            args, kwargs = self._resolve_args(task)
            self.actor_instance = cls(*args, **kwargs)
            self.actor_id = actor_id
            self.actor_incarnation = incarnation
            import inspect

            if any(inspect.iscoroutinefunction(getattr(cls, n, None))
                   for n in dir(cls)):
                # ASYNC actor: methods are scheduled onto this loop (the
                # executor thread posts, never waits), so awaits overlap
                # up to max_concurrency in-flight calls
                import asyncio

                self._actor_loop = asyncio.new_event_loop()
                self._actor_sem = asyncio.Semaphore(
                    max(1, int(task.get("max_concurrency") or 1)))
                threading.Thread(target=self._actor_loop.run_forever,
                                 daemon=True,
                                 name="actor-asyncio-loop").start()
            if not self._actor_exec_started:
                self._actor_exec_started = True
                threading.Thread(target=self._actor_exec_loop,
                                 daemon=True,
                                 name="actor-executor").start()
        except BaseException as e:  # noqa: BLE001
            self._send({"type": "actor_creation_failed",
                        "actor_id": actor_id,
                        "reason": f"{type(e).__name__}: {e}"})
            self._store_error(task, exc.ActorDiedError(
                actor_id, f"__init__ failed: {e!r}"))
            self._release_task_pin(task)
            self._ref_flush_now()   # the pin release must outrun os._exit
            self._send({"type": "task_done", "task_id": task.get("task_id")})
            os._exit(1)
        self._release_task_pin(task)
        self._store_returns(task, None)
        self._send({"type": "actor_ready", "actor_id": actor_id})
        self._send({"type": "task_done", "task_id": task.get("task_id")})

    def _enqueue_actor_task(self, task: dict):
        """Per-caller submission-order execution (sequence buffering)."""
        caller = task.get("caller_id", "?")
        seq = task.get("seq", 0)
        runnable = []
        with self._seq_lock:
            if seq < self._next_seq[caller]:
                # duplicate delivery (caller retried after a lost reply):
                # the original execution already sealed the return objects
                # (first-write-wins), so drop instead of re-running
                return
            self._seq_buffer[caller][seq] = task
            while self._next_seq[caller] in self._seq_buffer[caller]:
                t = self._seq_buffer[caller].pop(self._next_seq[caller])
                self._next_seq[caller] += 1
                runnable.append(t)
        for t in runnable:
            self._actor_exec_q.put(t)

    def _actor_exec_loop(self):
        from ray_tpu.runtime_context import set_task_namespace

        while True:
            task = self._actor_exec_q.get()
            # per-thread contextvar: the creator's namespace must be set
            # HERE (and is captured by run_coroutine_threadsafe for async
            # calls), not just on the channel thread that created the
            # actor
            set_task_namespace(getattr(self, "actor_namespace", None))
            try:
                if self._actor_loop is not None and not task.get("noop"):
                    self._post_async_actor_task(task)
                else:
                    self._run_actor_task(task)
            except BaseException:  # noqa: BLE001
                # _run_actor_task seals task errors itself; anything that
                # still escapes would silently kill this (sole) executor
                # thread and turn every future call into an acked-then-
                # queued-forever hang. Crash the worker instead — the
                # raylet's death path restarts the actor (the pre-
                # executor-thread behavior).
                traceback.print_exc()
                os._exit(1)

    def _post_async_actor_task(self, task: dict):
        """Async-actor dispatch: resolve args on THIS thread (dependency
        pulls are blocking control RPCs that must not stall the event
        loop), then fire the call onto the loop and move to the next
        queued task — calls START in per-caller submission order and
        interleave at await points (reference async-actor semantics)."""
        import asyncio
        import time as _time

        started = _time.monotonic()
        try:
            args, kwargs = self._resolve_args(task)
        except BaseException as e:  # noqa: BLE001
            self._store_error(
                task, exc.TaskError(task.get("name", "?"), e,
                                    tb=traceback.format_exc()))
            self._report_task_event(task, started, False)
            self._release_task_pin(task)
            if not task.get("_direct"):
                self._send({"type": "task_done",
                            "task_id": task.get("task_id")})
            return
        asyncio.run_coroutine_threadsafe(
            self._run_actor_coro(task, args, kwargs), self._actor_loop)

    async def _run_actor_coro(self, task: dict, args, kwargs):
        """One async-actor call, bounded by the concurrency semaphore.
        Sync methods of an async actor run inline ON the loop (they
        block it — reference behavior: everything posts to the loop)."""
        import inspect
        import time as _time

        async with self._actor_sem:
            started = _time.monotonic()
            _done = (lambda: None) if task.get("_direct") else (
                lambda: self._send({"type": "task_done",
                                    "task_id": task.get("task_id")}))

            def done():
                self._release_task_pin(task)
                _done()
            with _task_log_context(
                    task, getattr(self, "actor_namespace", None)):
                try:
                    from ray_tpu.util import tracing as _tracing

                    method = getattr(self.actor_instance,
                                     task["method_name"])
                    with _tracing.execution_span(task.get("name", "?"),
                                                 task.get("trace_ctx")), \
                            _tracing.inflight("actor_task",
                                              task.get("name", "?"),
                                              task.get("task_id")):
                        result = method(*args, **kwargs)
                        if inspect.isawaitable(result):
                            result = await result
                except BaseException as e:  # noqa: BLE001
                    self._store_error(
                        task, exc.TaskError(task.get("name", "?"), e,
                                            tb=traceback.format_exc()))
                    self._report_task_event(task, started, False)
                    done()
                    return
                try:
                    self._store_returns(task, result)
                except BaseException as e:  # noqa: BLE001
                    self._store_error(task, e)
                    self._report_task_event(task, started, False)
                    done()
                    return
                self._report_task_event(task, started, True)
                done()

    def _run_actor_task(self, task: dict):
        import time as _time

        # direct-pushed tasks (owner -> this worker, no raylet hop) need
        # no task_done: the raylet tracked nothing for them, and at 10k+
        # calls/s the per-call frame to the raylet channel is pure GIL
        # and syscall overhead on both ends
        _done = (lambda: None) if task.get("_direct") else (
            lambda: self._send({"type": "task_done",
                                "task_id": task.get("task_id")}))

        def done():
            self._release_task_pin(task)
            _done()
        if task.get("noop"):
            # seq gap-filler (owner sealed errors for a submit that never
            # arrived): advances the ordered queue, executes nothing
            done()
            return
        started = _time.monotonic()
        with _task_log_context(task, getattr(self, "actor_namespace",
                                             None)):
            try:
                from ray_tpu.util import tracing as _tracing

                from ray_tpu.runtime import fault_injection as _fi

                args, kwargs = self._resolve_args(task)
                method = getattr(self.actor_instance, task["method_name"])
                # crash point: actor method about to run — exercises the
                # actor RESTARTING/DEAD reconciliation + typed
                # ActorDiedError surfacing at the caller
                _fi.maybe_crash("worker.mid_actor_task")
                with _tracing.execution_span(task.get("name", "?"),
                                             task.get("trace_ctx")), \
                        _tracing.inflight("actor_task",
                                          task.get("name", "?"),
                                          task.get("task_id")):
                    result = method(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001
                self._store_error(
                    task, exc.TaskError(task.get("name", "?"), e,
                                        tb=traceback.format_exc()))
                self._report_task_event(task, started, False)
                done()
                return
            try:
                self._store_returns(task, result)
            except BaseException as e:  # noqa: BLE001
                self._store_error(task, e)
                self._report_task_event(task, started, False)
                done()
                return
            self._report_task_event(task, started, True)
            done()


def _iscoroutine(x) -> bool:
    import inspect

    return inspect.iscoroutine(x)


def _is_marker(x) -> bool:
    return (isinstance(x, tuple) and len(x) == 2 and x[0] == "__objref__")


def _iter_markers(args, kwargs):
    for a in args:
        if _is_marker(a):
            yield a
    for v in kwargs.values():
        if _is_marker(v):
            yield v


def main():
    import sys

    if "--zygote" in sys.argv[1:]:
        # fork-server template mode (runtime/prestart.py): preload the
        # worker import set once, then serve os.fork() requests over the
        # control pipe — each forked child re-enters Worker().run() with
        # a fresh identity. The template itself NEVER constructs a
        # Worker and never initializes a device backend (fork-after-
        # XLA-init is unsafe; devices attach post-fork in the child).
        from ray_tpu.runtime.prestart import zygote_main

        raise SystemExit(zygote_main())
    # flight recorder: dump recent spans/events before a SIGTERM death
    from ray_tpu.util import tracing as _tracing

    _tracing.install_crash_dump()
    Worker().run()


if __name__ == "__main__":
    main()
