"""Binary layout for objects in the shared-memory store.

Reference analog: plasma's data+metadata split (``plasma.fbs``) combined
with Ray's Pickle5 out-of-band serialization
(``python/ray/_private/serialization.py``). Layout:

    [u8 flags][u64 n_sections][u64 len_0 .. len_{n-1}][section bytes ...]

Section 0 is the pickle meta stream; sections 1..n-1 are out-of-band
buffers. Reads are zero-copy: sections are sliced views of the shm mapping
handed to pickle as PickleBuffers.

flags bit 0: error object (deserialized value is an exception to raise).
"""

from __future__ import annotations

import pickle
import struct
import threading
import time as _time_mod
from collections import deque

from ray_tpu.runtime.refcount import global_counter as _refs
from ray_tpu.runtime.serialization import SerializedObject, deserialize, serialize
from ray_tpu.util import metrics as _metrics

_U64 = struct.Struct("<Q")
FLAG_ERROR = 1

# -- memory-pressure attribution (the make-room/OOM path) ----------------
#
# Every StoreFullError a writer hits in put_value_durable is recorded
# here: a counter for the metrics plane and a small ring of recent
# events ({ts, oid, size, rounds}) that rides this process's
# mem/owners annex — so a forced spill on the raylet can be joined back
# to the WRITER whose allocation applied the pressure, not just the
# owners whose pinned bytes were spilled to relieve it.
_c_store_full = _metrics.counter(
    "ray_tpu_mem_store_full_total",
    "store-full (make-room) rounds hit by writers in this process")
_pressure_lock = threading.Lock()
_pressure_ring: deque = deque(maxlen=32)


def _note_store_full(oid_hex: str, size: int):
    if _metrics.enabled():
        _c_store_full.inc()
    with _pressure_lock:
        if _pressure_ring and _pressure_ring[-1]["oid"] == oid_hex:
            _pressure_ring[-1]["rounds"] += 1
            _pressure_ring[-1]["ts"] = _time_mod.time()
        else:
            _pressure_ring.append({"ts": _time_mod.time(),
                                   "oid": oid_hex, "size": int(size),
                                   "rounds": 1})


def recent_pressure() -> list[dict]:
    """Recent store-full events this process's writers hit, newest
    last (shipped on the mem/owners annex)."""
    with _pressure_lock:
        return [dict(e) for e in _pressure_ring]


def _serialize_capturing(value):
    """Serialize, capturing any ObjectRef pickled inside the value
    (reference: contained-in tracking, reference_count.h:67). Returns
    ``(obj, captured_oid_hexes)`` — the caller records the
    contains-edges only AFTER the store write succeeds; reporting a
    failed put's edges would inflate the inner refcounts forever."""
    with _refs.capture() as cap:
        obj = serialize(value)
    return obj, cap.oids


def _note_contains(object_id: bytes, caught):
    if caught:
        _refs.add_contains(object_id.hex(), caught)


def encoded_size(obj: SerializedObject) -> int:
    n = 1 + len(obj.buffers)
    return 1 + 8 + 8 * n + len(obj.meta) + sum(
        memoryview(b).nbytes for b in obj.buffers)


def encode_into(buf: memoryview, obj: SerializedObject, *, is_error: bool = False):
    """Write the object into a writable view (from ShmObjectStore.create)."""
    sections = [obj.meta] + [memoryview(b).cast("B") for b in obj.buffers]
    buf[0] = FLAG_ERROR if is_error else 0
    off = 1
    buf[off:off + 8] = _U64.pack(len(sections))
    off += 8
    for s in sections:
        buf[off:off + 8] = _U64.pack(memoryview(s).nbytes)
        off += 8
    for s in sections:
        s = memoryview(s).cast("B")
        buf[off:off + s.nbytes] = s
        off += s.nbytes


def decode_view(view: memoryview):
    """(value, is_error) from a read-only store view — zero-copy buffers."""
    flags = view[0]
    off = 1
    (n,) = _U64.unpack(view[off:off + 8])
    off += 8
    lens = []
    for _ in range(n):
        (ln,) = _U64.unpack(view[off:off + 8])
        off += 8
        lens.append(ln)
    sections = []
    for ln in lens:
        sections.append(view[off:off + ln])
        off += ln
    meta = bytes(sections[0])
    value = deserialize(SerializedObject(meta=meta, buffers=sections[1:]))
    return value, bool(flags & FLAG_ERROR)


def put_value(store, object_id: bytes, value, *, is_error: bool = False) -> int:
    """Serialize + write + seal into a ShmObjectStore. Returns byte size.

    First-write-wins: if the object already exists (e.g. a restarted actor
    re-running its creation task, or racing error/result writers), the put
    is a no-op returning 0 — consumers observe whichever write sealed first,
    matching the local-mode store's semantics."""
    from ray_tpu._private.shm_store import ObjectExistsError

    obj, caught = _serialize_capturing(value)
    size = encoded_size(obj)
    try:
        buf = store.create(object_id, size)
    except ObjectExistsError:
        return 0
    try:
        encode_into(buf, obj, is_error=is_error)
    finally:
        del buf
    store.seal(object_id)
    _note_contains(object_id, caught)
    return size


def put_value_durable(store, object_id: bytes, value, *,
                      is_error: bool = False, request_space=None,
                      timeout_s: float = 30.0, hold: bool = False,
                      preserialized=None, contained=None) -> int:
    """``put_value`` with memory-pressure backoff: when the store is full,
    ask the node manager to make room (synchronous spill of pinned-idle
    objects — ``request_space`` callable takes the needed byte count) and
    retry until the deadline (reference: plasma ``CreateRequestQueue``
    retrying creates while ``LocalObjectManager`` spills). The value is
    serialized ONCE, outside the retry loop.

    ``hold=True`` seals with a kept read ref (see ``ShmObjectStore.seal``)
    so the object cannot be evicted before the caller reports it to the
    node manager for pinning; the caller must ``store.release`` it after.
    """
    import time as _time

    from ray_tpu._private.shm_store import ObjectExistsError, StoreFullError

    if preserialized is not None:
        # a caller (the direct-return size probe) already serialized —
        # never pickle a large value twice
        obj, caught = preserialized, (contained or [])
    else:
        obj, caught = _serialize_capturing(value)
    size = encoded_size(obj)
    deadline = _time.monotonic() + timeout_s
    delay = 0.02
    while True:
        try:
            buf = store.create(object_id, size)
        except ObjectExistsError:
            return 0  # first write wins (see put_value)
        except StoreFullError:
            _note_store_full(object_id.hex(), size)
            if _time.monotonic() >= deadline:
                raise
            if request_space is not None:
                try:
                    request_space(size)
                except Exception:  # noqa: BLE001 - raylet busy; retry anyway
                    pass
            _time.sleep(delay)
            delay = min(delay * 2, 0.5)
            continue
        try:
            encode_into(buf, obj, is_error=is_error)
        finally:
            del buf
        store.seal(object_id, hold=hold)
        _note_contains(object_id, caught)
        return size


def get_value(store, object_id: bytes, timeout_ms: int = -1):
    """Read + deserialize. Returns (value, is_error).

    NOTE: the materialized value may alias shm (zero-copy numpy); the store
    refcount is dropped after deserialization, which copies for small
    objects; large arrays keep the view alive via the buffer protocol."""
    view = store.get(object_id, timeout_ms=timeout_ms)
    try:
        return decode_view(view)
    finally:
        del view
        store.release(object_id)


def raw_bytes(store, object_id: bytes, timeout_ms: int = -1) -> bytes:
    """Copy the full encoded object (for node-to-node transfer)."""
    view = store.get(object_id, timeout_ms=timeout_ms)
    try:
        return bytes(view)
    finally:
        del view
        store.release(object_id)


def encode_bytes(value, *, is_error: bool = False, limit: int | None = None):
    """Serialize a value into the store's binary layout WITHOUT touching
    a store (the direct small-return path: the bytes ride the task
    reply to the owner, who ``put_raw``s them into its local store —
    reference analog: small returns go to the owner's in-process
    memory store in the task reply, ``memory_store.h:43``).

    Returns ``(payload | None, serialized_obj, contained_oids)`` —
    payload is None when the encoded size exceeds ``limit`` (the size
    check runs BEFORE any byte copy, and the serialized form is handed
    back so the store path never re-pickles a large value)."""
    obj, caught = _serialize_capturing(value)
    size = encoded_size(obj)
    if limit is not None and size > limit:
        return None, obj, list(caught)
    buf = bytearray(size)
    encode_into(memoryview(buf), obj, is_error=is_error)
    return bytes(buf), obj, list(caught)


def put_raw(store, object_id: bytes, payload: bytes, *,
            hold: bool = False):
    """Write pre-encoded bytes (receiving side of a transfer).
    ``hold=True`` keeps a read ref through the seal (caller releases)."""
    buf = store.create(object_id, len(payload))
    try:
        buf[:] = payload
    finally:
        del buf
    store.seal(object_id, hold=hold)
