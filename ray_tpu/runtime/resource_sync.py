"""Versioned resource-view sync between raylets and the GCS.

Reference analog: ``src/ray/common/ray_syncer/ray_syncer.h:86`` — the
reference syncs versioned RESOURCE_VIEW messages over bidirectional
streams so the control plane's scheduling view tracks node state at RPC
latency. Round 3 shipped whole-snapshot heartbeats instead (0.5s
period): every spillback/pick_node decision ran on a view up to one
heartbeat stale, and the payload was O(resources) per beat regardless
of change.

This module is the TPU-native equivalent:

- every local resource mutation (lease grant/release, task dispatch,
  completion) bumps a VERSION and wakes a debounced pusher thread that
  sends ``resource_update(node_id, version, available)`` to the GCS
  within ``push_delay_s`` — staleness is bounded by RPC latency + the
  debounce, not the heartbeat period;
- heartbeats carry only the version number (payload O(1)); the GCS
  replies ``need_resources`` when its stored version lags (first beat,
  or a GCS restart lost the view), triggering one full push — the
  resync path;
- versions are monotonic per raylet incarnation, so out-of-order
  updates (a slow push racing a newer one) are dropped by the GCS.
"""

from __future__ import annotations

import threading


class ResourceSyncer:
    """Raylet-side half: version tracking + the debounced pusher."""

    def __init__(self, node, snapshot_fn, *, load_fn=None,
                 push_delay_s: float = 0.01):
        self._node = node
        self._snapshot = snapshot_fn        # () -> dict available
        self._load = load_fn or (lambda: 0)  # () -> ready-queue depth
        self._push_delay = push_delay_s
        self._cv = threading.Condition()
        self._version = 0
        self._pushed_version = 0
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(target=self._push_loop,
                                        daemon=True,
                                        name="resource-syncer")
        self._thread.start()

    def mark_changed(self):
        """A local resource mutation happened: bump the version and wake
        the pusher (called from the scheduler's acquire/release paths —
        must be cheap and never block on the network)."""
        with self._cv:
            self._version += 1
            self._cv.notify()

    @property
    def version(self) -> int:
        with self._cv:
            return self._version

    @property
    def pushed_version(self) -> int:
        """The last version KNOWN DELIVERED — what heartbeats should
        report. Reporting the live version instead makes every beat on
        a busy node look like a lost push (the debounced pusher is
        always slightly behind) and triggers spurious full resyncs."""
        with self._cv:
            return max(self._pushed_version, 0)

    def force_push(self):
        """GCS requested a resync (heartbeat replied need_resources)."""
        with self._cv:
            self._pushed_version = -1
            self._cv.notify()

    def _push_loop(self):
        import time

        node = self._node
        while not node._stopping:
            with self._cv:
                while (self._pushed_version >= self._version
                       and not node._stopping):
                    self._cv.wait(timeout=1.0)
                if node._stopping:
                    return
            # debounce: a dispatch burst (N grants in a few ms) becomes
            # one push carrying the latest view
            time.sleep(self._push_delay)
            with self._cv:
                version = self._version
            try:
                with node._gcs_lock:
                    node._gcs.call("resource_update",
                                   node_id=node.node_id,
                                   version=version,
                                   available=self._snapshot(),
                                   load=self._load())
                with self._cv:
                    self._pushed_version = max(self._pushed_version,
                                               version)
            except Exception:  # noqa: BLE001 - GCS down: the heartbeat's
                # version mismatch re-triggers the push after recovery
                time.sleep(0.2)
