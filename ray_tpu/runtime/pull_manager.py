"""Chunked object transfer with admission control.

Reference: ``ObjectManager`` chunked push/pull — ``PullManager``
(``pull_manager.h:52``, admission control over in-flight bytes),
``ObjectManager::Push/HandlePush`` (``object_manager.cc:339,562``),
default chunk size 5 MiB (``ray_config_def.h:355``). This is the PULL
side (locations come from the GCS object directory): a large object is
fetched as parallel chunk reads over a small pool of dedicated transfer
connections and written straight into a pre-allocated shm buffer — no
whole-object intermediate copy on either side — then sealed.

Admission control caps the total bytes in flight across ALL pulls: a
burst of large pulls queues instead of filling the destination store in
one shot (the backpressure the round-1 whole-object RPC lacked).

Dedup: concurrent pulls of one object share a single in-flight pull;
waiters block on its event rather than issuing duplicate transfers.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from ray_tpu.runtime.rpc import RpcClient
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing as _tracing

# transfers are rare and expensive relative to a histogram observe, so
# the leader of every successful pull is timed end to end (meta probe +
# chunk reads + seal); bytes feed the data-plane GiB/s dashboard view
_h_pull = _metrics.histogram(
    "ray_tpu_object_transfer_s",
    "leader-side object pull latency (spill restore or peer transfer)"
).handle()
_pull_bytes = _metrics.counter(
    "ray_tpu_object_transfer_bytes", "object bytes pulled from peers")


class _Pull:
    __slots__ = ("event", "ok")

    def __init__(self):
        self.event = threading.Event()
        self.ok = False


class PullManager:
    def __init__(self, *,
                 fetch_local: Callable[[str], bool],
                 peer_addresses: Callable[[str], list],
                 store,
                 on_pulled: Callable[[str, int], None],
                 chunk_size: int = 5 << 20,
                 max_in_flight_bytes: int = 256 << 20,
                 conns_per_peer: int = 4,
                 fault_label: str | None = None):
        """fetch_local(oid) -> restored from spill locally;
        peer_addresses(oid) -> [(node_id, address), ...] candidate
        sources; on_pulled(oid, size) -> track + register location."""
        self._fetch_local = fetch_local
        self._peer_addresses = peer_addresses
        self._store = store
        self._on_pulled = on_pulled
        self.chunk_size = chunk_size
        self._budget = max_in_flight_bytes
        self._in_flight_bytes = 0
        self._budget_cv = threading.Condition()
        self._pulls: dict[str, _Pull] = {}
        self._pulls_lock = threading.Lock()
        # transfer connections, pooled per peer address (chunk reads are
        # served on the peer's per-connection threads, so N connections
        # give N-way parallel reads)
        self._conns: dict[tuple, list] = {}
        self._conns_lock = threading.Lock()
        self._conns_per_peer = conns_per_peer
        # transfer connections carry the owning node's fault-injection
        # label: an injected raylet<->raylet partition must sever the
        # data plane too, not just the control RPCs
        self._fault_label = fault_label
        self._stopping = False

    def stats(self) -> dict:
        """Memory-plane view of in-flight transfer load: how many pulls
        are active and how many admitted bytes are currently in flight
        (the ± slack memory_summary allows when reconciling owner bytes
        against store occupancy)."""
        with self._budget_cv:
            in_flight = self._in_flight_bytes
        with self._pulls_lock:
            active = len(self._pulls)
        return {"num_active": active, "in_flight_bytes": in_flight,
                "budget_bytes": self._budget}

    def active_oids(self) -> set:
        with self._pulls_lock:
            return set(self._pulls)

    def stop(self):
        self._stopping = True
        with self._conns_lock:
            pools = list(self._conns.values())
            self._conns.clear()
        for pool in pools:
            for c in pool:
                try:
                    c.close()
                except Exception:  # noqa: BLE001
                    pass

    # -- admission -----------------------------------------------------

    def _acquire(self, nbytes: int):
        with self._budget_cv:
            # an oversized single object is admitted alone rather than
            # never (budget is a throttle, not a hard object-size cap)
            while (self._in_flight_bytes > 0
                   and self._in_flight_bytes + nbytes > self._budget
                   and not self._stopping):
                self._budget_cv.wait(timeout=0.5)
            self._in_flight_bytes += nbytes

    def _release(self, nbytes: int):
        with self._budget_cv:
            self._in_flight_bytes -= nbytes
            self._budget_cv.notify_all()

    # -- connections ---------------------------------------------------

    def _checkout(self, addr: tuple) -> RpcClient:
        with self._conns_lock:
            pool = self._conns.get(addr)
            if pool:
                return pool.pop()
        return RpcClient(addr, label=self._fault_label)

    def _checkin(self, addr: tuple, client: RpcClient):
        if client._closed:
            return
        with self._conns_lock:
            pool = self._conns.setdefault(addr, [])
            if len(pool) < self._conns_per_peer and not self._stopping:
                pool.append(client)
                return
        client.close()

    # -- pulling -------------------------------------------------------

    def pull(self, oid_hex: str, timeout_s: float = 30.0,
             known_sources: list | None = None) -> bool:
        """Make the object local (spill restore or peer transfer).
        Concurrent callers for one oid share a single transfer.
        ``known_sources``: (node_id, address) candidates the caller
        already resolved (ensure_local batches the directory lookup —
        a per-oid GCS query here melted the control plane at
        200k-object gets)."""
        import binascii

        oid = binascii.unhexlify(oid_hex)
        if self._store.contains(oid):
            return True
        with self._pulls_lock:
            pull = self._pulls.get(oid_hex)
            if pull is not None:
                leader = False
            else:
                pull = self._pulls[oid_hex] = _Pull()
                leader = True
        if not leader:
            pull.event.wait(timeout=timeout_s)
            return pull.ok or self._store.contains(oid)
        # watchdog + span cover the LEADER only (followers ride its
        # transfer); chunk worker threads re-bind this span's context so
        # their fetch RPCs parent into it across the peer hop
        token = _tracing.call_started("pull", oid_hex[:16])
        try:
            with _tracing.span(f"pull:{oid_hex[:8]}", kind="transfer"):
                t0 = time.perf_counter()
                pull.ok = self._do_pull(oid_hex, oid, known_sources)
                if pull.ok and _metrics.enabled():
                    _h_pull.observe(time.perf_counter() - t0)
                return pull.ok
        finally:
            _tracing.call_finished(token)
            with self._pulls_lock:
                self._pulls.pop(oid_hex, None)
            pull.event.set()

    def _do_pull(self, oid_hex: str, oid: bytes,
                 known_sources: list | None = None) -> bool:
        if self._fetch_local(oid_hex):
            return True
        pairs = (known_sources if known_sources is not None
                 else self._peer_addresses(oid_hex))
        addrs = [tuple(a) for _, a in pairs]
        if not addrs:
            return False
        # probe candidates for meta; large objects stripe across EVERY
        # holder that answers (a hot object must not serialize on one
        # source's NIC — reference: PullManager spreads chunk requests
        # over the object's location set)
        sources = []
        size = crc = None
        for addr in addrs:
            client = None
            try:
                client = self._checkout(addr)
                meta = client.call("fetch_object_meta", oid=oid_hex,
                                   timeout=30)
            except Exception:  # noqa: BLE001 - next candidate
                if client is not None:
                    client.close()
                continue
            self._checkin(addr, client)
            if not meta.get("found"):
                continue
            sources.append(addr)
            size = int(meta["size"])
            crc = meta.get("crc32")
            if size <= self.chunk_size:
                break   # one source is plenty for a single-chunk object
        if not sources:
            return False
        if size <= self.chunk_size:
            ok = self._pull_small(oid_hex, oid, sources[0], size, crc)
        else:
            ok = self._pull_chunked(oid_hex, oid, sources, size, crc)
        if ok and _metrics.enabled():
            _pull_bytes.inc(size)
        return ok

    def _pull_small(self, oid_hex: str, oid: bytes, addr: tuple,
                    size: int, crc) -> bool:
        client = self._checkout(addr)
        self._acquire(size)
        try:
            payload = client.call("fetch_object", oid=oid_hex,
                                  timeout=60)
            if not self._verify(oid_hex, payload, size, crc, addr):
                return False
            self._write_whole(oid, payload)
        except Exception:  # noqa: BLE001
            client.close()
            return False
        finally:
            self._release(size)
            self._checkin(addr, client)
        self._on_pulled(oid_hex, size)
        return True

    @staticmethod
    def _verify(oid_hex: str, payload, size: int, crc, addr) -> bool:
        """Transfer integrity: refuse to SEAL bytes that don't match the
        source's length+CRC — a torn read must surface as a retried
        fetch, never as a readable-but-corrupt object."""
        import sys
        import zlib

        if payload is None or len(payload) != size:
            print(f"[pull] length mismatch for {oid_hex[:8]} from {addr}: "
                  f"got {0 if payload is None else len(payload)} want "
                  f"{size}", file=sys.stderr)
            return False
        if crc is not None and zlib.crc32(payload) != crc:
            print(f"[pull] CRC mismatch for {oid_hex[:8]} from {addr} "
                  f"(size {size})", file=sys.stderr)
            return False
        return True

    def _write_whole(self, oid: bytes, payload: bytes):
        from ray_tpu.runtime import object_codec

        if not self._store.contains(oid):
            try:
                object_codec.put_raw(self._store, oid, payload)
            except Exception:  # noqa: BLE001 - racing pull won
                pass

    REFRESH_EVERY_CHUNKS = 16   # re-resolve holders every N chunks

    def _pull_chunked(self, oid_hex: str, oid: bytes, sources: list,
                      size: int, crc=None) -> bool:
        """Parallel chunk reads STRIPED across every known holder, into a
        pre-allocated shm buffer. While the transfer runs, the holder set
        is re-resolved periodically: a broadcast-hot object gains sources
        as other pullers complete and register, and in-flight pulls fan
        out onto them instead of hammering the origin (reference:
        spreading pull requests over the location set + proactive Push,
        object_manager.cc:339 — pull-based here, same effect)."""
        n_chunks = -(-size // self.chunk_size)
        try:
            view = self._store.create(oid, size)
        except Exception:  # noqa: BLE001 - exists (racing pull) or OOM
            return self._store.contains(oid)
        next_chunk = [0]
        done_chunks = [0]
        retries: list[int] = []   # chunks dropped by a dying source
        state_lock = threading.Lock()
        # contextvars do not cross threads: capture the pull span's
        # context here so chunk workers can re-bind it (their fetch
        # RPCs then carry the _trace header to the source node)
        trace_ctx = _tracing.current_context()
        known: list = list(sources)       # all holders seen so far
        failed = threading.Event()
        done_workers = threading.Semaphore(0)
        per_source = max(1, self._conns_per_peer)

        def fetch_range(client, addr):
            fetched = 0
            while not failed.is_set() and not self._stopping:
                with state_lock:
                    if retries:
                        i = retries.pop()
                    elif next_chunk[0] < n_chunks:
                        i = next_chunk[0]
                        next_chunk[0] += 1
                    else:
                        return True
                off = i * self.chunk_size
                length = min(self.chunk_size, size - off)
                self._acquire(length)
                try:
                    try:
                        chunk = client.call("fetch_object_chunk",
                                            oid=oid_hex, offset=off,
                                            length=length, timeout=60)
                    except Exception:
                        # hand the claimed chunk back for a surviving
                        # source; this worker dies with its connection
                        with state_lock:
                            retries.append(i)
                        raise
                    if chunk is None or len(chunk) != length:
                        failed.set()
                        return False
                    view[off:off + length] = chunk
                    with state_lock:
                        done_chunks[0] += 1
                finally:
                    self._release(length)
                fetched += 1
                if fetched % self.REFRESH_EVERY_CHUNKS == 0:
                    self._maybe_add_sources(oid_hex, known, state_lock,
                                            spawn)
            return True

        def run_worker(addr):
            if trace_ctx is not None:
                _tracing.bind(trace_ctx)
            try:
                try:
                    client = self._checkout(addr)
                except OSError:
                    # this source is unreachable; others may carry the
                    # transfer — only fail the pull if NOBODY can
                    return
                try:
                    fetch_range(client, addr)
                except Exception:  # noqa: BLE001
                    client.close()
                    return
                self._checkin(addr, client)
            finally:
                done_workers.release()

        spawned = [0]

        def spawn(addr):
            workers = min(per_source,
                          max(1, n_chunks // max(1, len(known))))
            for _ in range(workers):
                with state_lock:
                    spawned[0] += 1
                threading.Thread(target=run_worker, args=(addr,),
                                 daemon=True).start()

        for addr in sources:
            spawn(addr)
        # wait for EVERY worker, including ones spawned mid-transfer by
        # the holder refresh (re-read the count each round: sealing
        # while a late-spawned worker still writes into the view would
        # be a torn object)
        finished = 0
        while True:
            done_workers.acquire()
            finished += 1
            with state_lock:
                if finished >= spawned[0]:
                    break
        # workers may have exited without fetching every chunk (dead
        # sources): incomplete coverage is a failure
        with state_lock:
            complete = done_chunks[0] >= n_chunks and not failed.is_set()
        try:
            if not complete or self._stopping:
                view.release()
                self._store.abort(oid)   # unsealed: writer-owned free
                return False
            if not self._verify(oid_hex, view, size, crc, sources[0]):
                view.release()
                self._store.abort(oid)
                return False
            view.release()
            self._store.seal(oid)
        except Exception:  # noqa: BLE001
            return False
        self._on_pulled(oid_hex, size)
        return True

    def _maybe_add_sources(self, oid_hex: str, known: list, state_lock,
                           spawn):
        """Mid-transfer holder refresh: stripe onto newly registered
        copies of a hot object."""
        try:
            fresh = [tuple(a) for _, a in self._peer_addresses(oid_hex)]
        except Exception:  # noqa: BLE001 - GCS hiccup; keep pulling
            return
        new = []
        with state_lock:
            for addr in fresh:
                if addr not in known:
                    known.append(addr)
                    new.append(addr)
        for addr in new:
            spawn(addr)
