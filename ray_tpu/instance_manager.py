"""Autoscaler v2-style instance manager: versioned instance storage plus
a reconciler that converges instance records against the provider's and
the GCS's views.

Reference analog: ``autoscaler/v2/instance_manager/instance_storage.py``
(versioned records, compare-and-swap upserts) and the v2 reconciler
(``instance_manager.py``) driving the instance lifecycle::

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                   \\__________________/      |
                    (provider lost it)        v
                       TERMINATED  <-  TERMINATING

The autoscaler's decisions (launch/terminate) become instance records;
the reconciler — not the decision code — owns state transitions, so a
crash or a slow cloud never leaves bookkeeping about what exists to the
scaling policy's imagination.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field

# lifecycle states (reference: instance_manager.proto Instance.Status)
QUEUED = "QUEUED"                  # decided, not yet sent to the provider
REQUESTED = "REQUESTED"            # provider call made; VM not visible yet
ALLOCATED = "ALLOCATED"            # provider lists it; raylet not yet up
RAY_RUNNING = "RAY_RUNNING"        # GCS sees the node alive
TERMINATING = "TERMINATING"        # terminate sent to the provider
TERMINATED = "TERMINATED"          # gone from the provider view

LIVE_STATES = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING, TERMINATING)


@dataclass
class Instance:
    instance_id: str
    status: str = QUEUED
    node_id: str | None = None     # cloud/provider node id once known
    resources: dict = field(default_factory=dict)
    requested_at: float | None = None
    running_at: float | None = None
    terminated_at: float | None = None
    version: int = 0
    status_history: list = field(default_factory=list)


class VersionConflict(Exception):
    pass


class InstanceStorage:
    """Versioned store (reference: ``instance_storage.py:31``): every
    upsert names the version it read; a mismatch is a conflict the
    caller retries against fresh state. Single-process here, but the
    contract keeps reconciler and decision code from clobbering each
    other's transitions."""

    def __init__(self):
        self._instances: dict[str, Instance] = {}
        self._ids = itertools.count(1)

    def create(self, resources: dict) -> Instance:
        inst = Instance(instance_id=f"i-{next(self._ids):05d}",
                        resources=dict(resources))
        inst.status_history.append((QUEUED, time.monotonic()))
        self._instances[inst.instance_id] = inst
        return inst

    def get(self, instance_id: str) -> Instance | None:
        return self._instances.get(instance_id)

    def delete(self, instance_id: str):
        self._instances.pop(instance_id, None)

    def list(self, statuses: tuple | None = None) -> list[Instance]:
        out = list(self._instances.values())
        if statuses is not None:
            out = [i for i in out if i.status in statuses]
        return out

    def update_status(self, instance_id: str, status: str,
                      expected_version: int, **fields) -> Instance:
        inst = self._instances[instance_id]
        if inst.version != expected_version:
            raise VersionConflict(
                f"{instance_id}: version {inst.version} != expected "
                f"{expected_version}")
        inst.status = status
        inst.version += 1
        inst.status_history.append((status, time.monotonic()))
        for k, v in fields.items():
            setattr(inst, k, v)
        return inst


class InstanceManager:
    """Decision intake + reconciliation over an InstanceStorage."""

    KEEP_TERMINATED = 128   # recent dead records kept for observability
    # a REQUESTED instance whose VM never appears (cloud quota, failed
    # resize) times out to TERMINATED so it stops counting toward the
    # cap and blocking further scale-up forever
    REQUEST_TIMEOUT_S = 600.0

    def __init__(self, provider):
        self.provider = provider
        self.storage = InstanceStorage()

    # -- decisions (the scaling policy calls these) --------------------

    def launch(self, resources: dict) -> Instance:
        return self.storage.create(resources)

    def terminate(self, node_id: str):
        for inst in self.storage.list(LIVE_STATES):
            if inst.node_id == node_id:
                self.storage.update_status(inst.instance_id, TERMINATING,
                                           inst.version)
                break
        self.provider.terminate_node(node_id)

    # -- views ----------------------------------------------------------

    def live_count(self) -> int:
        return len(self.storage.list(LIVE_STATES))

    def provisioning(self) -> list[Instance]:
        return self.storage.list((QUEUED, REQUESTED, ALLOCATED))

    # -- reconciliation --------------------------------------------------

    def reconcile(self, gcs_alive: set[str] | None = None):
        """One pass: push QUEUED launches to the provider, then converge
        records against the provider listing (ALLOCATED/TERMINATED) and
        the GCS alive set (RAY_RUNNING)."""
        gcs_alive = gcs_alive or set()
        for inst in self.storage.list((QUEUED,)):
            try:
                node_id = self.provider.create_node(dict(inst.resources))
            except Exception:  # noqa: BLE001 - cloud hiccup: retry next tick
                continue
            self.storage.update_status(
                inst.instance_id, REQUESTED, inst.version,
                node_id=node_id or None,
                requested_at=time.monotonic())
        provider_nodes = set(self.provider.non_terminated_nodes())
        unclaimed = provider_nodes - {
            i.node_id for i in self.storage.list(LIVE_STATES)
            if i.node_id}
        now = time.monotonic()
        for inst in self.storage.list((REQUESTED,)):
            if inst.node_id and inst.node_id in provider_nodes:
                self.storage.update_status(inst.instance_id, ALLOCATED,
                                           inst.version)
            elif (inst.requested_at is not None
                  and now - inst.requested_at > self.REQUEST_TIMEOUT_S):
                self.storage.update_status(
                    inst.instance_id, TERMINATED, inst.version,
                    terminated_at=now)
            elif not inst.node_id and unclaimed:
                # async providers (GKE) return no id at request time: the
                # next new provider node claims the oldest such request
                node_id = sorted(unclaimed)[0]
                unclaimed.discard(node_id)
                self.storage.update_status(inst.instance_id, ALLOCATED,
                                           inst.version, node_id=node_id)
        for inst in self.storage.list((ALLOCATED, RAY_RUNNING)):
            if inst.node_id not in provider_nodes:
                self.storage.update_status(
                    inst.instance_id, TERMINATED, inst.version,
                    terminated_at=time.monotonic())
            elif inst.status == ALLOCATED and inst.node_id in gcs_alive:
                self.storage.update_status(
                    inst.instance_id, RAY_RUNNING, inst.version,
                    running_at=time.monotonic())
        for inst in self.storage.list((TERMINATING,)):
            if inst.node_id not in provider_nodes:
                self.storage.update_status(
                    inst.instance_id, TERMINATED, inst.version,
                    terminated_at=time.monotonic())
        # prune old TERMINATED records: a long-running autoscaler churns
        # nodes for weeks, and keeping every dead record makes each
        # reconcile O(total-ever-launched) and memory unbounded — keep a
        # recent tail for observability
        dead = self.storage.list((TERMINATED,))
        if len(dead) > self.KEEP_TERMINATED:
            dead.sort(key=lambda i: i.terminated_at or 0.0)
            for inst in dead[:-self.KEEP_TERMINATED]:
                self.storage.delete(inst.instance_id)
        # ADOPT provider nodes nobody requested (pre-existing pool VMs,
        # out-of-band scale-ups): unrecorded capacity would make
        # live_count() undercount and the policy over-provision past its
        # cap
        for node_id in sorted(unclaimed):
            inst = self.storage.create({})
            self.storage.update_status(inst.instance_id, ALLOCATED,
                                       inst.version, node_id=node_id)
