"""Mixtral-family sparse MoE transformer (BASELINE.json config
"Mixtral 8x7B MoE with expert-parallel placement").

Same skeleton as the Llama family (stacked blocks + lax.scan, logical-axis
annotations) with the dense SwiGLU MLP replaced by a top-2 MoE FFN
(``ray_tpu.ops.moe``). Expert weights carry the "expert" logical axis →
the ``moe`` sharding preset maps it to the ``ep`` mesh axis and XLA emits
the token all-to-all.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import (
    attention_sublayer,
    cross_entropy_loss,
    fanin_init as _dense_init,
    num_params,  # noqa: F401 - re-exported for API parity with llama
)
from ray_tpu.ops.moe import moe_ffn
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import rope_sin_cos


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    head_dim: int = 128
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    rope_theta: float = 1000000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: str = "full"

    @property
    def param_dtype(self):
        return jnp.dtype(self.dtype)


def mixtral_8x7b() -> MixtralConfig:
    return MixtralConfig()


def mixtral_tiny(vocab_size: int = 512) -> MixtralConfig:
    return MixtralConfig(
        vocab_size=vocab_size, d_model=128, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=256, head_dim=32, n_experts=4, top_k=2,
        remat="none",
    )


def param_logical_axes(cfg: MixtralConfig) -> dict:
    block = {
        "attn_norm": (None, "embed"),
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "kv_heads"),
        "wv": (None, "embed", "kv_heads"),
        "wo": (None, "heads", "embed"),
        "mlp_norm": (None, "embed"),
        "router": (None, "embed", None),          # router stays replicated
        "wi_gate": (None, "expert", "embed", "mlp"),
        "wi_up": (None, "expert", "embed", "mlp"),
        "wo_e": (None, "expert", "mlp", "embed"),
    }
    return {
        "embedding": ("vocab", "embed"),
        "blocks": block,
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(cfg: MixtralConfig, key) -> dict:
    dt = cfg.param_dtype
    k_emb, k_blocks, k_head = jax.random.split(key, 3)
    d, l, e = cfg.d_model, cfg.n_layers, cfg.n_experts
    qdim = cfg.n_heads * cfg.head_dim
    kvdim = cfg.n_kv_heads * cfg.head_dim

    def dense(key, shape, fan_in, dtype=dt):
        return _dense_init(key, shape, fan_in).astype(dtype)

    ks = jax.random.split(k_blocks, 8)
    blocks = {
        "attn_norm": jnp.ones((l, d), dtype=dt),
        "wq": dense(ks[0], (l, d, qdim), d),
        "wk": dense(ks[1], (l, d, kvdim), d),
        "wv": dense(ks[2], (l, d, kvdim), d),
        "wo": dense(ks[3], (l, qdim, d), qdim),
        "mlp_norm": jnp.ones((l, d), dtype=dt),
        "router": dense(ks[4], (l, d, e), d, dtype=jnp.float32),
        "wi_gate": dense(ks[5], (l, e, d, cfg.d_ff), d),
        "wi_up": dense(ks[6], (l, e, d, cfg.d_ff), d),
        "wo_e": dense(ks[7], (l, e, cfg.d_ff, d), cfg.d_ff),
    }
    return {
        "embedding": dense(k_emb, (cfg.vocab_size, d), d),
        "blocks": blocks,
        "final_norm": jnp.ones((d,), dtype=dt),
        "lm_head": dense(k_head, (d, cfg.vocab_size), d),
    }


def _block(cfg: MixtralConfig, x, p, sin, cos, segment_ids, attn_impl):
    b, s, d = x.shape
    x = attention_sublayer(cfg, x, p, sin, cos, segment_ids, attn_impl)

    h = rms_norm(x, p["mlp_norm"], eps=cfg.rms_eps)
    flat = h.reshape(b * s, d)
    moe_out, aux = moe_ffn(
        flat, p["router"], p["wi_gate"], p["wi_up"], p["wo_e"],
        top_k=cfg.top_k, capacity_factor=cfg.capacity_factor,
    )
    x = x + moe_out.reshape(b, s, d)
    return x, aux


def forward(
    cfg: MixtralConfig,
    params: dict,
    tokens,
    *,
    segment_ids=None,
    attn_impl: str = "auto",
    return_aux_loss: bool = False,
):
    """Token ids -> logits [batch, seq, vocab] (fp32); optionally also the
    summed router load-balancing loss."""
    b, s = tokens.shape
    x = params["embedding"][tokens]
    positions = jnp.arange(s, dtype=jnp.int32)[None, :]
    sin, cos = rope_sin_cos(positions, cfg.head_dim, theta=cfg.rope_theta)

    body = partial(_block, cfg, sin=sin, cos=cos, segment_ids=segment_ids,
                   attn_impl=attn_impl)
    if cfg.remat == "full":
        body = jax.checkpoint(body)
    elif cfg.remat == "dots":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
        )
    elif cfg.remat != "none":
        raise ValueError(f"unknown remat policy {cfg.remat!r}")

    def scan_fn(x, layer_params):
        x, aux = body(x, layer_params)
        return x, aux

    x, aux_losses = lax.scan(scan_fn, x, params["blocks"])

    x = rms_norm(x, params["final_norm"], eps=cfg.rms_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    if return_aux_loss:
        return logits, jnp.sum(aux_losses) * cfg.aux_loss_weight
    return logits


def loss_fn(cfg, params, tokens, targets, *, mask=None):
    logits, aux = forward(cfg, params, tokens, return_aux_loss=True)
    return cross_entropy_loss(logits, targets, mask=mask) + aux
