"""Autoregressive decoding: KV cache, prefill/decode, sampling, generation.

TPU-first design (net-new capability vs the reference, which serves models
only through user code inside Serve replicas — `python/ray/serve/`, P15):

- One **unified cached forward** handles prefill (T=prompt) and decode (T=1):
  static shapes, per-sequence write offsets via vmapped dynamic slicing, so
  a single compiled program serves every step of continuous batching.
- The KV cache is slot-based: `[layers, max_batch, max_len, kv_heads, hd]`.
  A "slot" is one row of the batch; the serving engine (ray_tpu.serve.llm)
  assigns/frees slots as requests arrive/finish. All control flow that
  depends on which slots are live is expressed as masks, never Python
  branches — the decode program never recompiles.
- Layers run under `lax.scan` with the cache as scanned xs/ys, matching the
  stacked-block layout of `ray_tpu.models.llama`.
- Sampling (greedy/temperature/top-k/top-p) is jitted alongside the model
  so logits never leave HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import llama
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_sin_cos


@jax.tree_util.register_pytree_node_class
@dataclass
class KVCache:
    """Slot-based KV cache.

    k, v: [n_layers, max_batch, max_len, n_kv_heads, head_dim]
    lengths: [max_batch] int32 — tokens currently cached per slot.
    """

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    def tree_flatten(self):
        return (self.k, self.v, self.lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def max_batch(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(cfg, max_batch: int, max_len: int, dtype=None) -> KVCache:
    dtype = dtype or cfg.param_dtype
    shape = (cfg.n_layers, max_batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype=dtype),
        v=jnp.zeros(shape, dtype=dtype),
        lengths=jnp.zeros((max_batch,), dtype=jnp.int32),
    )


def lax_slice_row(arr, slot):
    """arr [L, B, ...] -> [L, 1, ...] at dynamic row `slot` (one cache
    slot's KV across all layers)."""
    start = (0, slot) + (0,) * (arr.ndim - 2)
    sizes = (arr.shape[0], 1) + arr.shape[2:]
    return lax.dynamic_slice(arr, start, sizes)


def lax_update_row(arr, row, slot):
    """Inverse of lax_slice_row: write row [L, 1, ...] back at `slot`."""
    start = (0, slot) + (0,) * (arr.ndim - 2)
    return lax.dynamic_update_slice(arr, row.astype(arr.dtype), start)


def _write_cache(cache_kv, new_kv, start):
    """Write new_kv [B, T, ...] into cache_kv [B, S, ...] at per-row offsets
    start [B]. vmapped dynamic_update_slice keeps shapes static."""

    def write_one(row_cache, row_new, s):
        return lax.dynamic_update_slice(
            row_cache, row_new.astype(row_cache.dtype), (s, 0, 0)
        )

    return jax.vmap(write_one)(cache_kv, new_kv, start)


def _cached_attention(q, k_cache, v_cache, start, *, scale):
    """q: [B, T, nh, hd]; caches [B, S, nkv, hd]; start [B] = offset of the
    first query token. Causal over the whole cache: query i attends to
    key positions <= start + i."""
    b, t, nh, hd = q.shape
    s = k_cache.shape[1]
    nkv = k_cache.shape[2]
    n_rep = nh // nkv
    # Grouped attention without materializing repeated KV: fold the
    # query heads as [B, T, nkv, n_rep, hd] and contract against the
    # cache directly — repeating K/V would multiply HBM traffic on the
    # hottest decode-step tensor by n_rep.
    qg = q.reshape(b, t, nkv, n_rep, hd)
    logits = jnp.einsum("bqgrd,bkgd->bgrqk", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    qpos = start[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # [B,T]
    kpos = jnp.arange(s, dtype=jnp.int32)                            # [S]
    mask = kpos[None, None, :] <= qpos[:, :, None]                   # [B,T,S]
    logits = jnp.where(mask[:, None, None, :, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs.astype(v_cache.dtype),
                     v_cache, preferred_element_type=jnp.float32)
    return out.reshape(b, t, nh, hd).astype(q.dtype)


def cached_forward(cfg, params, tokens, cache: KVCache, *,
                   start=None, logits_mode: str = "last", logits_idx=None):
    """Run the transformer over `tokens` [B, T] against/through the cache.

    start [B]: write offset per row (defaults to cache.lengths). The cache
    rows are updated in place (functionally); `cache.lengths` is NOT
    advanced here — the caller owns slot bookkeeping (so speculative or
    masked steps stay possible).

    Returns (logits, new_cache); logits_mode:
      "last"  -> [B, vocab] at position T-1 (decode steps)
      "index" -> [B, vocab] at per-row position logits_idx [B] (prefill of
                 right-padded prompts: idx = prompt_len - 1). Keeps memory
                 at O(d_model), not O(vocab*T).
      "all"   -> [B, T, vocab]

    Reference analog: none — the reference delegates model execution to
    user frameworks inside replicas (SURVEY.md P15); this is the TPU-native
    serving compute path.
    """
    b, t = tokens.shape
    if start is None:
        start = cache.lengths
    x = params["embedding"][tokens]
    positions = start[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    sin, cos = rope_sin_cos(positions, cfg.head_dim, theta=cfg.rope_theta)
    scale = cfg.head_dim ** -0.5

    def block(x, xs):
        p, k_cache, v_cache = xs
        h = rms_norm(x, p["attn_norm"], eps=cfg.rms_eps)
        q = (h @ p["wq"]).reshape(b, t, cfg.n_heads, cfg.head_dim)
        k = (h @ p["wk"]).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ p["wv"]).reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        k_cache = _write_cache(k_cache, k, start)
        v_cache = _write_cache(v_cache, v, start)
        attn = _cached_attention(q, k_cache, v_cache, start, scale=scale)
        x = x + attn.reshape(b, t, cfg.n_heads * cfg.head_dim) @ p["wo"]
        h = rms_norm(x, p["mlp_norm"], eps=cfg.rms_eps)
        gated = jax.nn.silu(h @ p["w_gate"]) * (h @ p["w_up"])
        x = x + gated @ p["w_down"]
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(
        block, x, (params["blocks"], cache.k, cache.v)
    )
    x = rms_norm(x, params["final_norm"], eps=cfg.rms_eps)
    head = llama.lm_head_weights(cfg, params)
    if logits_mode == "last":
        x = x[:, -1, :]
    elif logits_mode == "index":
        x = jnp.take_along_axis(x, logits_idx[:, None, None], axis=1)
        x = x.squeeze(1)
    if logits_mode in ("last", "index"):
        logits = jnp.einsum("bd,dv->bv", x, head,
                            preferred_element_type=jnp.float32)
    else:
        logits = jnp.einsum("bsd,dv->bsv", x, head,
                            preferred_element_type=jnp.float32)
    return logits, KVCache(k=new_k, v=new_v, lengths=cache.lengths)


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------

def select_tokens(logits, temps, key):
    """The serving engines' per-slot token choice: greedy at temp 0,
    temperature-scaled categorical otherwise. ONE implementation — the
    dense and paged engines' decode/prefill programs all call this, and
    their exact-token-equality contract depends on it staying shared."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(
        jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 1.0
    top_k: int = 0          # 0 = disabled
    top_p: float = 1.0      # 1.0 = disabled
    max_new_tokens: int = 128


def sample(logits, key, params: SamplingParams):
    """logits [B, V] -> token ids [B]. temperature==0 means greedy."""
    if params.temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(params.temperature, 1e-6)
    if params.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -params.top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with mass >= top_p (always >= 1 token)
        cutoff_idx = jnp.sum(cum < params.top_p, axis=-1)
        cutoff = jnp.take_along_axis(
            sorted_logits, cutoff_idx[:, None], axis=-1
        )
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Whole-batch generation (offline / eval path)
# ---------------------------------------------------------------------------

def generate(cfg, params, prompts, *, key=None,
             sampling: SamplingParams | None = None,
             eos_id: int | None = None, pad_id: int = 0):
    """Batch generation: prompts [B, P] (right-padded with pad_id; actual
    lengths inferred), returns tokens [B, max_new_tokens] (pad_id after eos).

    Everything after prefill is one `lax.scan` — the whole decode loop is a
    single XLA program.
    """
    sampling = sampling or SamplingParams()
    key = key if key is not None else jax.random.key(0)
    b, p = prompts.shape
    # length = 1 + last non-pad POSITION (not a count): a valid interior
    # token equal to pad_id must not shorten the prompt
    positions = jnp.arange(p, dtype=jnp.int32)[None, :]
    prompt_lens = jnp.max(
        jnp.where(prompts != pad_id, positions + 1, 0), axis=1)
    prompt_lens = jnp.maximum(prompt_lens, 1)
    max_len = p + sampling.max_new_tokens
    cache = init_cache(cfg, b, max_len)

    # logits at position len-1 predict the first new token
    last, cache = cached_forward(
        cfg, params, prompts, cache, start=jnp.zeros((b,), jnp.int32),
        logits_mode="index", logits_idx=prompt_lens - 1,
    )
    key, sub = jax.random.split(key)
    first = sample(last, sub, sampling)
    cache = KVCache(k=cache.k, v=cache.v, lengths=prompt_lens)

    def step(carry, key_t):
        cache, tok, done = carry
        logits, cache = cached_forward(
            cfg, params, tok[:, None], cache, logits_mode="last"
        )
        nxt = sample(logits, key_t, sampling)
        nxt = jnp.where(done, pad_id, nxt)
        if eos_id is not None:
            done = done | (nxt == eos_id)
        cache = KVCache(k=cache.k, v=cache.v, lengths=cache.lengths + 1)
        return (cache, nxt, done), nxt

    done0 = (first == eos_id) if eos_id is not None else jnp.zeros((b,), bool)
    keys = jax.random.split(key, max(sampling.max_new_tokens - 1, 1))
    (_, _, _), rest = lax.scan(step, (cache, first, done0), keys[: sampling.max_new_tokens - 1])
    out = jnp.concatenate([first[:, None], rest.T], axis=1)
    return out


generate_jit = jax.jit(
    generate, static_argnums=(0,), static_argnames=("sampling", "eos_id", "pad_id")
)
