"""Llama-3-family transformer, TPU-first.

Flagship model of the framework (BASELINE.json config "Llama-3 8B/70B").
Design (deliberately NOT a port of any torch module tree):

- Pure functional: params are a pytree of arrays; a parallel pytree of
  *logical axis names* feeds ``ray_tpu.parallel.sharding`` so any strategy
  preset (fsdp / tp / fsdp_tp / fsdp_tp_sp) shards the same model without
  touching model code.
- All transformer blocks are stacked into single arrays with a leading
  ``layer`` axis and the forward pass runs ``lax.scan`` over them: one
  compiled block body regardless of depth (fast XLA compiles at 32-80
  layers), and the natural hook for per-layer rematerialization and
  pipeline-stage splitting.
- bf16 params/activations by default, fp32 for softmax/norm statistics and
  the final logits; matmuls via MXU with ``preferred_element_type=f32``
  where accuracy matters.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_sin_cos


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    head_dim: int = 128
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"
    # remat policy for the scan body:
    #   "none"      - save all activations (most HBM, no recompute)
    #   "full"      - save only layer inputs (least HBM, full recompute)
    #   "dots"      - save matmul outputs (recompute elementwise only)
    #   "attn"      - save only the attention OUTPUT: the backward never
    #                 re-runs the flash-attention forward — the known
    #                 lever for long-context MFU where attention
    #                 dominates (policy: save_only_these_names)
    #   "dots_attn" - dots + the attention output (skips both matmul and
    #                 flash-fwd recompute; elementwise-only recompute)
    remat: str = "full"
    tie_embeddings: bool = False

    @property
    def param_dtype(self):
        return jnp.dtype(self.dtype)


def llama3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama3_70b() -> LlamaConfig:
    return LlamaConfig(d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                       d_ff=28672)


def llama_tiny(vocab_size: int = 512) -> LlamaConfig:
    """Test-size config: runs in seconds on the 8-device CPU mesh."""
    return LlamaConfig(
        vocab_size=vocab_size, d_model=128, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=256, head_dim=32, remat="none",
    )


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def param_logical_axes(cfg: LlamaConfig) -> dict:
    """Logical axis annotation pytree, mirroring init_params' structure.
    The leading scan axis of stacked blocks carries the ``layers`` logical
    axis: replicated under dp/fsdp/tp presets (rules.layers=None) and
    sharded over ``pp`` under the pipeline-parallel preset, which makes the
    contiguous per-stage layer groups land on their stage's devices."""
    block = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),       # [L, D, H*hd]
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    axes = {
        "embedding": ("vocab", "embed"),
        "blocks": block,
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def fanin_init(key, shape, fan_in):
    """Fan-in-scaled normal init in fp32 (cast to param dtype at call sites).
    Shared by all model families."""
    scale = fan_in ** -0.5
    return jax.random.normal(key, shape, dtype=jnp.float32) * scale


def init_params(cfg: LlamaConfig, key) -> dict:
    """Initialize the parameter pytree (stacked-block layout)."""
    dt = cfg.param_dtype
    k_emb, k_blocks, k_head = jax.random.split(key, 3)
    d, l = cfg.d_model, cfg.n_layers
    qdim = cfg.n_heads * cfg.head_dim
    kvdim = cfg.n_kv_heads * cfg.head_dim

    def dense_init(key, shape, fan_in):
        return fanin_init(key, shape, fan_in).astype(dt)

    ks = jax.random.split(k_blocks, 7)
    blocks = {
        "attn_norm": jnp.ones((l, d), dtype=dt),
        "wq": dense_init(ks[0], (l, d, qdim), d),
        "wk": dense_init(ks[1], (l, d, kvdim), d),
        "wv": dense_init(ks[2], (l, d, kvdim), d),
        "wo": dense_init(ks[3], (l, qdim, d), qdim),
        "mlp_norm": jnp.ones((l, d), dtype=dt),
        "w_gate": dense_init(ks[4], (l, d, cfg.d_ff), d),
        "w_up": dense_init(ks[5], (l, d, cfg.d_ff), d),
        "w_down": dense_init(ks[6], (l, cfg.d_ff, d), cfg.d_ff),
    }
    params = {
        "embedding": dense_init(k_emb, (cfg.vocab_size, d), d),
        "blocks": blocks,
        "final_norm": jnp.ones((d,), dtype=dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(k_head, (d, cfg.vocab_size), d)
    return params


def num_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def attention_sublayer(cfg, x, p, sin, cos, segment_ids, attn_impl,
                       mesh=None, sp_axis="sp"):
    """Pre-norm attention sublayer (shared by Llama and Mixtral blocks).
    Returns the residual-added stream."""
    b, s, d = x.shape
    h = rms_norm(x, p["attn_norm"], eps=cfg.rms_eps)
    q = (h @ p["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (h @ p["wk"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ p["wv"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, sin, cos)
    k = apply_rope(k, sin, cos)
    if attn_impl == "ring":
        from ray_tpu.parallel.ring_attention import ring_attention

        if mesh is None:
            raise ValueError(
                "attn_impl='ring' requires mesh= (and an sp mesh axis)"
            )
        if segment_ids is not None:
            raise ValueError("ring attention does not support segment_ids yet")
        attn_out = ring_attention(q, k, v, mesh=mesh, axis=sp_axis,
                                  causal=True)
    else:
        attn_out = attention(q, k, v, causal=True, segment_ids=segment_ids,
                             impl=attn_impl)
    # checkpoint naming for the "attn"/"dots_attn" remat policies lives
    # INSIDE the attention impls (flash names its kernel residuals in
    # _flash_vjp_fwd; the reference impl names its output in
    # ops/attention.py) — naming the post-reshape copy here too would
    # double-store ~b*s*d per layer under those policies.
    attn_out = attn_out.reshape(b, s, cfg.n_heads * cfg.head_dim)
    return x + attn_out @ p["wo"]


def _block(cfg: LlamaConfig, x, layer_params, sin, cos, segment_ids,
           attn_impl, mesh=None, sp_axis="sp"):
    """One transformer block: pre-norm attention + SwiGLU MLP."""
    p = layer_params
    x = attention_sublayer(cfg, x, p, sin, cos, segment_ids, attn_impl,
                           mesh=mesh, sp_axis=sp_axis)
    h = rms_norm(x, p["mlp_norm"], eps=cfg.rms_eps)
    gated = jax.nn.silu(h @ p["w_gate"]) * (h @ p["w_up"])
    x = x + gated @ p["w_down"]
    return x


def forward(
    cfg: LlamaConfig,
    params: dict,
    tokens,             # [batch, seq] int32
    *,
    positions=None,     # [batch, seq] int32 (defaults to arange)
    segment_ids=None,   # [batch, seq] for packed sequences
    attn_impl: str = "auto",
    mesh=None,          # required for attn_impl="ring" (sequence parallel)
    sp_axis: str = "sp",
):
    """Token ids -> logits [batch, seq, vocab] (fp32)."""
    x = forward_hidden(cfg, params, tokens, positions=positions,
                       segment_ids=segment_ids, attn_impl=attn_impl,
                       mesh=mesh, sp_axis=sp_axis)
    logits = jnp.einsum("bsd,dv->bsv", x, lm_head_weights(cfg, params),
                        preferred_element_type=jnp.float32)
    return logits


def forward_hidden(cfg, params, tokens, *, positions=None,
                   segment_ids=None, attn_impl="auto", mesh=None,
                   sp_axis="sp"):
    """Token ids -> final normalized hidden states [b, s, d] (the input
    to the LM head). Split out so losses can fuse the head projection."""
    b, s = tokens.shape
    x = params["embedding"][tokens]
    if positions is None:
        positions = jnp.arange(s, dtype=jnp.int32)[None, :]
    sin, cos = rope_sin_cos(positions, cfg.head_dim, theta=cfg.rope_theta)

    body = partial(_block, cfg, sin=sin, cos=cos, segment_ids=segment_ids,
                   attn_impl=attn_impl, mesh=mesh, sp_axis=sp_axis)
    if cfg.remat == "full":
        body = jax.checkpoint(body)
    elif cfg.remat == "dots":
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        )
    elif cfg.remat == "attn":
        # "attn_lse" must be saved WITH the output: both are flash-bwd
        # residuals — with them saved, remat DCE drops the flash-forward
        # call from the backward entirely
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out", "attn_lse"),
        )
    elif cfg.remat == "dots_attn":
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
                jax.checkpoint_policies.save_only_these_names(
                    "attn_out", "attn_lse"),
            ),
        )

    def scan_fn(x, layer_params):
        return body(x, layer_params), None

    x, _ = lax.scan(scan_fn, x, params["blocks"])
    return rms_norm(x, params["final_norm"], eps=cfg.rms_eps)


def lm_head_weights(cfg, params):
    """The LM head matrix [d, vocab] honoring tie_embeddings — the ONE
    place tied-embedding semantics live."""
    return (params["embedding"].T if cfg.tie_embeddings
            else params["lm_head"])


def fused_cross_entropy(cfg, params, hidden, targets, *, mask=None,
                        chunk: int = 1024, z_loss: float = 0.0):
    """CE loss WITHOUT materializing the full [b, s, vocab] fp32 logits
    (2+ GB at 8x2048x32k): the LM-head matmul + logsumexp run per
    sequence chunk inside a checkpointed scan, so peak memory is one
    chunk of logits and the backward recomputes them. This is the
    standard fused-softmax-xent trade: ~2x head FLOPs for ~vocab/chunk x
    less logits HBM traffic.

    hidden: [b, s, d] from forward_hidden; targets [b, s] int; mask
    [b, s] in {0,1}.
    """
    head = lm_head_weights(cfg, params)
    b, s, d = hidden.shape
    n = b * s
    xm = hidden.reshape(n, d)
    tg = jnp.maximum(targets.reshape(n), 0)
    # mask=None derives the mask from the -1 padding convention (same
    # contract as the trainer's dense path) — silently averaging padding
    # in as class-0 predictions would be a wrong loss with no error
    mk = ((targets.reshape(n) >= 0).astype(jnp.float32) if mask is None
          else mask.reshape(n).astype(jnp.float32))
    # pad to a whole number of chunks (padding masked out)
    pad = (-n) % chunk
    if pad:
        xm = jnp.concatenate([xm, jnp.zeros((pad, d), xm.dtype)])
        tg = jnp.concatenate([tg, jnp.zeros((pad,), tg.dtype)])
        mk = jnp.concatenate([mk, jnp.zeros((pad,), mk.dtype)])
    n_chunks = (n + pad) // chunk
    xc = xm.reshape(n_chunks, chunk, d)
    tc = tg.reshape(n_chunks, chunk)
    mc = mk.reshape(n_chunks, chunk)

    def body(carry, inp):
        x_i, t_i, m_i = inp
        logits = jnp.einsum("cd,dv->cv", x_i, head,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, t_i[:, None], axis=1).squeeze(-1)
        nll = lse - tl
        if z_loss > 0.0:
            nll = nll + z_loss * jnp.square(lse)
        total, count = carry
        return (total + jnp.sum(nll * m_i), count + jnp.sum(m_i)), None

    (total, count), _ = lax.scan(
        jax.checkpoint(body), (jnp.float32(0.0), jnp.float32(0.0)),
        (xc, tc, mc))
    return total / jnp.maximum(count, 1.0)


def cross_entropy_loss(logits, targets, *, mask=None, z_loss: float = 0.0):
    """Token-level CE in fp32 with optional z-loss regularizer.

    ``mask`` [batch, seq] in {0,1} excludes padding from the mean.
    """
    logits = logits.astype(jnp.float32)
    logsumexp = jax.nn.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1
    ).squeeze(-1)
    nll = logsumexp - target_logit
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(logsumexp)
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)
