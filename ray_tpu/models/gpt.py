"""GPT-2-family transformer, TPU-first.

Second decoder-only family next to Llama (reference parity: the
reference trains GPT-class models through Train integrations, e.g. the
GPT-J DeepSpeed example under ``train/examples/deepspeed/`` — here the
family is in-framework). Architecture: learned absolute position
embeddings, pre-LN LayerNorm blocks with biases, standard multi-head
attention (no GQA), GELU MLP, tied LM head.

Same TPU conventions as ``models/llama.py``: stacked per-layer arrays
scanned with ``lax.scan`` (one compiled block body at any depth), a
parallel logical-axis pytree so every sharding preset applies unchanged,
bf16 params with fp32 norm statistics and logits.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import cross_entropy_loss, fanin_init, num_params
from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import layer_norm

__all__ = ["GPTConfig", "gpt2_small", "gpt2_xl", "gpt_tiny",
           "param_logical_axes", "init_params", "forward",
           "cross_entropy_loss", "num_params"]


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    ln_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: str = "none"           # "none" | "full"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def param_dtype(self):
        return jnp.dtype(self.dtype)


def gpt2_small() -> GPTConfig:
    return GPTConfig()


def gpt2_xl() -> GPTConfig:
    return GPTConfig(d_model=1600, n_layers=48, n_heads=25, d_ff=6400,
                     remat="full")


def gpt_tiny(vocab_size: int = 512) -> GPTConfig:
    """Test-size config: seconds on the 8-device CPU mesh."""
    return GPTConfig(vocab_size=vocab_size, max_seq_len=128, d_model=128,
                     n_layers=2, n_heads=4, d_ff=256)


def param_logical_axes(cfg: GPTConfig) -> dict:
    """Logical-axis pytree mirroring ``init_params`` (consumed by
    ``ray_tpu.parallel.sharding`` presets, same names as Llama's)."""
    block = {
        "ln1_w": ("layers", "embed"),
        "ln1_b": ("layers", "embed"),
        "wqkv": ("layers", "embed", "heads"),
        "bqkv": ("layers", "heads"),
        "wo": ("layers", "heads", "embed"),
        "bo": ("layers", "embed"),
        "ln2_w": ("layers", "embed"),
        "ln2_b": ("layers", "embed"),
        "w_up": ("layers", "embed", "mlp"),
        "b_up": ("layers", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
        "b_down": ("layers", "embed"),
    }
    return {
        "embedding": ("vocab", "embed"),
        "pos_embedding": (None, "embed"),
        "blocks": block,
        "final_ln_w": ("embed",),
        "final_ln_b": ("embed",),
    }


def init_params(cfg: GPTConfig, key) -> dict:
    dt = cfg.param_dtype
    d, l = cfg.d_model, cfg.n_layers
    k_emb, k_pos, k_blocks = jax.random.split(key, 3)

    def dense(k, shape, fan_in):
        return fanin_init(k, shape, fan_in).astype(dt)

    ks = jax.random.split(k_blocks, 4)
    blocks = {
        "ln1_w": jnp.ones((l, d), dtype=dt),
        "ln1_b": jnp.zeros((l, d), dtype=dt),
        "wqkv": dense(ks[0], (l, d, 3 * d), d),
        "bqkv": jnp.zeros((l, 3 * d), dtype=dt),
        "wo": dense(ks[1], (l, d, d), d),
        "bo": jnp.zeros((l, d), dtype=dt),
        "ln2_w": jnp.ones((l, d), dtype=dt),
        "ln2_b": jnp.zeros((l, d), dtype=dt),
        "w_up": dense(ks[2], (l, d, cfg.d_ff), d),
        "b_up": jnp.zeros((l, cfg.d_ff), dtype=dt),
        "w_down": dense(ks[3], (l, cfg.d_ff, d), cfg.d_ff),
        "b_down": jnp.zeros((l, d), dtype=dt),
    }
    return {
        "embedding": dense(k_emb, (cfg.vocab_size, d), d),
        "pos_embedding": (fanin_init(k_pos, (cfg.max_seq_len, d), d)
                          .astype(dt) * 0.1),
        "blocks": blocks,
        "final_ln_w": jnp.ones((d,), dtype=dt),
        "final_ln_b": jnp.zeros((d,), dtype=dt),
    }


def _block(cfg: GPTConfig, x, p, segment_ids, attn_impl):
    b, s, d = x.shape
    h = layer_norm(x, p["ln1_w"], p["ln1_b"], eps=cfg.ln_eps)
    qkv = h @ p["wqkv"] + p["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.n_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.n_heads, cfg.head_dim)
    attn_out = attention(q, k, v, causal=True, segment_ids=segment_ids,
                         impl=attn_impl)
    attn_out = attn_out.reshape(b, s, d)
    x = x + attn_out @ p["wo"] + p["bo"]
    h = layer_norm(x, p["ln2_w"], p["ln2_b"], eps=cfg.ln_eps)
    up = jax.nn.gelu(h @ p["w_up"] + p["b_up"])
    return x + up @ p["w_down"] + p["b_down"]


def forward(cfg: GPTConfig, params: dict, tokens, *, positions=None,
            segment_ids=None, attn_impl: str = "auto"):
    """Token ids [b, s] -> logits [b, s, vocab] (fp32, tied head)."""
    b, s = tokens.shape
    if s > cfg.max_seq_len:
        # learned absolute positions clamp OOB gathers silently; reject
        raise ValueError(
            f"sequence length {s} exceeds max_seq_len={cfg.max_seq_len}")
    if positions is None:
        positions = jnp.arange(s, dtype=jnp.int32)[None, :]
    x = params["embedding"][tokens] + params["pos_embedding"][positions]

    body = partial(_block, cfg, segment_ids=segment_ids,
                   attn_impl=attn_impl)
    if cfg.remat == "full":
        body = jax.checkpoint(body)

    def scan_fn(x, layer_params):
        return body(x, layer_params), None

    x, _ = lax.scan(scan_fn, x, params["blocks"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"],
                   eps=cfg.ln_eps)
    return jnp.einsum("bsd,vd->bsv", x, params["embedding"],
                      preferred_element_type=jnp.float32)
