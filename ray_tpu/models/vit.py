"""Vision Transformer (the ViT-L / CLIP-vision BASELINE model family).

TPU-first design mirroring the llama module's conventions: stacked-layer
parameter arrays scanned with ``lax.scan`` (the ``layers`` logical axis
makes the stack pp-shardable), logical-axis annotations for GSPMD
sharding via ``parallel/sharding.py`` rules, fp32 statistics inside
bf16-friendly compute, and patch embedding expressed as ONE matmul
([B, N, P·P·C] @ [P·P·C, D]) instead of a conv — XLA maps it straight
onto the MXU.

Reference analog: the torchvision/timm ViT models the reference's AIR
examples fine-tune (e.g. ``python/ray/train`` image examples); there is
no first-party ViT in the reference — this is the TPU-native equivalent
the BASELINE's "ViT-L / CLIP multimodal (image pipeline → TPU)" config
needs.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import fanin_init
from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import layer_norm


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    n_classes: int = 1000
    ln_eps: float = 1e-6
    param_dtype: object = jnp.float32
    pool: str = "cls"            # "cls" token or "mean" pooling

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels


def vit_tiny(image_size: int = 32, patch_size: int = 8,
             n_classes: int = 10) -> ViTConfig:
    """Test-size config: runs in seconds on the 8-device CPU mesh."""
    return ViTConfig(image_size=image_size, patch_size=patch_size,
                     d_model=64, n_layers=2, n_heads=4, d_ff=128,
                     n_classes=n_classes)


def vit_l16() -> ViTConfig:
    """ViT-L/16 (the BASELINE's ViT-L)."""
    return ViTConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def param_logical_axes(cfg: ViTConfig) -> dict:
    block = {
        "ln1_w": ("layers", "embed"),
        "ln1_b": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "heads"),
        "wv": ("layers", "embed", "heads"),
        "wo": ("layers", "heads", "embed"),
        "ln2_w": ("layers", "embed"),
        "ln2_b": ("layers", "embed"),
        "w_up": ("layers", "embed", "mlp"),
        "b_up": ("layers", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
        "b_down": ("layers", "embed"),
    }
    return {
        "patch_embed": (None, "embed"),
        "patch_bias": ("embed",),
        "pos_embed": (None, "embed"),
        "cls_token": ("embed",),
        "blocks": block,
        "final_ln_w": ("embed",),
        "final_ln_b": ("embed",),
        "head": ("embed", "vocab"),
        "head_bias": ("vocab",),
    }


def init_params(cfg: ViTConfig, key) -> dict:
    dt = cfg.param_dtype
    d, l = cfg.d_model, cfg.n_layers
    ks = jax.random.split(key, 10)

    def dense(k, shape, fan_in):
        return fanin_init(k, shape, fan_in).astype(dt)

    blocks = {
        "ln1_w": jnp.ones((l, d), dt),
        "ln1_b": jnp.zeros((l, d), dt),
        "wq": dense(ks[0], (l, d, d), d),
        "wk": dense(ks[1], (l, d, d), d),
        "wv": dense(ks[2], (l, d, d), d),
        "wo": dense(ks[3], (l, d, d), d),
        "ln2_w": jnp.ones((l, d), dt),
        "ln2_b": jnp.zeros((l, d), dt),
        "w_up": dense(ks[4], (l, d, cfg.d_ff), d),
        "b_up": jnp.zeros((l, cfg.d_ff), dt),
        "w_down": dense(ks[5], (l, cfg.d_ff, d), cfg.d_ff),
        "b_down": jnp.zeros((l, d), dt),
    }
    return {
        "patch_embed": dense(ks[6], (cfg.patch_dim, d), cfg.patch_dim),
        "patch_bias": jnp.zeros((d,), dt),
        "pos_embed": (jax.random.normal(
            ks[7], (cfg.n_patches + 1, d)) * 0.02).astype(dt),
        "cls_token": (jax.random.normal(ks[8], (d,)) * 0.02).astype(dt),
        "blocks": blocks,
        "final_ln_w": jnp.ones((d,), dt),
        "final_ln_b": jnp.zeros((d,), dt),
        "head": dense(ks[9], (d, cfg.n_classes), d),
        "head_bias": jnp.zeros((cfg.n_classes,), dt),
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def patchify(cfg: ViTConfig, images):
    """[B, H, W, C] -> [B, N, P·P·C]: reshape-only (no conv needed)."""
    b, h, w, c = images.shape
    p = cfg.patch_size
    if h != cfg.image_size or w != cfg.image_size or c != cfg.channels:
        raise ValueError(
            f"expected [{cfg.image_size},{cfg.image_size},{cfg.channels}] "
            f"images, got {images.shape[1:]}")
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)          # [B, hp, wp, p, p, c]
    return x.reshape(b, cfg.n_patches, cfg.patch_dim)


def _block(cfg: ViTConfig, x, p):
    """Pre-LN encoder block: MHA + GELU MLP, both with residuals."""
    b, s, d = x.shape
    h = layer_norm(x, p["ln1_w"], p["ln1_b"], eps=cfg.ln_eps)
    q = (h @ p["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (h @ p["wk"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    v = (h @ p["wv"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    attn = attention(q, k, v, causal=False, impl="reference")
    x = x + attn.reshape(b, s, d) @ p["wo"]
    h = layer_norm(x, p["ln2_w"], p["ln2_b"], eps=cfg.ln_eps)
    h = jax.nn.gelu(h @ p["w_up"] + p["b_up"])
    return x + (h @ p["w_down"] + p["b_down"])


def forward(cfg: ViTConfig, params: dict, images):
    """Images [B, H, W, C] (float; caller normalizes) -> logits
    [B, n_classes] (fp32)."""
    x = patchify(cfg, images).astype(params["patch_embed"].dtype)
    x = x @ params["patch_embed"] + params["patch_bias"]   # [B, N, D]
    b = x.shape[0]
    cls = jnp.broadcast_to(params["cls_token"], (b, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"]

    body = partial(_block, cfg)

    def scan_fn(x, layer_params):
        return body(x, layer_params), None

    x, _ = lax.scan(scan_fn, x, params["blocks"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"],
                   eps=cfg.ln_eps)
    pooled = x[:, 0] if cfg.pool == "cls" else x.mean(axis=1)
    return jnp.einsum("bd,dc->bc", pooled, params["head"],
                      preferred_element_type=jnp.float32) \
        + params["head_bias"].astype(jnp.float32)


def num_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
