"""BERT-family bidirectional encoder, TPU-first.

Encoder model family next to the decoder families (llama/gpt) and
vision (vit) — covers masked-LM pretraining and sequence embedding
(reference parity: the reference trains BERT-class models through its
Train/Transformers integrations; here the family is in-framework).

Architecture: learned absolute positions + token-type embeddings,
post-LN transformer blocks (the original BERT residual order), GELU
MLP, weight-tied MLM head over the final hidden states. Same TPU
conventions as the other families: stacked per-layer arrays under one
``lax.scan`` body, a logical-axis pytree for the sharding presets, bf16
params with fp32 norms/logits.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import fanin_init, num_params
from ray_tpu.ops.attention import attention
from ray_tpu.ops.norms import layer_norm

__all__ = ["BertConfig", "bert_base", "bert_large", "bert_tiny",
           "param_logical_axes", "init_params", "encode", "mlm_logits",
           "mlm_loss", "num_params"]


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_seq_len: int = 512
    type_vocab_size: int = 2
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    ln_eps: float = 1e-12
    dtype: str = "bfloat16"
    remat: str = "none"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def param_dtype(self):
        return jnp.dtype(self.dtype)


def bert_base() -> BertConfig:
    return BertConfig()


def bert_large() -> BertConfig:
    return BertConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096,
                      remat="full")


def bert_tiny(vocab_size: int = 512) -> BertConfig:
    return BertConfig(vocab_size=vocab_size, max_seq_len=128, d_model=128,
                      n_layers=2, n_heads=4, d_ff=256)


def param_logical_axes(cfg: BertConfig) -> dict:
    block = {
        "wqkv": ("layers", "embed", "heads"),
        "bqkv": ("layers", "heads"),
        "wo": ("layers", "heads", "embed"),
        "bo": ("layers", "embed"),
        "ln1_w": ("layers", "embed"),
        "ln1_b": ("layers", "embed"),
        "w_up": ("layers", "embed", "mlp"),
        "b_up": ("layers", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
        "b_down": ("layers", "embed"),
        "ln2_w": ("layers", "embed"),
        "ln2_b": ("layers", "embed"),
    }
    return {
        "embedding": ("vocab", "embed"),
        "pos_embedding": (None, "embed"),
        "type_embedding": (None, "embed"),
        "emb_ln_w": ("embed",),
        "emb_ln_b": ("embed",),
        "blocks": block,
        "mlm_dense_w": ("embed", "embed"),
        "mlm_dense_b": ("embed",),
        "mlm_ln_w": ("embed",),
        "mlm_ln_b": ("embed",),
        "mlm_bias": ("vocab",),
    }


def init_params(cfg: BertConfig, key) -> dict:
    dt = cfg.param_dtype
    d, l = cfg.d_model, cfg.n_layers
    keys = jax.random.split(key, 8)

    def dense(k, shape, fan_in):
        return fanin_init(k, shape, fan_in).astype(dt)

    blocks = {
        "wqkv": dense(keys[0], (l, d, 3 * d), d),
        "bqkv": jnp.zeros((l, 3 * d), dtype=dt),
        "wo": dense(keys[1], (l, d, d), d),
        "bo": jnp.zeros((l, d), dtype=dt),
        "ln1_w": jnp.ones((l, d), dtype=dt),
        "ln1_b": jnp.zeros((l, d), dtype=dt),
        "w_up": dense(keys[2], (l, d, cfg.d_ff), d),
        "b_up": jnp.zeros((l, cfg.d_ff), dtype=dt),
        "w_down": dense(keys[3], (l, cfg.d_ff, d), cfg.d_ff),
        "b_down": jnp.zeros((l, d), dtype=dt),
        "ln2_w": jnp.ones((l, d), dtype=dt),
        "ln2_b": jnp.zeros((l, d), dtype=dt),
    }
    return {
        "embedding": dense(keys[4], (cfg.vocab_size, d), d),
        "pos_embedding": dense(keys[5], (cfg.max_seq_len, d), d) * 0.1,
        "type_embedding": dense(keys[6], (cfg.type_vocab_size, d), d) * 0.1,
        "emb_ln_w": jnp.ones((d,), dtype=dt),
        "emb_ln_b": jnp.zeros((d,), dtype=dt),
        "blocks": blocks,
        "mlm_dense_w": dense(keys[7], (d, d), d),
        "mlm_dense_b": jnp.zeros((d,), dtype=dt),
        "mlm_ln_w": jnp.ones((d,), dtype=dt),
        "mlm_ln_b": jnp.zeros((d,), dtype=dt),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), dtype=jnp.float32),
    }


def _block(cfg: BertConfig, x, p, attn_mask, attn_impl):
    """Post-LN block: sublayer -> residual add -> LayerNorm."""
    b, s, d = x.shape
    qkv = x @ p["wqkv"] + p["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.n_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.n_heads, cfg.head_dim)
    # bidirectional attention; padding masked via segment_ids (pad
    # tokens get segment 0, real tokens 1 — cross-segment is masked)
    attn_out = attention(q, k, v, causal=False, segment_ids=attn_mask,
                         impl=attn_impl)
    attn_out = attn_out.reshape(b, s, d)
    x = layer_norm(x + attn_out @ p["wo"] + p["bo"],
                   p["ln1_w"], p["ln1_b"], eps=cfg.ln_eps)
    up = jax.nn.gelu(x @ p["w_up"] + p["b_up"])
    return layer_norm(x + up @ p["w_down"] + p["b_down"],
                      p["ln2_w"], p["ln2_b"], eps=cfg.ln_eps)


def encode(cfg: BertConfig, params: dict, tokens, *,
           attention_mask=None, token_type_ids=None,
           attn_impl: str = "auto"):
    """Token ids [b, s] -> contextual hidden states [b, s, d].

    ``attention_mask`` [b, s] in {0, 1} (1 = real token); padding can
    neither attend nor be attended to.
    """
    b, s = tokens.shape
    if s > cfg.max_seq_len:
        raise ValueError(
            f"sequence length {s} exceeds max_seq_len={cfg.max_seq_len}")
    positions = jnp.arange(s, dtype=jnp.int32)[None, :]
    x = params["embedding"][tokens] + params["pos_embedding"][positions]
    if token_type_ids is not None:
        x = x + params["type_embedding"][token_type_ids]
    x = layer_norm(x, params["emb_ln_w"], params["emb_ln_b"],
                   eps=cfg.ln_eps)

    seg = (attention_mask.astype(jnp.int32)
           if attention_mask is not None else None)
    body = partial(_block, cfg, attn_mask=seg, attn_impl=attn_impl)
    if cfg.remat == "full":
        body = jax.checkpoint(body)

    def scan_fn(x, layer_params):
        return body(x, layer_params), None

    x, _ = lax.scan(scan_fn, x, params["blocks"])
    return x


def mlm_logits(cfg: BertConfig, params: dict, hidden):
    """MLM head: dense+GELU+LN then the tied embedding matrix."""
    h = jax.nn.gelu(hidden @ params["mlm_dense_w"]
                    + params["mlm_dense_b"])
    h = layer_norm(h, params["mlm_ln_w"], params["mlm_ln_b"],
                   eps=cfg.ln_eps)
    return (jnp.einsum("bsd,vd->bsv", h, params["embedding"],
                       preferred_element_type=jnp.float32)
            + params["mlm_bias"])


def mlm_loss(cfg: BertConfig, params: dict, tokens, targets, *,
             attention_mask=None, loss_mask=None,
             attn_impl: str = "auto"):
    """Masked-LM cross entropy: ``targets`` are the ORIGINAL token ids;
    ``loss_mask`` [b, s] selects the masked positions the loss covers
    (the standard 15% MLM positions)."""
    hidden = encode(cfg, params, tokens, attention_mask=attention_mask,
                    attn_impl=attn_impl)
    logits = mlm_logits(cfg, params, hidden)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None],
                               axis=-1).squeeze(-1)
    if loss_mask is None:
        loss_mask = jnp.ones_like(nll)
    loss_mask = loss_mask.astype(jnp.float32)
    return (nll * loss_mask).sum() / jnp.maximum(loss_mask.sum(), 1.0)
