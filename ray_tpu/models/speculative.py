"""Greedy speculative decoding: draft proposes, target verifies.

Net-new TPU-native capability (the reference serves models only through
user code inside replicas — SURVEY.md P15). A small DRAFT model
proposes ``k`` tokens autoregressively; the TARGET model scores all
``k+1`` positions in ONE forward (prefill-shaped, MXU-friendly) and
commits the longest matching prefix plus its own next token. With
greedy acceptance the output is BIT-EXACT to the target's own greedy
decode, for any draft — a bad draft only costs speed, never
correctness. Wall-clock win ≈ (mean accepted + 1) target-forwards per
round amortized over one verify pass.

Everything is static-shaped and the whole loop is one
``lax.while_loop`` program:

- both KV caches advance by fixed-size chunk writes at per-row offsets
  (stale entries past the accepted length are simply overwritten next
  round — the slot convention of ``models.decoding.cached_forward``);
- after verification the committed chunk is re-fed to the draft in one
  (k+1)-token forward, which both repairs its cache to the committed
  prefix and appends the entry a fully-accepted round needs;
- per-row acceptance counts, EOS stops, and output writes are masks and
  ``dynamic_update_slice`` — no recompiles across rounds.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.decoding import KVCache, cached_forward, init_cache


def _prompt_lengths(prompts, pad_id):
    p = prompts.shape[1]
    positions = jnp.arange(p, dtype=jnp.int32)[None, :]
    lens = jnp.max(jnp.where(prompts != pad_id, positions + 1, 0), axis=1)
    return jnp.maximum(lens, 1)


def speculative_generate(cfg_t, params_t, cfg_d, params_d, prompts, *,
                         k_spec: int = 4, max_new_tokens: int = 128,
                         eos_id: int | None = None, pad_id: int = 0,
                         return_stats: bool = False):
    """Greedy decode of the TARGET model, accelerated by a draft.

    prompts [B, P] right-padded with ``pad_id``. Returns tokens
    [B, max_new_tokens] (``pad_id`` after EOS), plus
    ``{"rounds": int, "accepted": [B]}`` when ``return_stats``.
    Guarantee: identical to ``decoding.generate`` with
    ``SamplingParams(temperature=0, max_new_tokens=...)`` on the target.
    """
    b, p = prompts.shape
    k = k_spec
    prompt_lens = _prompt_lengths(prompts, pad_id)
    max_total = p + max_new_tokens + k + 2

    cache_t = init_cache(cfg_t, b, max_total)
    cache_d = init_cache(cfg_d, b, max_total)

    # Prefill both models; the target's last-position logits give the
    # first pending token (exactly like decoding.generate).
    zeros = jnp.zeros((b,), jnp.int32)
    logits_t, cache_t = cached_forward(
        cfg_t, params_t, prompts, cache_t, start=zeros,
        logits_mode="index", logits_idx=prompt_lens - 1)
    _, cache_d = cached_forward(
        cfg_d, params_d, prompts, cache_d, start=zeros,
        logits_mode="last")
    pending = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)

    # Both caches hold exactly the prompt; the invariant below is:
    # cache length m = committed-token count - 1, and `pending` is the
    # single committed-but-not-yet-fed token.
    m0 = prompt_lens
    out = jnp.full((b, max_new_tokens + k + 1), pad_id, dtype=jnp.int32)
    done0 = ((pending == eos_id) if eos_id is not None
             else jnp.zeros((b,), bool))
    # the pending first token is emitted immediately
    out = out.at[:, 0].set(pending)
    o0 = jnp.ones((b,), jnp.int32)
    state = (cache_t.k, cache_t.v, cache_d.k, cache_d.v, m0, pending,
             out, o0, done0, jnp.zeros((), jnp.int32),
             jnp.zeros((b,), jnp.int32))

    def cond(state):
        o, done = state[7], state[8]
        return jnp.any(~done & (o < max_new_tokens))

    def body(state):
        (kt, vt, kd, vd, m, t0, out, o, done, rounds, acc) = state
        cache_t = KVCache(k=kt, v=vt, lengths=m)
        cache_d = KVCache(k=kd, v=vd, lengths=m)

        # -- draft proposes k tokens, one at a time ------------------
        def draft_step(carry, j):
            tok, kd, vd = carry
            logits, cd = cached_forward(
                cfg_d, params_d, tok[:, None],
                KVCache(k=kd, v=vd, lengths=m + j), logits_mode="last")
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, cd.k, cd.v), nxt

        (_, kd, vd), draft_toks = lax.scan(
            draft_step, (t0, cache_d.k, cache_d.v),
            jnp.arange(k, dtype=jnp.int32))
        d = draft_toks.T                     # [B, k] proposals d1..dk

        # -- target verifies the whole chunk in one forward ----------
        chunk = jnp.concatenate([t0[:, None], d], axis=1)   # [B, k+1]
        logits_t, cache_t = cached_forward(
            cfg_t, params_t, chunk, cache_t, start=m, logits_mode="all")
        g = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)  # [B, k+1]

        # accepted prefix length n = leading i with d[:, i] == g[:, i]
        match = d == g[:, :k]
        n = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        # committed this round: d1..dn then the target's own token
        t0_new = jnp.take_along_axis(g, n[:, None], axis=1).squeeze(1)
        # emitted chunk [B, k+1]: positions <n -> accepted d, ==n -> the
        # target's own token at the first mismatch (or bonus)
        idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
        cand = jnp.where(idx < n[:, None],
                         jnp.pad(d, ((0, 0), (0, 1))), 0)
        cand = jnp.where(idx == n[:, None], t0_new[:, None], cand)

        advance = n + 1
        if eos_id is not None:
            is_eos = (cand == eos_id) & (idx <= n[:, None])
            eos_pos = jnp.where(
                jnp.any(is_eos, axis=1),
                jnp.argmax(is_eos, axis=1), k + 1).astype(jnp.int32)
            advance = jnp.minimum(advance, eos_pos + 1)
            newly_done = jnp.any(is_eos, axis=1)
        else:
            newly_done = jnp.zeros((b,), bool)
        cand = jnp.where(idx < advance[:, None], cand, pad_id)
        done_at_entry = done   # rows already finished BEFORE this round
        advance = jnp.where(done_at_entry, 0, advance)
        cand = jnp.where(done_at_entry[:, None], pad_id, cand)

        # -- write the chunk into the output at per-row offsets ------
        def write_row(row, chunk_row, off):
            return lax.dynamic_update_slice(row, chunk_row, (off,))

        out = jax.vmap(write_row)(out, cand, o)
        o_new = jnp.minimum(o + advance, max_new_tokens + k + 1)
        done = done | newly_done | (o_new >= max_new_tokens)

        # -- repair/extend the draft cache with the committed chunk --
        _, cache_d = cached_forward(
            cfg_d, params_d, chunk,
            KVCache(k=kd, v=vd, lengths=m), start=m, logits_mode="last")

        m_new = jnp.where(advance > 0, m + advance, m)
        t0 = jnp.where(advance > 0, t0_new, t0)
        # count acceptances for rows ACTIVE at round entry — masking
        # with the updated `done` would drop each row's final round
        acc = acc + jnp.where(done_at_entry, 0, n)
        return (cache_t.k, cache_t.v, cache_d.k, cache_d.v, m_new, t0,
                out, o_new, done, rounds + 1, acc)

    state = lax.while_loop(cond, body, state)
    out, rounds, acc = state[6], state[9], state[10]
    tokens = out[:, :max_new_tokens]
    if return_stats:
        return tokens, {"rounds": rounds, "accepted": acc}
    return tokens


speculative_generate_jit = jax.jit(
    speculative_generate,
    static_argnums=(0, 2),
    static_argnames=("k_spec", "max_new_tokens", "eos_id", "pad_id",
                     "return_stats"),
)
