"""Multi-node-on-one-host test cluster.

Reference analog: ``python/ray/cluster_utils.py:108`` — the workhorse for
distributed tests: N raylets (+1 GCS) as local processes sharing one
machine; node failure = kill the raylet process.

The GCS and the head raylet run in-process (threads); added nodes run as
separate OS processes so ``remove_node`` is a real process kill.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time

from ray_tpu.runtime.gcs import GcsServer
from ray_tpu.runtime.raylet import Raylet
from ray_tpu.utils.ids import NodeID


class NodeHandle:
    def __init__(self, node_id: str, *, raylet: Raylet | None = None,
                 proc: subprocess.Popen | None = None, address=None):
        self.node_id = node_id
        self.raylet = raylet
        self.proc = proc
        self.address = address


class Cluster:
    """``Cluster()`` → ``add_node(num_cpus=...)`` → drive via ray_tpu.init
    (address=cluster.gcs_address)."""

    def __init__(self, *, heartbeat_timeout_s: float = 3.0,
                 gcs_fault_tolerance: bool = False,
                 external_gcs: bool = False):
        self._hb_timeout = heartbeat_timeout_s
        self._gcs_persist_dir = None
        self._owns_persist_dir = False
        self._gcs_proc = None
        if gcs_fault_tolerance:
            import tempfile

            self._gcs_persist_dir = tempfile.mkdtemp(prefix="raytpu_gcs_")
            self._owns_persist_dir = True
        if external_gcs:
            # the control plane as its OWN process (the reference's
            # gcs_server is one too): its RPC handling must not share
            # the driver's GIL — the hot resource in submit benchmarks.
            # Chaos helpers (kill_gcs/restart_gcs) stay in-process-only.
            if gcs_fault_tolerance:
                raise ValueError(
                    "external_gcs does not compose with the in-process "
                    "chaos helpers; use gcs_fault_tolerance without it")
            cfg = {"heartbeat_timeout_s": heartbeat_timeout_s}
            self._gcs_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.runtime.gcs",
                 json.dumps(cfg)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            line = self._gcs_proc.stdout.readline()
            if not line.strip():
                err = ""
                try:
                    _, err = self._gcs_proc.communicate(timeout=5)
                except subprocess.TimeoutExpired:
                    self._gcs_proc.kill()
                    self._gcs_proc.wait()
                self._gcs_proc = None
                raise RuntimeError(
                    f"external GCS process failed to start: "
                    f"{(err or '').strip()[-2000:]}")
            self.gcs = None
            self.gcs_address = tuple(json.loads(line)["address"])
        else:
            self.gcs = GcsServer(
                heartbeat_timeout_s=heartbeat_timeout_s,
                persistence_dir=self._gcs_persist_dir).start()
            self.gcs_address = self.gcs.address
        self.nodes: dict[str, NodeHandle] = {}
        self._head_id: str | None = None
        self._lock = threading.Lock()

    def kill_gcs(self):
        """Chaos path: hard-stop the GCS WITHOUT a final snapshot (as a
        crash would), severing every client connection."""
        if self.gcs._persist is not None:
            self.gcs._persist.close()
            self.gcs._persist = None   # skip stop()'s snapshot
        self.gcs.stop()

    def restart_gcs(self):
        """Start a fresh GCS on the SAME address, reloading persisted
        state (reference: GCS fault-tolerance restart with Redis-backed
        reload — gcs_init_data.cc). Raylets/drivers reconnect via their
        ReconnectingRpcClient and re-register on the first heartbeat."""
        if self._gcs_persist_dir is None:
            raise RuntimeError(
                "restart_gcs requires Cluster(gcs_fault_tolerance=True)")
        host, port = self.gcs_address
        self.gcs = GcsServer(
            host=host, port=port,
            heartbeat_timeout_s=self._hb_timeout,
            persistence_dir=self._gcs_persist_dir).start()
        self.gcs_address = self.gcs.address
        return self.gcs

    # ------------------------------------------------------------------

    def add_node(self, *, num_cpus: float = 4, num_tpus: float = 0,
                 resources: dict | None = None, external: bool = False,
                 store_capacity: int = 256 << 20,
                 labels: dict | None = None,
                 infeasible_timeout_s: float = 10.0) -> NodeHandle:
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        if resources:
            res.update({k: float(v) for k, v in resources.items()})
        node_id = NodeID.from_random().hex()
        labels = dict(labels or {})
        with self._lock:
            if self._head_id is None:
                labels.setdefault("head", True)
        if external:
            cfg = {"node_id": node_id, "gcs_address": list(self.gcs_address),
                   "resources": res, "store_capacity": store_capacity,
                   "labels": labels,
                   "infeasible_timeout_s": infeasible_timeout_s}
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.runtime.raylet",
                 json.dumps(cfg)],
                stdout=subprocess.PIPE, text=True)
            line = proc.stdout.readline()
            info = json.loads(line)
            handle = NodeHandle(node_id, proc=proc,
                                address=tuple(info["address"]))
        else:
            raylet = Raylet(node_id=node_id, gcs_address=self.gcs_address,
                            resources=res, store_capacity=store_capacity,
                            labels=labels,
                            infeasible_timeout_s=infeasible_timeout_s
                            ).start()
            handle = NodeHandle(node_id, raylet=raylet,
                                address=raylet.address)
        with self._lock:
            self.nodes[node_id] = handle
            if self._head_id is None:
                self._head_id = node_id
        return handle

    def remove_node(self, handle: NodeHandle, *, graceful: bool = False):
        """Kill a node (chaos path: non-graceful = SIGKILL, heartbeat
        timeout detection; reference: NodeKillerActor test_utils.py:1401)."""
        with self._lock:
            self.nodes.pop(handle.node_id, None)
        if handle.proc is not None:
            if graceful:
                handle.proc.terminate()
            else:
                handle.proc.kill()
            handle.proc.wait(timeout=10)
        elif handle.raylet is not None:
            handle.raylet.stop()
        if graceful:
            try:
                from ray_tpu.runtime.rpc import ConnectionLost, RpcClient
                c = RpcClient(self.gcs_address)
                try:
                    c.call("drain_node", node_id=handle.node_id)
                finally:
                    c.close()
            except (OSError, ConnectionLost, TimeoutError):
                pass  # GCS already gone: nothing left to drain from

    def wait_for_nodes(self, n: int, timeout: float = 10.0):
        from ray_tpu.runtime.rpc import RpcClient
        client = RpcClient(self.gcs_address)
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                nodes = client.call("get_nodes", alive_only=True)
                if len(nodes) >= n:
                    return
                time.sleep(0.05)
            raise TimeoutError(f"cluster did not reach {n} nodes")
        finally:
            client.close()

    def shutdown(self):
        for handle in list(self.nodes.values()):
            self.remove_node(handle, graceful=True)
        if self._gcs_proc is not None:
            self._gcs_proc.terminate()
            try:
                self._gcs_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._gcs_proc.kill()
        if self.gcs is not None:
            self.gcs.stop()
        if self._owns_persist_dir and self._gcs_persist_dir:
            import shutil

            shutil.rmtree(self._gcs_persist_dir, ignore_errors=True)
