"""Multi-node-on-one-host test cluster.

Reference analog: ``python/ray/cluster_utils.py:108`` — the workhorse for
distributed tests: N raylets (+1 GCS) as local processes sharing one
machine; node failure = kill the raylet process.

The GCS and the head raylet run in-process (threads); added nodes run as
separate OS processes so ``remove_node`` is a real process kill. With
``external_gcs=True`` the control plane is its own process as well, and
together with ``gcs_fault_tolerance=True`` it can be crash-killed and
restarted on the same address with WAL-replayed state.

``start_supervisor()`` turns the cluster into its own nanny: a poll loop
that respawns crashed external raylets under the SAME node id (the fresh
raylet's first heartbeat replays its node registration with the GCS) and
crash-restarts an external fault-tolerant GCS. Each detected death is
recorded in ``crash_events`` with detection/recovery timestamps — the
raw material for the chaos soak's per-class MTTR accounting.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

from ray_tpu.runtime.gcs import GcsServer
from ray_tpu.runtime.raylet import Raylet
from ray_tpu.utils.ids import NodeID


class NodeHandle:
    def __init__(self, node_id: str, *, raylet: Raylet | None = None,
                 proc: subprocess.Popen | None = None, address=None,
                 spawn_cfg: dict | None = None,
                 err_path: str | None = None):
        self.node_id = node_id
        self.raylet = raylet
        self.proc = proc
        self.address = address
        self.spawn_cfg = spawn_cfg     # external nodes: argv cfg for respawn
        self.err_path = err_path       # external nodes: stderr redirect file
        self.restart_count = 0
        self.removed = False           # deliberate remove: nanny must not respawn


class Cluster:
    """``Cluster()`` → ``add_node(num_cpus=...)`` → drive via ray_tpu.init
    (address=cluster.gcs_address)."""

    def __init__(self, *, heartbeat_timeout_s: float = 3.0,
                 gcs_fault_tolerance: bool = False,
                 external_gcs: bool = False):
        self._hb_timeout = heartbeat_timeout_s
        self._gcs_persist_dir = None
        self._owns_persist_dir = False
        self._gcs_proc = None
        self._gcs_err_path = None
        self._external_gcs = external_gcs
        self.gcs_restart_count = 0
        # deaths the supervisor detected and repaired:
        # {"class", "node_id", "detected_at", "recovered_at",
        #  "restart_count", "crash_point", "last_words"}
        self.crash_events: list[dict] = []
        self._supervisor: threading.Thread | None = None
        self._supervise = False
        import tempfile

        self._log_dir = tempfile.mkdtemp(prefix="raytpu_cluster_")
        if gcs_fault_tolerance:
            self._gcs_persist_dir = tempfile.mkdtemp(prefix="raytpu_gcs_")
            self._owns_persist_dir = True
        if external_gcs:
            # the control plane as its OWN process (the reference's
            # gcs_server is one too): its RPC handling must not share
            # the driver's GIL — the hot resource in submit benchmarks.
            self.gcs = None
            self.gcs_address = self._spawn_gcs_proc()
        else:
            self.gcs = GcsServer(
                heartbeat_timeout_s=heartbeat_timeout_s,
                persistence_dir=self._gcs_persist_dir).start()
            self.gcs_address = self.gcs.address
        self.nodes: dict[str, NodeHandle] = {}
        self._head_id: str | None = None
        self._lock = threading.Lock()

    # -- control-plane process management ------------------------------

    def _spawn_gcs_proc(self, host: str | None = None,
                        port: int | None = None) -> tuple:
        cfg = {"heartbeat_timeout_s": self._hb_timeout}
        if self._gcs_persist_dir is not None:
            cfg["persistence_dir"] = self._gcs_persist_dir
        if host is not None:
            cfg["host"] = host
            cfg["port"] = port
        self._gcs_err_path = os.path.join(self._log_dir, "gcs.err")
        with open(self._gcs_err_path, "ab") as err_f:
            self._gcs_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.runtime.gcs",
                 json.dumps(cfg)],
                stdout=subprocess.PIPE, stderr=err_f, text=True)
        line = self._gcs_proc.stdout.readline()
        if not line.strip():
            self._gcs_proc.kill()
            self._gcs_proc.wait()
            self._gcs_proc = None
            err = _read_tail(self._gcs_err_path)
            raise RuntimeError(
                f"external GCS process failed to start: {err[-2000:]}")
        return tuple(json.loads(line)["address"])

    def kill_gcs(self):
        """Chaos path: hard-stop the GCS WITHOUT a final snapshot (as a
        crash would), severing every client connection."""
        if self._gcs_proc is not None:
            self._gcs_proc.kill()
            self._gcs_proc.wait(timeout=10)
            self._gcs_proc = None
            return
        if self.gcs._persist is not None:
            self.gcs._persist.close()
            self.gcs._persist = None   # skip stop()'s snapshot
        self.gcs.stop()

    def restart_gcs(self):
        """Start a fresh GCS on the SAME address, reloading persisted
        state (reference: GCS fault-tolerance restart with Redis-backed
        reload — gcs_init_data.cc). Raylets/drivers reconnect via their
        ReconnectingRpcClient and re-register on the first heartbeat."""
        if self._gcs_persist_dir is None:
            raise RuntimeError(
                "restart_gcs requires Cluster(gcs_fault_tolerance=True)")
        host, port = self.gcs_address
        self.gcs_restart_count += 1
        if self._external_gcs:
            self.gcs_address = self._spawn_gcs_proc(host, port)
            return None
        self.gcs = GcsServer(
            host=host, port=port,
            heartbeat_timeout_s=self._hb_timeout,
            persistence_dir=self._gcs_persist_dir).start()
        self.gcs_address = self.gcs.address
        return self.gcs

    # ------------------------------------------------------------------

    def _spawn_raylet_proc(self, cfg: dict, err_path: str):
        with open(err_path, "ab") as err_f:
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.runtime.raylet",
                 json.dumps(cfg)],
                stdout=subprocess.PIPE, stderr=err_f, text=True)
        line = proc.stdout.readline()
        if not line.strip():
            proc.kill()
            proc.wait()
            err = _read_tail(err_path)
            raise RuntimeError(
                f"raylet process failed to start: {err[-2000:]}")
        info = json.loads(line)
        return proc, tuple(info["address"])

    def add_node(self, *, num_cpus: float = 4, num_tpus: float = 0,
                 resources: dict | None = None, external: bool = False,
                 store_capacity: int = 256 << 20,
                 labels: dict | None = None,
                 infeasible_timeout_s: float = 10.0) -> NodeHandle:
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        if resources:
            res.update({k: float(v) for k, v in resources.items()})
        node_id = NodeID.from_random().hex()
        labels = dict(labels or {})
        with self._lock:
            if self._head_id is None:
                labels.setdefault("head", True)
        if external:
            cfg = {"node_id": node_id, "gcs_address": list(self.gcs_address),
                   "resources": res, "store_capacity": store_capacity,
                   "labels": labels,
                   "infeasible_timeout_s": infeasible_timeout_s}
            err_path = os.path.join(self._log_dir,
                                    f"raylet-{node_id[:12]}.err")
            proc, address = self._spawn_raylet_proc(cfg, err_path)
            handle = NodeHandle(node_id, proc=proc, address=address,
                                spawn_cfg=cfg, err_path=err_path)
        else:
            raylet = Raylet(node_id=node_id, gcs_address=self.gcs_address,
                            resources=res, store_capacity=store_capacity,
                            labels=labels,
                            infeasible_timeout_s=infeasible_timeout_s
                            ).start()
            handle = NodeHandle(node_id, raylet=raylet,
                                address=raylet.address)
        with self._lock:
            self.nodes[node_id] = handle
            if self._head_id is None:
                self._head_id = node_id
        return handle

    def respawn_node(self, handle: NodeHandle) -> NodeHandle:
        """Revive a crashed EXTERNAL raylet under the same node id. The
        fresh process re-registers with the GCS on its first heartbeat
        (registration replay), so to the scheduler the node comes back
        rather than a new one appearing."""
        if handle.spawn_cfg is None:
            raise RuntimeError("respawn_node only revives external nodes")
        if handle.proc is not None and handle.proc.poll() is None:
            handle.proc.kill()
        if handle.proc is not None:
            handle.proc.wait(timeout=10)
        proc, address = self._spawn_raylet_proc(handle.spawn_cfg,
                                                handle.err_path)
        handle.proc = proc
        handle.address = address
        handle.restart_count += 1
        return handle

    def remove_node(self, handle: NodeHandle, *, graceful: bool = False):
        """Kill a node (chaos path: non-graceful = SIGKILL, heartbeat
        timeout detection; reference: NodeKillerActor test_utils.py:1401)."""
        with self._lock:
            handle.removed = True
            self.nodes.pop(handle.node_id, None)
        if handle.proc is not None:
            if graceful:
                handle.proc.terminate()
            else:
                handle.proc.kill()
            handle.proc.wait(timeout=10)
        elif handle.raylet is not None:
            handle.raylet.stop()
        if graceful:
            try:
                from ray_tpu.runtime.rpc import ConnectionLost, RpcClient
                c = RpcClient(self.gcs_address)
                try:
                    c.call("drain_node", node_id=handle.node_id)
                finally:
                    c.close()
            except (OSError, ConnectionLost, TimeoutError):
                pass  # GCS already gone: nothing left to drain from

    # -- supervisor (nanny) --------------------------------------------

    def start_supervisor(self, poll_s: float = 0.25):
        """Watch external raylet processes (and an external
        fault-tolerant GCS) and respawn any that die outside
        ``remove_node``. Records one ``crash_events`` entry per repaired
        death; ``recovered_at - detected_at`` is the respawn MTTR."""
        if self._supervisor is not None:
            return
        self._supervise = True
        self._supervisor = threading.Thread(
            target=self._supervise_loop, args=(max(0.05, poll_s),),
            name="cluster-supervisor", daemon=True)
        self._supervisor.start()

    def stop_supervisor(self):
        self._supervise = False
        t, self._supervisor = self._supervisor, None
        if t is not None:
            t.join(timeout=5)

    def _supervise_loop(self, poll_s: float):
        while self._supervise:
            with self._lock:
                handles = [h for h in self.nodes.values()
                           if h.proc is not None and not h.removed]
            for h in handles:
                if not self._supervise:
                    return
                if h.proc.poll() is None:
                    continue
                detected = time.time()
                with self._lock:
                    # deliberate remove raced the poll: not a crash
                    if h.removed or h.node_id not in self.nodes:
                        continue
                words = _last_words(h.err_path)
                try:
                    self.respawn_node(h)
                except (RuntimeError, OSError) as e:
                    words.setdefault("last_words", []).append(
                        f"respawn failed: {e!r}")
                self.crash_events.append({
                    "class": "raylet", "node_id": h.node_id,
                    "detected_at": detected, "recovered_at": time.time(),
                    "restart_count": h.restart_count,
                    "crash_point": words.get("crash_point"),
                    "last_words": words.get("last_words", [])})
            if (self._supervise and self._gcs_proc is not None
                    and self._gcs_proc.poll() is not None
                    and self._gcs_persist_dir is not None):
                detected = time.time()
                words = _last_words(self._gcs_err_path)
                self._gcs_proc = None
                try:
                    self.restart_gcs()
                except (RuntimeError, OSError) as e:
                    words.setdefault("last_words", []).append(
                        f"restart failed: {e!r}")
                self.crash_events.append({
                    "class": "gcs", "node_id": None,
                    "detected_at": detected, "recovered_at": time.time(),
                    "restart_count": self.gcs_restart_count,
                    "crash_point": words.get("crash_point"),
                    "last_words": words.get("last_words", [])})
            time.sleep(poll_s)

    # ------------------------------------------------------------------

    def wait_for_nodes(self, n: int, timeout: float = 10.0):
        from ray_tpu.runtime.rpc import RpcClient
        client = RpcClient(self.gcs_address)
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                nodes = client.call("get_nodes", alive_only=True)
                if len(nodes) >= n:
                    return
                time.sleep(0.05)
            raise TimeoutError(f"cluster did not reach {n} nodes")
        finally:
            client.close()

    def shutdown(self):
        self.stop_supervisor()
        for handle in list(self.nodes.values()):
            self.remove_node(handle, graceful=True)
        if self._gcs_proc is not None:
            self._gcs_proc.terminate()
            try:
                self._gcs_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._gcs_proc.kill()
        if self.gcs is not None:
            self.gcs.stop()
        import shutil

        shutil.rmtree(self._log_dir, ignore_errors=True)
        if self._owns_persist_dir and self._gcs_persist_dir:
            shutil.rmtree(self._gcs_persist_dir, ignore_errors=True)


def _read_tail(path: str | None, nbytes: int = 4096) -> str:
    if not path:
        return ""
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            f.seek(max(0, f.tell() - nbytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""


def _last_words(path: str | None) -> dict:
    from ray_tpu.runtime.worker_pool import _last_words as harvest
    return harvest(path)
