"""Runtime context (reference: ``python/ray/runtime_context.py:444,16`` —
``ray.get_runtime_context()``)."""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass
class RuntimeContext:
    node_id: str
    worker_id: str
    job_id: str
    gcs_address: str | None

    def get_node_id(self) -> str:
        return self.node_id

    def get_worker_id(self) -> str:
        return self.worker_id

    def get_job_id(self) -> str:
        return self.job_id


def get_runtime_context() -> RuntimeContext:
    from ray_tpu.runtime import core as _core

    node_id = os.environ.get("RAY_TPU_NODE_ID", "")
    worker_id = os.environ.get("RAY_TPU_WORKER_ID", "driver")
    gcs = None
    if os.environ.get("RAY_TPU_GCS_HOST"):
        gcs = (f"{os.environ['RAY_TPU_GCS_HOST']}:"
               f"{os.environ['RAY_TPU_GCS_PORT']}")
    job_id = ""
    if _core.is_initialized():
        rt = _core.get_runtime()
        node_id = node_id or getattr(rt, "node_id", "")
        if hasattr(node_id, "hex"):
            node_id = node_id.hex()
        job = getattr(rt, "job_id", None)
        job_id = job.hex() if hasattr(job, "hex") else str(job or "")
    return RuntimeContext(node_id=str(node_id), worker_id=worker_id,
                          job_id=job_id, gcs_address=gcs)
