"""Runtime context (reference: ``python/ray/runtime_context.py:444,16`` —
``ray.get_runtime_context()``)."""

from __future__ import annotations

import contextvars
import os
from dataclasses import dataclass

# Ambient namespace of the currently executing task/actor (reference:
# workers inherit the submitting job's namespace —
# ``_private/worker.py:1157``). Set by the worker around task execution;
# read by get_actor()/named-actor creation when the runtime has no
# explicit ``init(namespace=...)``.
_task_namespace: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "ray_tpu_task_namespace", default=None)


def current_task_namespace() -> str | None:
    return _task_namespace.get()


def set_task_namespace(ns: str | None):
    """Returns a reset token."""
    return _task_namespace.set(ns)


def reset_task_namespace(token):
    _task_namespace.reset(token)


@dataclass
class RuntimeContext:
    node_id: str
    worker_id: str
    job_id: str
    gcs_address: str | None
    namespace: str = ""

    def get_node_id(self) -> str:
        return self.node_id

    def get_worker_id(self) -> str:
        return self.worker_id

    def get_job_id(self) -> str:
        return self.job_id

    def get_task_id(self) -> str | None:
        """Id of the currently executing task/actor method, or None
        outside one (reference: ``RuntimeContext.get_task_id``). Comes
        from the log plane's execution bracket, so it is also the key
        ``util.state.get_log(task_id=...)`` resolves."""
        from ray_tpu.runtime import log_plane as _lp

        return _lp.current_task_id()


def get_runtime_context() -> RuntimeContext:
    from ray_tpu.runtime import core as _core

    node_id = os.environ.get("RAY_TPU_NODE_ID", "")
    worker_id = os.environ.get("RAY_TPU_WORKER_ID", "driver")
    gcs = None
    if os.environ.get("RAY_TPU_GCS_HOST"):
        gcs = (f"{os.environ['RAY_TPU_GCS_HOST']}:"
               f"{os.environ['RAY_TPU_GCS_PORT']}")
    job_id = ""
    if _core.is_initialized():
        rt = _core.get_runtime()
        node_id = node_id or getattr(rt, "node_id", "")
        if hasattr(node_id, "hex"):
            node_id = node_id.hex()
        job = getattr(rt, "job_id", None)
        job_id = job.hex() if hasattr(job, "hex") else str(job or "")
    ns = _task_namespace.get() or ""
    if not ns and _core.is_initialized():
        ns = getattr(_core.get_runtime(), "namespace", "") or ""
    return RuntimeContext(node_id=str(node_id), worker_id=worker_id,
                          job_id=job_id, gcs_address=gcs, namespace=ns)
