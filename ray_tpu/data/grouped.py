"""GroupedData: Dataset.groupby(key) results.

Reference analog: ``python/ray/data/grouped_data.py`` (GroupedData with
sum/min/max/mean/std/count/aggregate/map_groups). Execution is a
distributed two-phase aggregate: per-block partials as tasks, merged by
group key on the driver (partials are tiny — one tuple per key per
block), so the full dataset never materializes centrally.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.data.aggregate import (AggregateFn, Count, Max, Mean, Min, Std,
                                    Sum)
from ray_tpu.data.block import BlockAccessor, concat_blocks


def _block_partials(block, key, aggs):
    """Task: per-group partial aggregates for one block."""
    acc = BlockAccessor.for_block(block)
    batch = acc.to_batch()
    keys = np.asarray(batch[key])
    out = {}
    # group rows of this block by key value
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    boundaries = np.flatnonzero(
        np.concatenate(([True], sorted_keys[1:] != sorted_keys[:-1])))
    for bi, start in enumerate(boundaries):
        end = boundaries[bi + 1] if bi + 1 < len(boundaries) else len(keys)
        idx = order[start:end]
        kval = sorted_keys[start]
        kval = kval.item() if hasattr(kval, "item") else kval
        partials = []
        for agg in aggs:
            col = np.asarray(batch[agg.on])[idx] if agg.on else idx
            partials.append(agg.partial(col))
        out[kval] = partials
    return out


def _partition_by_key(block, key, n_parts):
    """Exchange map task: split one block into n_parts pieces by key
    hash, so every row of a group lands in the same reduce partition."""
    acc = BlockAccessor.for_block(block)
    batch = acc.to_batch()
    keys = np.asarray(batch[key])
    assign = np.asarray(
        [hash(v.item() if hasattr(v, "item") else v) % n_parts
         for v in keys])
    parts = []
    for p in range(n_parts):
        idx = np.flatnonzero(assign == p)
        parts.append({k: np.asarray(v)[idx] for k, v in batch.items()})
    return parts if n_parts > 1 else parts[0]


def _group_map(fn, key, *pieces):
    """Reduce task: concat this partition's pieces, then apply fn per
    whole group."""
    block = concat_blocks(list(pieces))
    acc = BlockAccessor.for_block(block)
    batch = acc.to_batch()
    if not batch:
        return []
    keys = np.asarray(batch[key])
    out_blocks = []
    for kval in np.unique(keys):
        idx = np.flatnonzero(keys == kval)
        group = {k: np.asarray(v)[idx] for k, v in batch.items()}
        res = fn(group)
        out_blocks.append(res)
    return out_blocks


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    # -- aggregate entry points -----------------------------------------

    def aggregate(self, *aggs: AggregateFn):
        """Returns a Dataset of one row per group:
        {key, <agg.output_name>...}."""
        from ray_tpu.data.dataset import Dataset, from_items

        ds, key = self._ds, self._key
        agg_list = list(aggs)
        part_fn = ray_tpu.remote(_block_partials)

        def source():
            refs = []
            for bundle in ds.iter_bundles():
                for ref in bundle.refs:
                    # pass the ref — task args auto-deref, block bytes
                    # never transit the driver
                    refs.append(part_fn.remote(ref, key, agg_list))
            merged: dict = {}
            for partials in ray_tpu.get(refs):
                for kval, plist in partials.items():
                    if kval not in merged:
                        merged[kval] = plist
                    else:
                        merged[kval] = [a.merge(x, y) for a, x, y in
                                        zip(aggs, merged[kval], plist)]
            rows = []
            for kval in sorted(merged):
                row = {key: kval}
                for agg, p in zip(aggs, merged[kval]):
                    row[agg.output_name] = agg.finalize(p)
                rows.append(row)
            return from_items(rows)._source_fn()

        return Dataset(source)

    def count(self):
        return self.aggregate(Count())

    def sum(self, on: str):
        return self.aggregate(Sum(on))

    def min(self, on: str):
        return self.aggregate(Min(on))

    def max(self, on: str):
        return self.aggregate(Max(on))

    def mean(self, on: str):
        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(Std(on, ddof=ddof))

    def map_groups(self, fn):
        """Apply ``fn(group_batch_dict) -> batch_dict`` per group.
        Distributed exchange: blocks are hash-partitioned by key (map
        tasks), then one reduce task per partition applies fn to each of
        its whole groups — partitions process in parallel and blocks
        move by ObjectRef (args auto-deref in tasks)."""
        from ray_tpu.data.dataset import Dataset

        ds, key = self._ds, self._key
        part_task = ray_tpu.remote(_partition_by_key)
        reduce_task = ray_tpu.remote(_group_map)

        def source():
            from ray_tpu.data.dataset import _bundle_of

            in_refs = [ref for bundle in ds.iter_bundles()
                       for ref in bundle.refs]
            n_parts = max(1, len(in_refs))
            piece_refs = []
            for ref in in_refs:
                refs = part_task.options(num_returns=n_parts).remote(
                    ref, key, n_parts)
                piece_refs.append([refs] if n_parts == 1 else refs)
            out_refs = [
                reduce_task.remote(fn, key,
                                   *[plist[p] for plist in piece_refs])
                for p in range(n_parts)
            ]
            bundles = []
            for out_blocks in ray_tpu.get(out_refs):
                bundles.extend(
                    _bundle_of(b) for b in out_blocks
                    if BlockAccessor.for_block(b).num_rows())
            return bundles

        return Dataset(source)
