"""Per-operator execution statistics for the streaming executor.

Reference analog: ``python/ray/data/_internal/stats.py`` —
``DatasetStats`` gives per-operator wall/task-time and row/byte
breakdowns, the thing that makes streaming-executor performance
debuggable (``ds.stats()``). Collected passively by the executor; zero
cost when never read.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class OperatorStats:
    name: str
    bundles_in: int = 0
    bundles_out: int = 0
    rows_out: int = 0
    bytes_in: int = 0
    bytes_out: int = 0
    tasks: int = 0
    # wall time of individual tasks (submit -> result observed)
    task_wall_s: list = field(default_factory=list)
    first_activity: float | None = None
    last_activity: float | None = None
    extra: dict = field(default_factory=dict)

    @property
    def wall_s(self) -> float:
        if self.first_activity is None or self.last_activity is None:
            return 0.0
        return self.last_activity - self.first_activity

    def summary_line(self) -> str:
        parts = [f"{self.name}:",
                 f"in {self.bundles_in} bundles/{_fmt_bytes(self.bytes_in)}",
                 f"out {self.bundles_out}/{_fmt_bytes(self.bytes_out)}"
                 f" ({self.rows_out} rows)"]
        if self.tasks:
            parts.append(f"{self.tasks} tasks")
        if self.task_wall_s:
            ts = sorted(self.task_wall_s)
            mean = sum(ts) / len(ts)
            parts.append(
                f"task wall min/p50/mean/max "
                f"{ts[0] * 1e3:.0f}/{ts[len(ts) // 2] * 1e3:.0f}/"
                f"{mean * 1e3:.0f}/{ts[-1] * 1e3:.0f}ms")
        parts.append(f"total {self.wall_s:.2f}s")
        for k, v in self.extra.items():
            parts.append(f"{k}={v}")
        return " ".join(parts)


class DatasetStats:
    """Stats for one streaming execution: per-operator breakdown plus
    the end-to-end wall time."""

    def __init__(self):
        self.operators: list[OperatorStats] = []
        self.start_t = time.monotonic()
        self.end_t: float | None = None

    @property
    def wall_s(self) -> float:
        end = self.end_t if self.end_t is not None else time.monotonic()
        return end - self.start_t

    def summary(self) -> str:
        lines = [f"Dataset execution: {self.wall_s:.2f}s, "
                 f"{len(self.operators)} operators"]
        for i, op in enumerate(self.operators):
            lines.append(f"  Operator {i} {op.summary_line()}")
        return "\n".join(lines)

    # dict-style access by operator name, plus substring probes on the
    # rendered summary — ``stats()["Map"]["tasks"]`` and
    # ``"task wall" in stats()`` both work
    def __getitem__(self, name: str) -> dict:
        for op in self.operators:
            if op.name == name:
                return {
                    "bundles_in": op.bundles_in,
                    "bundles_out": op.bundles_out,
                    "rows_out": op.rows_out,
                    "bytes_in": op.bytes_in,
                    "bytes_out": op.bytes_out,
                    "tasks": op.tasks,
                    "wall_s": op.wall_s,
                }
        raise KeyError(name)

    def __contains__(self, item) -> bool:
        return item in self.summary()

    def __repr__(self):
        return self.summary()

    __str__ = __repr__


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}GB"
