"""ray_tpu.data: streaming datasets (reference: Ray Data, SURVEY P13)."""

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.dataset import (
    Dataset,
    from_items,
    from_numpy,
    range,  # noqa: A004 - mirrors the reference's ray.data.range
    read_csv,
    read_json,
)
from ray_tpu.data.execution import ExecutionOptions, StreamingExecutor
from ray_tpu.data.iterator import DataIterator

__all__ = [
    "BlockAccessor",
    "Dataset",
    "DataIterator",
    "ExecutionOptions",
    "StreamingExecutor",
    "from_items",
    "from_numpy",
    "range",
    "read_csv",
    "read_json",
]
