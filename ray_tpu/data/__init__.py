"""ray_tpu.data: streaming datasets (reference: Ray Data, SURVEY P13)."""

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("data")


from ray_tpu.data import aggregate, preprocessors
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import (
    Dataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A004 - mirrors the reference's ray.data.range
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_parquet,
    read_text,
    read_tfrecords,
    read_avro,
    read_webdataset,
    write_avro_file,
    write_tfrecords_file,
)
from ray_tpu.data.execution import ExecutionOptions, StreamingExecutor
from ray_tpu.data.grouped import GroupedData
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.mongo import read_mongo, write_mongo
from ray_tpu.data.sql import read_sql, write_sql

__all__ = [
    "BlockAccessor",
    "DataContext",
    "Dataset",
    "DataIterator",
    "ExecutionOptions",
    "GroupedData",
    "StreamingExecutor",
    "aggregate",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "preprocessors",
    "range",
    "read_binary_files",
    "read_csv",
    "read_images",
    "read_json",
    "read_parquet",
    "read_mongo",
    "read_sql",
    "write_mongo",
    "write_sql",
    "read_text",
    "read_tfrecords",
    "read_avro",
    "read_webdataset",
    "write_avro_file",
    "write_tfrecords_file",
]
