"""DataIterator: per-consumer batch iteration with device prefetch.

Reference analog: ``python/ray/data/iterator.py`` (``DataIterator:60``,
``iter_torch_batches:239``) — here the accelerator path is
``iter_jax_batches``: host batches are re-batched to a fixed size, cast,
and ``jax.device_put`` for the NEXT batch overlaps consumption of the
current one (1-deep device prefetch hides host→HBM latency).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Iterator

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, concat_blocks


class DataIterator:
    def __init__(self, bundles: Iterator):
        self._bundles = bundles

    def iter_batches(self, *, batch_size: int | None = None,
                     drop_last: bool = False) -> Iterator[dict]:
        """Column-dict numpy batches, re-batched to ``batch_size``."""
        carry: dict | None = None
        for bundle in self._bundles:
            for ref in bundle.refs:
                batch = BlockAccessor.for_block(ray_tpu.get(ref)).to_batch()
                if not batch:
                    continue
                if batch_size is None:
                    yield batch
                    continue
                if carry is not None:
                    batch = concat_blocks([carry, batch])
                    carry = None
                n = len(next(iter(batch.values())))
                start = 0
                while n - start >= batch_size:
                    yield {k: v[start:start + batch_size]
                           for k, v in batch.items()}
                    start += batch_size
                if start < n:
                    carry = {k: v[start:] for k, v in batch.items()}
        if carry is not None and not drop_last:
            yield carry

    def iter_rows(self):
        for bundle in self._bundles:
            for ref in bundle.refs:
                yield from BlockAccessor.for_block(
                    ray_tpu.get(ref)).iter_rows()

    def iter_jax_batches(self, *, batch_size: int | None = None,
                         drop_last: bool = True, dtypes: dict | None = None,
                         device=None, sharding=None,
                         prefetch: int = 1) -> Iterator[dict]:
        """Batches as jax arrays already on device (or sharded across a
        mesh via ``sharding``), with ``prefetch`` transfers in flight."""
        import jax

        def transfer(batch: dict):
            out = {}
            for k, v in batch.items():
                arr = np.asarray(v)
                if dtypes and k in dtypes:
                    arr = arr.astype(dtypes[k])
                if sharding is not None:
                    out[k] = jax.device_put(arr, sharding)
                elif device is not None:
                    out[k] = jax.device_put(arr, device)
                else:
                    out[k] = jax.device_put(arr)
            return out

        window: deque = deque()
        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            window.append(transfer(batch))  # async dispatch (jax is lazy)
            if len(window) > prefetch:
                yield window.popleft()
        while window:
            yield window.popleft()

    def iter_torch_batches(self, *, batch_size: int | None = None,
                           drop_last: bool = False, dtypes: dict | None = None,
                           device: str | None = None) -> Iterator[dict]:
        """Batches as torch tensors (reference:
        ``data/iterator.py:239 iter_torch_batches``) — CPU torch interop
        for TorchTrainer-style loops; numeric columns only."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                arr = np.ascontiguousarray(v)
                if not arr.flags.writeable or arr is v:
                    # blocks alias the (read-only, shared) object store;
                    # torch tensors must own their memory — an in-place
                    # op on a zero-copy view would corrupt the stored
                    # block for every other consumer (or SIGSEGV on the
                    # read-only shm mapping)
                    arr = arr.copy()
                t = torch.from_numpy(arr)
                if (dtypes and k in dtypes) or device is not None:
                    t = t.to(device=device,
                             dtype=dtypes.get(k) if dtypes else None)
                out[k] = t
            yield out
