"""Execution context / config for ray_tpu.data.

Reference analog: ``python/ray/data/context.py`` (``DataContext``,
``use_push_based_shuffle`` toggle at ``context.py:156-187``). Holds
dataset-level knobs consulted at plan/execution time; one context per
process, overridable per dataset via ``Dataset.with_context`` if needed.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import ClassVar


@dataclass
class DataContext:
    # Distributed two-stage shuffle (map partitions -> reduce concat) vs
    # the centralized gather shuffle. The push-based path keeps every
    # partition in the object store as its own task output, so no single
    # process materializes the whole dataset.
    use_push_based_shuffle: bool = False
    # default parallelism for shuffle reduce tasks (None = #input blocks)
    shuffle_partitions: int | None = None
    # target rows per block for sources that chunk data
    target_num_blocks: int = 8
    # Blocks larger than this are split after a map task (size-based
    # block splitting; reference: DataContext.target_max_block_size,
    # default 128 MiB — smaller here because blocks round-trip through a
    # per-node shm store sized for tests and single hosts).
    target_max_block_size: int = 32 << 20
    # Streaming-executor backpressure: cap on bytes resident across the
    # topology (queued + in-flight). None = execution_budget_fraction of
    # the object store capacity (reference budgets 25% of the store —
    # streaming_executor_state.py:39).
    execution_budget_bytes: int | None = None
    execution_budget_fraction: float = 0.25
    extra: dict = field(default_factory=dict)

    _lock: ClassVar[threading.Lock] = threading.Lock()
    _current: ClassVar["DataContext | None"] = None

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = DataContext()
            return cls._current
