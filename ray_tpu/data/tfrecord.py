"""TFRecord IO without TensorFlow.

Reference analog: ``python/ray/data/datasource/tfrecords_datasource.py``
(which binds tf.train.Example). The file format and the Example proto
wire format are both simple enough to speak directly:

- TFRecord framing: ``uint64 length | uint32 masked_crc(length) | data |
  uint32 masked_crc(data)`` with CRC32C and the TF mask constant.
- ``tf.train.Example`` protobuf: ``features(1) -> map<string(1),
  Feature(2)>``; ``Feature`` is a oneof of ``bytes_list(1)``,
  ``float_list(2)``, ``int64_list(3)``.

``read_tfrecords`` yields one dict per record (single-element lists are
unwrapped, like the reference); ``write_tfrecords`` writes blocks back.
No tensorflow import anywhere.
"""

from __future__ import annotations

import struct


# ---------------------------------------------------------------------------
# CRC32C (software table; small and dependency-free)
# ---------------------------------------------------------------------------

def _make_tables():
    """Slicing-by-8 tables: 8 bytes per loop iteration instead of 1 —
    the per-byte table loop is ~5-20 MB/s in pure Python, which would
    make checksum (run over every record on both read and write) the
    TFRecord throughput ceiling."""
    poly = 0x82F63B78
    t0 = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        t0.append(c)
    tables = [t0]
    for k in range(1, 8):
        prev = tables[k - 1]
        tables.append([t0[prev[n] & 0xFF] ^ (prev[n] >> 8)
                       for n in range(256)])
    return tables


_T = _make_tables()


def _load_native_crc():
    """The C/SSE4.2 implementation (src/util/crc32c.cc) when built —
    ~GB/s vs single-digit MB/s for any pure-Python loop; checksums run
    over every record's full payload on both read and write."""
    import ctypes
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "_private", "libtpucrc.so")
    try:
        lib = ctypes.CDLL(path)
        lib.crc32c.restype = ctypes.c_uint32
        lib.crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]

        def crc(data: bytes) -> int:
            return lib.crc32c(bytes(data), len(data))

        assert crc(b"123456789") == 0xE3069283  # Castagnoli check vector
        return crc
    except Exception:  # noqa: BLE001 - lib absent/mismatched: Python path
        return None


_U64S = struct.Struct("<Q")


def _crc32c_py(data: bytes) -> int:
    crc = 0xFFFFFFFF
    n = len(data)
    i = 0
    t0, t1, t2, t3, t4, t5, t6, t7 = _T
    end8 = n - (n % 8)
    unpack = _U64S.unpack_from
    while i < end8:
        (word,) = unpack(data, i)
        word ^= crc
        hi = word >> 32
        crc = (t7[word & 0xFF] ^ t6[(word >> 8) & 0xFF]
               ^ t5[(word >> 16) & 0xFF] ^ t4[(word >> 24) & 0xFF]
               ^ t3[hi & 0xFF] ^ t2[(hi >> 8) & 0xFF]
               ^ t1[(hi >> 16) & 0xFF] ^ t0[hi >> 24])
        i += 8
    while i < n:
        crc = t0[(crc ^ data[i]) & 0xFF] ^ (crc >> 8)
        i += 1
    return crc ^ 0xFFFFFFFF


_crc32c = _load_native_crc() or _crc32c_py


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# protobuf wire helpers
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int):
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a proto message."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == 0:            # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 1:          # fixed64
            value = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:          # length-delimited
            ln, pos = _read_varint(buf, pos)
            value = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:          # fixed32
            value = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


def _parse_feature(buf: bytes):
    """Feature: oneof bytes_list(1)/float_list(2)/int64_list(3); each
    list holds repeated value(1)."""
    for field, _, value in _iter_fields(buf):
        items: list = []
        if field == 1:      # BytesList
            for f2, _, v2 in _iter_fields(value):
                if f2 == 1:
                    items.append(bytes(v2))
        elif field == 2:    # FloatList (packed or repeated fixed32)
            for f2, w2, v2 in _iter_fields(value):
                if f2 != 1:
                    continue
                if w2 == 2:   # packed
                    items.extend(
                        struct.unpack(f"<{len(v2) // 4}f", v2))
                else:
                    items.append(struct.unpack("<f", v2)[0])
        elif field == 3:    # Int64List (packed or repeated varint)
            for f2, w2, v2 in _iter_fields(value):
                if f2 != 1:
                    continue
                if w2 == 2:   # packed
                    pos = 0
                    while pos < len(v2):
                        item, pos = _read_varint(v2, pos)
                        items.append(_to_signed(item))
                else:
                    items.append(_to_signed(v2))
        return items
    return []


def _to_signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_example(buf: bytes) -> dict:
    """tf.train.Example bytes -> {name: value}; single-element lists
    unwrap (reference behavior)."""
    out: dict = {}
    for field, _, value in _iter_fields(buf):
        if field != 1:      # Example.features
            continue
        for f2, _, entry in _iter_fields(value):
            if f2 != 1:     # Features.feature map entry
                continue
            name = None
            items: list = []
            for f3, _, v3 in _iter_fields(entry):
                if f3 == 1:
                    name = v3.decode("utf-8")
                elif f3 == 2:
                    items = _parse_feature(v3)
            if name is not None:
                out[name] = items[0] if len(items) == 1 else items
    return out


def build_example(row: dict) -> bytes:
    """{name: value} -> tf.train.Example bytes. Values: bytes/str,
    int, float, or lists thereof."""
    entries = bytearray()
    for name, value in row.items():
        items = value if isinstance(value, (list, tuple)) else [value]
        feat = bytearray()
        if all(isinstance(v, (bytes, str)) for v in items):
            inner = bytearray()
            for v in items:
                b = v.encode("utf-8") if isinstance(v, str) else v
                inner.append(0x0A)           # field 1, wire 2
                _write_varint(inner, len(b))
                inner += b
            feat.append(0x0A)                # bytes_list = field 1
            _write_varint(feat, len(inner))
            feat += inner
        elif all(isinstance(v, bool) or isinstance(v, int)
                 for v in items):
            inner = bytearray()
            for v in items:
                inner.append(0x08)           # field 1, varint
                _write_varint(inner, int(v) & ((1 << 64) - 1))
            feat.append(0x1A)                # int64_list = field 3
            _write_varint(feat, len(inner))
            feat += inner
        elif all(isinstance(v, (int, float)) for v in items):
            packed = struct.pack(f"<{len(items)}f",
                                 *[float(v) for v in items])
            inner = bytearray()
            inner.append(0x0A)               # field 1, packed wire 2
            _write_varint(inner, len(packed))
            inner += packed
            feat.append(0x12)                # float_list = field 2
            _write_varint(feat, len(inner))
            feat += inner
        else:
            raise TypeError(
                f"feature {name!r}: unsupported value {value!r}")
        name_b = name.encode("utf-8")
        entry = bytearray()
        entry.append(0x0A)                   # map key = field 1
        _write_varint(entry, len(name_b))
        entry += name_b
        entry.append(0x12)                   # map value = field 2
        _write_varint(entry, len(feat))
        entry += feat
        entries.append(0x0A)                 # Features.feature = field 1
        _write_varint(entries, len(entry))
        entries += entry
    msg = bytearray()
    msg.append(0x0A)                         # Example.features = field 1
    _write_varint(msg, len(entries))
    msg += entries
    return bytes(msg)


# ---------------------------------------------------------------------------
# record framing
# ---------------------------------------------------------------------------

def iter_records(data: bytes):
    pos = 0
    n = len(data)
    while pos < n:
        (length,) = struct.unpack_from("<Q", data, pos)
        (len_crc,) = struct.unpack_from("<I", data, pos + 8)
        if _masked_crc(data[pos:pos + 8]) != len_crc:
            raise ValueError("TFRecord length CRC mismatch")
        start = pos + 12
        record = data[start:start + length]
        (data_crc,) = struct.unpack_from("<I", data, start + length)
        if _masked_crc(record) != data_crc:
            raise ValueError("TFRecord data CRC mismatch")
        yield record
        pos = start + length + 4


def frame_record(record: bytes) -> bytes:
    header = struct.pack("<Q", len(record))
    return (header + struct.pack("<I", _masked_crc(header)) + record
            + struct.pack("<I", _masked_crc(record)))
