"""SQL datasource: ``read_sql`` / ``write_sql`` over any DB-API 2
connection (reference: ``python/ray/data/datasource/sql_datasource.py``
— Ray Data's SQL reader takes a ``connection_factory`` returning a
DB-API2 connection, e.g. ``sqlite3.connect``, psycopg2, mysql).

The factory (not a live connection) crosses task boundaries: connections
are not picklable, so each reading block opens its own — exactly the
reference's contract."""

from __future__ import annotations

from typing import Callable

from ray_tpu.data.dataset import Dataset, from_items


def read_sql(sql: str, connection_factory: Callable, *,
             num_blocks: int = 8) -> Dataset:
    """Execute ``sql`` and return a row Dataset (one dict per row,
    column names from ``cursor.description``).

    Reference: ``ray.data.read_sql(sql, connection_factory)``
    (sql_datasource.py). The query runs once at materialization; rows
    split into ``num_blocks`` blocks for downstream parallelism."""

    def source():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = [dict(zip(cols, r)) for r in cur.fetchall()]
        finally:
            conn.close()
        return from_items(rows, num_blocks=num_blocks)._source_fn()

    return Dataset(source)


def write_sql(ds: Dataset, sql: str, connection_factory: Callable) -> None:
    """Write every row through a parameterized statement (reference:
    ``Dataset.write_sql(sql, connection_factory)``): ``sql`` is an
    INSERT with ``?``/``%s`` placeholders matching the dataset's column
    order, executed via ``executemany`` per block, one commit at the
    end."""
    conn = connection_factory()
    try:
        cur = conn.cursor()
        for batch in ds.iter_batches():
            rows = [tuple(r.values()) for r in rows_from_batch(batch)]
            if rows:
                cur.executemany(sql, rows)
        conn.commit()
    finally:
        conn.close()


def rows_from_batch(batch: dict) -> list[dict]:
    """Columnar batch -> row dicts with numpy scalars coerced to native
    Python (DB drivers reject np.int64 etc.). Shared by the SQL and
    Mongo writers."""
    keys = list(batch)
    n = len(batch[keys[0]]) if keys else 0
    return [{k: _py(batch[k][i]) for k in keys} for i in range(n)]


def _py(v):
    """numpy scalars -> native Python (sqlite3 rejects np.int64 etc.)."""
    item = getattr(v, "item", None)
    return item() if item is not None and getattr(v, "ndim", 0) == 0 else v
