"""Aggregations for Dataset.groupby / Dataset.aggregate.

Reference analog: ``python/ray/data/aggregate.py`` (AggregateFn, Sum,
Min, Max, Mean, Std, Count) computed here with numpy over column-dict
blocks. Each aggregation is (init, accumulate-block, merge, finalize) so
it composes with the distributed groupby (per-block partials merged on
the reduce side).
"""

from __future__ import annotations

import numpy as np


class AggregateFn:
    """name: output column; on: input column (None for Count)."""

    name = "agg"

    def __init__(self, on: str | None = None, alias_name: str | None = None):
        self.on = on
        self.output_name = alias_name or (
            f"{self.name.lower()}({on})" if on else self.name.lower())

    # partial: computed per block; merge: combine partials; finalize: scalar
    def partial(self, values: np.ndarray):
        raise NotImplementedError

    def merge(self, a, b):
        raise NotImplementedError

    def finalize(self, partial):
        return partial


class Count(AggregateFn):
    name = "count"

    def partial(self, values):
        return int(len(values))

    def merge(self, a, b):
        return a + b


class Sum(AggregateFn):
    name = "sum"

    def partial(self, values):
        return np.sum(values)

    def merge(self, a, b):
        return a + b


class Min(AggregateFn):
    name = "min"

    def partial(self, values):
        return np.min(values)

    def merge(self, a, b):
        return min(a, b)


class Max(AggregateFn):
    name = "max"

    def partial(self, values):
        return np.max(values)

    def merge(self, a, b):
        return max(a, b)


class Mean(AggregateFn):
    name = "mean"

    def partial(self, values):
        return (np.sum(values), len(values))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def finalize(self, partial):
        s, n = partial
        return s / n if n else float("nan")


class Std(AggregateFn):
    """Numerically stable parallel variance (Chan et al. pairwise merge,
    the same scheme the reference's Std aggregate uses)."""

    name = "std"

    def __init__(self, on=None, alias_name=None, ddof: int = 1):
        super().__init__(on, alias_name)
        self.ddof = ddof

    def partial(self, values):
        v = np.asarray(values, dtype=np.float64)
        n = len(v)
        if n == 0:
            return (0, 0.0, 0.0)
        mean = float(np.mean(v))
        m2 = float(np.sum((v - mean) ** 2))
        return (n, mean, m2)

    def merge(self, a, b):
        na, ma, m2a = a
        nb, mb, m2b = b
        if na == 0:
            return b
        if nb == 0:
            return a
        n = na + nb
        delta = mb - ma
        mean = ma + delta * nb / n
        m2 = m2a + m2b + delta * delta * na * nb / n
        return (n, mean, m2)

    def finalize(self, partial):
        n, _, m2 = partial
        d = n - self.ddof
        return float(np.sqrt(m2 / d)) if d > 0 else float("nan")
