"""Dataset: lazy logical plan over blocks, executed by the streaming
executor.

Reference analog: ``python/ray/data/dataset.py`` (``Dataset`` :178,
``map_batches:397``, ``iter_batches:3499``, ``streaming_split:1149``) with
the logical-plan → physical-operator structure of
``_internal/logical/``/`_internal/planner/`` collapsed into one layer:
each transform appends an operator factory; ``_build_ops`` instantiates
the physical topology at iteration time. Blocks are column-dict numpy
batches (TPU host format — feeds device transfer directly).
"""

from __future__ import annotations

import builtins
import queue as _queue
import threading
from typing import Any, Callable, Iterable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, concat_blocks
from ray_tpu.data.execution import (
    AllToAllOperator,
    ExecutionOptions,
    InputDataOperator,
    LimitOperator,
    MapOperator,
    PhysicalOperator,
    RefBundle,
    StreamingExecutor,
)


class Dataset:
    def __init__(self, source_fn: Callable[[], list[RefBundle]],
                 ops: tuple = (), options: ExecutionOptions | None = None):
        self._source_fn = source_fn
        self._ops = ops          # tuple of factories () -> PhysicalOperator
        self._options = options or ExecutionOptions()

    # ------------------------------------------------------------------
    # transforms (lazy)
    # ------------------------------------------------------------------

    def _with(self, factory) -> "Dataset":
        return Dataset(self._source_fn, self._ops + (factory,), self._options)

    def map_batches(self, fn, *, compute: str = "tasks", num_cpus: float = 1,
                    actor_pool_size: int = 2) -> "Dataset":
        """Apply ``fn(batch_dict) -> batch_dict`` per block.
        ``compute="actors"`` keeps fn state resident (pass a zero-arg
        factory as ``fn`` to build per-actor state once)."""
        return self._with(lambda: MapOperator(
            "MapBatches", "batches", fn, compute=compute, num_cpus=num_cpus,
            actor_pool_size=actor_pool_size))

    def map(self, fn, **kw) -> "Dataset":
        return self._with(lambda: MapOperator("Map", "rows", fn, **kw))

    def flat_map(self, fn, **kw) -> "Dataset":
        return self._with(lambda: MapOperator("FlatMap", "flat", fn, **kw))

    def filter(self, fn, **kw) -> "Dataset":
        return self._with(lambda: MapOperator("Filter", "filter", fn, **kw))

    def limit(self, n: int) -> "Dataset":
        return self._with(lambda: LimitOperator(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(lambda: AllToAllOperator(
            f"Repartition[{num_blocks}]",
            lambda bundles: _repartition(bundles, num_blocks)))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return self._with(lambda: AllToAllOperator(
            "RandomShuffle", lambda bundles: _shuffle(bundles, seed)))

    def sort(self, key: str) -> "Dataset":
        return self._with(lambda: AllToAllOperator(
            f"Sort[{key}]", lambda bundles: _sort(bundles, key)))

    def union(self, other: "Dataset") -> "Dataset":
        left_src, right_src = self._source_fn, other._source_fn
        left_ops, right_ops = self._ops, other._ops

        def source():
            return (_drain(left_src, left_ops, self._options)
                    + _drain(right_src, right_ops, other._options))
        return Dataset(source, (), self._options)

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def _build_ops(self) -> list[PhysicalOperator]:
        ops: list[PhysicalOperator] = [InputDataOperator(self._source_fn())]
        for factory in self._ops:
            ops.append(factory())
        return ops

    def iter_bundles(self) -> Iterator[RefBundle]:
        yield from StreamingExecutor(self._build_ops(),
                                     self._options).execute()

    def iter_batches(self) -> Iterator[dict]:
        for bundle in self.iter_bundles():
            for ref in bundle.refs:
                block = ray_tpu.get(ref)
                yield BlockAccessor.for_block(block).to_batch()

    def iter_rows(self) -> Iterator[Any]:
        for bundle in self.iter_bundles():
            for ref in bundle.refs:
                yield from BlockAccessor.for_block(
                    ray_tpu.get(ref)).iter_rows()

    def take(self, n: int = 20) -> list:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.num_rows for b in self.iter_bundles())

    def materialize(self) -> "Dataset":
        bundles = list(self.iter_bundles())
        return Dataset(lambda: bundles, (), self._options)

    def stats(self) -> dict:
        ops = self._build_ops()
        list(StreamingExecutor(ops, self._options).execute())
        return {op.name: dict(op.metrics) for op in ops}

    # ------------------------------------------------------------------
    # consumption for training (reference: streaming_split:1149)
    # ------------------------------------------------------------------

    def streaming_split(self, n: int) -> list["DataIterator"]:
        """n iterators fed round-robin from ONE shared streaming execution
        (per-rank ingest; each bundle goes to exactly one split)."""
        from ray_tpu.data.iterator import DataIterator

        queues = [_queue.Queue(maxsize=4) for _ in builtins.range(n)]

        def pump():
            i = 0
            try:
                for bundle in self.iter_bundles():
                    queues[i % n].put(bundle)
                    i += 1
            finally:
                for q in queues:
                    q.put(None)

        threading.Thread(target=pump, daemon=True).start()
        return [DataIterator(_queue_iter(q)) for q in queues]

    def iterator(self) -> "DataIterator":
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self.iter_bundles())

    def __repr__(self):
        return f"Dataset(ops={len(self._ops)})"


def _queue_iter(q: "_queue.Queue"):
    while True:
        item = q.get()
        if item is None:
            return
        yield item


def _drain(source_fn, ops, options) -> list[RefBundle]:
    ds = Dataset(source_fn, ops, options)
    return list(ds.iter_bundles())


# ---------------------------------------------------------------------------
# all-to-all transforms (centralized v1; push-based shuffle is a planned
# upgrade — reference toggles via DataContext.use_push_based_shuffle)
# ---------------------------------------------------------------------------

def _gather_rows(bundles: list[RefBundle]):
    blocks = []
    for b in bundles:
        blocks.extend(ray_tpu.get(list(b.refs)))
    return concat_blocks(blocks)


def _emit_blocks(block, num_blocks: int) -> list[RefBundle]:
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    num_blocks = max(1, min(num_blocks, n) if n else 1)
    out = []
    for i in builtins.range(num_blocks):
        start = i * n // num_blocks
        end = (i + 1) * n // num_blocks
        part = acc.slice(start, end)
        pacc = BlockAccessor.for_block(part)
        out.append(RefBundle([ray_tpu.put(part)],
                             num_rows=pacc.num_rows(),
                             size_bytes=pacc.size_bytes()))
    return out


def _repartition(bundles, num_blocks):
    return _emit_blocks(_gather_rows(bundles), num_blocks)


def _shuffle(bundles, seed):
    merged = _gather_rows(bundles)
    acc = BlockAccessor.for_block(merged)
    n = acc.num_rows()
    perm = np.random.default_rng(seed).permutation(n)
    if isinstance(merged, dict):
        shuffled = {k: np.asarray(v)[perm] for k, v in merged.items()}
    else:
        shuffled = [merged[i] for i in perm]
    return _emit_blocks(shuffled, max(1, len(bundles)))


def _sort(bundles, key):
    merged = _gather_rows(bundles)
    if isinstance(merged, dict):
        order = np.argsort(np.asarray(merged[key]), kind="stable")
        out = {k: np.asarray(v)[order] for k, v in merged.items()}
    else:
        out = sorted(merged, key=lambda r: r[key])
    return _emit_blocks(out, max(1, len(bundles)))


# ---------------------------------------------------------------------------
# sources (reference: data/read_api.py + datasource/)
# ---------------------------------------------------------------------------

def _bundle_of(block) -> RefBundle:
    acc = BlockAccessor.for_block(block)
    return RefBundle([ray_tpu.put(block)], num_rows=acc.num_rows(),
                     size_bytes=acc.size_bytes())


def range(n: int, *, num_blocks: int = 8) -> Dataset:  # noqa: A001
    def source():
        out = []
        for i in builtins.range(num_blocks):
            start = i * n // num_blocks
            end = (i + 1) * n // num_blocks
            if end > start:
                out.append(_bundle_of(
                    {"id": np.arange(start, end, dtype=np.int64)}))
        return out
    return Dataset(source)


def from_items(items: list, *, num_blocks: int = 8) -> Dataset:
    items = list(items)

    def source():
        out = []
        nb = max(1, min(num_blocks, len(items)))
        for i in builtins.range(nb):
            start = i * len(items) // nb
            end = (i + 1) * len(items) // nb
            if end > start:
                out.append(_bundle_of(items[start:end]))
        return out
    return Dataset(source)


def from_numpy(arrays: dict, *, num_blocks: int = 8) -> Dataset:
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    n = len(next(iter(arrays.values())))

    def source():
        out = []
        nb = max(1, min(num_blocks, n))
        for i in builtins.range(nb):
            start = i * n // nb
            end = (i + 1) * n // nb
            if end > start:
                out.append(_bundle_of(
                    {k: v[start:end] for k, v in arrays.items()}))
        return out
    return Dataset(source)


def read_json(paths, *, num_blocks: int = 8) -> Dataset:
    """Line-delimited JSON files → row datasets."""
    import json as _json

    if isinstance(paths, str):
        paths = [paths]

    def source():
        rows = []
        for p in paths:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(_json.loads(line))
        return [b for ds_b in [from_items(rows, num_blocks=num_blocks)
                               ._source_fn()] for b in ds_b]
    return Dataset(source)


def read_csv(paths, *, num_blocks: int = 8) -> Dataset:
    import csv as _csv

    if isinstance(paths, str):
        paths = [paths]

    def source():
        rows = []
        for p in paths:
            with open(p, newline="") as f:
                rows.extend(dict(r) for r in _csv.DictReader(f))
        return from_items(rows, num_blocks=num_blocks)._source_fn()
    return Dataset(source)
