"""Dataset: lazy logical plan over blocks, executed by the streaming
executor.

Reference analog: ``python/ray/data/dataset.py`` (``Dataset`` :178,
``map_batches:397``, ``iter_batches:3499``, ``streaming_split:1149``) with
the logical-plan → physical-operator structure of
``_internal/logical/``/`_internal/planner/`` collapsed into one layer:
each transform appends an operator factory; ``_build_ops`` instantiates
the physical topology at iteration time. Blocks are column-dict numpy
batches (TPU host format — feeds device transfer directly).
"""

from __future__ import annotations

import builtins
import queue as _queue
import threading
from typing import Any, Callable, Iterable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, concat_blocks
from ray_tpu.data.execution import (
    AllToAllOperator,
    ExecutionOptions,
    InputDataOperator,
    LimitOperator,
    MapOperator,
    PhysicalOperator,
    RefBundle,
    StreamingExecutor,
)


class Dataset:
    def __init__(self, source_fn: Callable[[], list[RefBundle]],
                 ops: tuple = (), options: ExecutionOptions | None = None):
        self._source_fn = source_fn
        self._ops = ops          # tuple of factories () -> PhysicalOperator
        self._options = options or ExecutionOptions()

    # ------------------------------------------------------------------
    # transforms (lazy)
    # ------------------------------------------------------------------

    def _with(self, factory) -> "Dataset":
        return Dataset(self._source_fn, self._ops + (factory,), self._options)

    def map_batches(self, fn, *, compute: str = "tasks", num_cpus: float = 1,
                    actor_pool_size: int = 2,
                    max_actor_pool_size: int | None = None) -> "Dataset":
        """Apply ``fn(batch_dict) -> batch_dict`` per block.
        ``compute="actors"`` keeps fn state resident (pass a zero-arg
        factory as ``fn`` to build per-actor state once); the pool
        autoscales between actor_pool_size and max_actor_pool_size."""
        return self._with(lambda: MapOperator(
            "MapBatches", "batches", fn, compute=compute, num_cpus=num_cpus,
            actor_pool_size=actor_pool_size,
            max_actor_pool_size=max_actor_pool_size))

    def map(self, fn, **kw) -> "Dataset":
        return self._with(lambda: MapOperator("Map", "rows", fn, **kw))

    def flat_map(self, fn, **kw) -> "Dataset":
        return self._with(lambda: MapOperator("FlatMap", "flat", fn, **kw))

    def filter(self, fn, **kw) -> "Dataset":
        return self._with(lambda: MapOperator("Filter", "filter", fn, **kw))

    def limit(self, n: int) -> "Dataset":
        return self._with(lambda: LimitOperator(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(lambda: AllToAllOperator(
            f"Repartition[{num_blocks}]",
            lambda bundles: _repartition(bundles, num_blocks)))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        from ray_tpu.data.context import DataContext

        def do_shuffle(bundles):
            if DataContext.get_current().use_push_based_shuffle:
                return _push_shuffle(bundles, seed)
            return _shuffle(bundles, seed)

        return self._with(lambda: AllToAllOperator(
            "RandomShuffle", do_shuffle))

    def sort(self, key: str) -> "Dataset":
        return self._with(lambda: AllToAllOperator(
            f"Sort[{key}]", lambda bundles: _sort(bundles, key)))

    def union(self, other: "Dataset") -> "Dataset":
        left_src, right_src = self._source_fn, other._source_fn
        left_ops, right_ops = self._ops, other._ops

        def source():
            return (_drain(left_src, left_ops, self._options)
                    + _drain(right_src, right_ops, other._options))
        return Dataset(source, (), self._options)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise join of two row-aligned datasets (reference:
        Dataset.zip). Right columns that collide get a ``_1`` suffix."""
        left, right = self, other

        def source():
            lb = _gather_rows(list(left.iter_bundles()))
            rb = _gather_rows(list(right.iter_bundles()))
            la = BlockAccessor.for_block(lb).to_batch()
            ra = BlockAccessor.for_block(rb).to_batch()
            n_l = BlockAccessor.for_block(lb).num_rows()
            n_r = BlockAccessor.for_block(rb).num_rows()
            if n_l != n_r:
                raise ValueError(
                    f"zip requires equal row counts, got {n_l} vs {n_r}")
            out = dict(la)
            for k, v in ra.items():
                name = k
                suffix = 1
                while name in out:  # probe until unique; never overwrite
                    name = f"{k}_{suffix}"
                    suffix += 1
                out[name] = v
            return _emit_blocks(out, 8)
        return Dataset(source, (), self._options)

    # -- column ops (reference: dataset.py add_column/drop_columns/...) --

    def add_column(self, name: str, fn) -> "Dataset":
        def add(batch):
            batch = dict(batch)
            batch[name] = np.asarray(fn(batch))
            return batch
        return self.map_batches(add)

    def drop_columns(self, cols: list[str]) -> "Dataset":
        drop = set(cols)
        return self.map_batches(
            lambda b: {k: v for k, v in b.items() if k not in drop})

    def select_columns(self, cols: list[str]) -> "Dataset":
        keep = list(cols)
        return self.map_batches(lambda b: {k: b[k] for k in keep})

    def rename_columns(self, mapping: dict) -> "Dataset":
        return self.map_batches(
            lambda b: {mapping.get(k, k): v for k, v in b.items()})

    def random_sample(self, fraction: float, *, seed=None) -> "Dataset":
        """Uniform row sample. Runs as an exchange so each block draws
        from a distinct per-block-index stream — a per-batch rng seeded
        identically would repeat the same keep-mask in every block (a
        positionally biased sample)."""
        def do_sample(bundles):
            out = []
            for i, b in enumerate(bundles):
                block = _gather_rows([b])
                acc = BlockAccessor.for_block(block)
                batch = acc.to_batch()
                n = acc.num_rows()
                rng = np.random.default_rng(
                    None if seed is None else [seed, i])
                keep = rng.random(n) < fraction
                sampled = {k: np.asarray(v)[keep] for k, v in batch.items()}
                sacc = BlockAccessor.for_block(sampled)
                if sacc.num_rows():
                    out.append(RefBundle([ray_tpu.put(sampled)],
                                         num_rows=sacc.num_rows(),
                                         size_bytes=sacc.size_bytes()))
            return out
        return self._with(lambda: AllToAllOperator("RandomSample",
                                                   do_sample))

    # -- grouped / global aggregates ------------------------------------

    def groupby(self, key: str):
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key)

    def aggregate(self, *aggs):
        """Whole-dataset aggregation → single result dict."""
        merged = [None] * len(aggs)
        for batch in self.iter_batches():
            for i, agg in enumerate(aggs):
                col = np.asarray(batch[agg.on]) if agg.on else \
                    np.arange(len(next(iter(batch.values()))))
                p = agg.partial(col)
                merged[i] = p if merged[i] is None else agg.merge(
                    merged[i], p)
        return {agg.output_name: agg.finalize(p)
                for agg, p in builtins.zip(aggs, merged) if p is not None}

    def groupby_all(self, *aggs):
        return self.aggregate(*aggs)

    def sum(self, on: str):
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(on))[f"sum({on})"]

    def min(self, on: str):
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(on))[f"min({on})"]

    def max(self, on: str):
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(on))[f"max({on})"]

    def mean(self, on: str):
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1):
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(on, ddof=ddof))[f"std({on})"]

    def unique(self, column: str) -> list:
        vals = set()
        for batch in self.iter_batches():
            vals.update(np.unique(np.asarray(batch[column])).tolist())
        return sorted(vals)

    def schema(self) -> dict:
        """Column name -> dtype of the first non-empty block."""
        for batch in self.iter_batches():
            if batch:
                return {k: np.asarray(v).dtype for k, v in batch.items()}
        return {}

    def split(self, n: int) -> list["Dataset"]:
        """Materialize into EXACTLY n row-balanced datasets (some may be
        empty when rows < n — callers index one per rank). For streaming
        per-rank ingest use streaming_split."""
        merged = _gather_rows(list(self.iter_bundles()))
        acc = BlockAccessor.for_block(merged)
        total = acc.num_rows()
        out = []
        for i in builtins.range(n):
            start = i * total // n
            end = (i + 1) * total // n
            part = acc.slice(start, end)
            pacc = BlockAccessor.for_block(part)
            bundles = ([RefBundle([ray_tpu.put(part)],
                                  num_rows=pacc.num_rows(),
                                  size_bytes=pacc.size_bytes())]
                       if pacc.num_rows() else [])
            out.append(Dataset((lambda bb=bundles: list(bb)), (),
                               self._options))
        return out

    # -- writes (reference: data/datasource write paths) -----------------

    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, batch in enumerate(self.iter_batches()):
            table = pa.table({k: np.asarray(v) for k, v in batch.items()})
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str) -> None:
        import csv as _csv
        import os

        os.makedirs(path, exist_ok=True)
        for i, batch in enumerate(self.iter_batches()):
            keys = list(batch)
            with open(os.path.join(path, f"part-{i:05d}.csv"), "w",
                      newline="") as f:
                w = _csv.writer(f)
                w.writerow(keys)
                n = len(batch[keys[0]]) if keys else 0
                for r in builtins.range(n):
                    w.writerow([batch[k][r] for k in keys])

    def write_sql(self, sql: str, connection_factory) -> None:
        """Write rows through a parameterized INSERT over a DB-API 2
        connection (reference: Dataset.write_sql, sql_datasource.py)."""
        from ray_tpu.data.sql import write_sql as _write_sql

        _write_sql(self, sql, connection_factory)

    def write_json(self, path: str) -> None:
        import json as _json
        import os

        os.makedirs(path, exist_ok=True)
        for i, batch in enumerate(self.iter_batches()):
            with open(os.path.join(path, f"part-{i:05d}.json"), "w") as f:
                keys = list(batch)
                n = len(batch[keys[0]]) if keys else 0
                for r in builtins.range(n):
                    row = {k: np.asarray(batch[k][r]).item()
                           if hasattr(batch[k][r], "item") else batch[k][r]
                           for k in keys}
                    f.write(_json.dumps(row) + "\n")

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def _build_ops(self) -> list[PhysicalOperator]:
        ops: list[PhysicalOperator] = [InputDataOperator(self._source_fn())]
        for factory in self._ops:
            ops.append(factory())
        return ops

    def iter_bundles(self) -> Iterator[RefBundle]:
        executor = StreamingExecutor(self._build_ops(), self._options)
        self._last_stats = executor.stats
        yield from executor.execute()

    def stats(self):
        """Per-operator execution breakdown (reference: ``ds.stats()``
        — ``data/_internal/stats.py``): wall time, bundles/bytes/rows in
        and out, and task wall-time distribution. Uses the LAST
        execution's stats when this dataset has been consumed; executes
        once otherwise. The returned DatasetStats prints the summary and
        indexes per-operator metrics by name (``stats()["Map"]``)."""
        stats = getattr(self, "_last_stats", None)
        if stats is None or stats.end_t is None:
            list(self.iter_bundles())
            stats = self._last_stats
        return stats

    def iter_batches(self) -> Iterator[dict]:
        for bundle in self.iter_bundles():
            for ref in bundle.refs:
                block = ray_tpu.get(ref)
                yield BlockAccessor.for_block(block).to_batch()

    def iter_rows(self) -> Iterator[Any]:
        for bundle in self.iter_bundles():
            for ref in bundle.refs:
                yield from BlockAccessor.for_block(
                    ray_tpu.get(ref)).iter_rows()

    def take(self, n: int = 20) -> list:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def to_arrow(self):
        """Materialize as one pyarrow Table (reference:
        ``Dataset.to_arrow_refs`` surface, eagerly concatenated)."""
        import pyarrow as pa

        return pa.Table.from_pandas(self.to_pandas(),
                                    preserve_index=False)

    def to_pandas(self):
        """Materialize as one pandas DataFrame (reference:
        ``Dataset.to_pandas``) — concatenates whole column batches,
        never per-row dicts."""
        import pandas as pd

        def frame(batch):
            # multi-dim columns can't build a DataFrame column-wise;
            # fall back to object cells (list of per-row arrays)
            cols = {k: (list(v) if getattr(v, "ndim", 1) > 1 else v)
                    for k, v in batch.items()}
            return pd.DataFrame(cols)

        frames = [frame(b) for b in self.iter_batches()]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def take_all(self) -> list:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.num_rows for b in self.iter_bundles())

    def materialize(self) -> "Dataset":
        bundles = list(self.iter_bundles())
        return Dataset(lambda: bundles, (), self._options)


    # ------------------------------------------------------------------
    # consumption for training (reference: streaming_split:1149)
    # ------------------------------------------------------------------

    def streaming_split(self, n: int) -> list["DataIterator"]:
        """n iterators fed round-robin from ONE shared streaming execution
        (per-rank ingest; each bundle goes to exactly one split)."""
        from ray_tpu.data.iterator import DataIterator

        queues = [_queue.Queue(maxsize=4) for _ in builtins.range(n)]

        def pump():
            i = 0
            try:
                for bundle in self.iter_bundles():
                    queues[i % n].put(bundle)
                    i += 1
            finally:
                for q in queues:
                    q.put(None)

        threading.Thread(target=pump, daemon=True).start()
        return [DataIterator(_queue_iter(q)) for q in queues]

    def iterator(self) -> "DataIterator":
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self.iter_bundles())

    def __repr__(self):
        return f"Dataset(ops={len(self._ops)})"


def _queue_iter(q: "_queue.Queue"):
    while True:
        item = q.get()
        if item is None:
            return
        yield item


def _drain(source_fn, ops, options) -> list[RefBundle]:
    ds = Dataset(source_fn, ops, options)
    return list(ds.iter_bundles())


# ---------------------------------------------------------------------------
# all-to-all transforms (centralized v1; push-based shuffle is a planned
# upgrade — reference toggles via DataContext.use_push_based_shuffle)
# ---------------------------------------------------------------------------

def _gather_rows(bundles: list[RefBundle]):
    blocks = []
    for b in bundles:
        blocks.extend(ray_tpu.get(list(b.refs)))
    return concat_blocks(blocks)


def _emit_blocks(block, num_blocks: int) -> list[RefBundle]:
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    num_blocks = max(1, min(num_blocks, n) if n else 1)
    out = []
    for i in builtins.range(num_blocks):
        start = i * n // num_blocks
        end = (i + 1) * n // num_blocks
        part = acc.slice(start, end)
        pacc = BlockAccessor.for_block(part)
        out.append(RefBundle([ray_tpu.put(part)],
                             num_rows=pacc.num_rows(),
                             size_bytes=pacc.size_bytes()))
    return out


def _repartition(bundles, num_blocks):
    return _emit_blocks(_gather_rows(bundles), num_blocks)


def _shuffle(bundles, seed):
    merged = _gather_rows(bundles)
    acc = BlockAccessor.for_block(merged)
    n = acc.num_rows()
    perm = np.random.default_rng(seed).permutation(n)
    if isinstance(merged, dict):
        shuffled = {k: np.asarray(v)[perm] for k, v in merged.items()}
    else:
        shuffled = [merged[i] for i in perm]
    return _emit_blocks(shuffled, max(1, len(bundles)))


def _sort(bundles, key):
    merged = _gather_rows(bundles)
    if isinstance(merged, dict):
        order = np.argsort(np.asarray(merged[key]), kind="stable")
        out = {k: np.asarray(v)[order] for k, v in merged.items()}
    else:
        out = sorted(merged, key=lambda r: r[key])
    return _emit_blocks(out, max(1, len(bundles)))


# ---------------------------------------------------------------------------
# sources (reference: data/read_api.py + datasource/)
# ---------------------------------------------------------------------------

def _bundle_of(block) -> RefBundle:
    acc = BlockAccessor.for_block(block)
    return RefBundle([ray_tpu.put(block)], num_rows=acc.num_rows(),
                     size_bytes=acc.size_bytes())


def range(n: int, *, num_blocks: int = 8) -> Dataset:  # noqa: A001
    def source():
        out = []
        for i in builtins.range(num_blocks):
            start = i * n // num_blocks
            end = (i + 1) * n // num_blocks
            if end > start:
                out.append(_bundle_of(
                    {"id": np.arange(start, end, dtype=np.int64)}))
        return out
    return Dataset(source)


def from_items(items: list, *, num_blocks: int = 8) -> Dataset:
    items = list(items)

    def source():
        out = []
        nb = max(1, min(num_blocks, len(items)))
        for i in builtins.range(nb):
            start = i * len(items) // nb
            end = (i + 1) * len(items) // nb
            if end > start:
                out.append(_bundle_of(items[start:end]))
        return out
    return Dataset(source)


def from_numpy(arrays: dict, *, num_blocks: int = 8) -> Dataset:
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    n = len(next(iter(arrays.values())))

    def source():
        out = []
        nb = max(1, min(num_blocks, n))
        for i in builtins.range(nb):
            start = i * n // nb
            end = (i + 1) * n // nb
            if end > start:
                out.append(_bundle_of(
                    {k: v[start:end] for k, v in arrays.items()}))
        return out
    return Dataset(source)


def _resolve_fs(path: str):
    """(filesystem, fs_path) for a possibly-URI path (reference: the
    pyarrow.fs resolution behind every datasource — ``file://``, S3/GCS
    URIs included). Plain local paths bypass pyarrow entirely."""
    if "://" not in path:
        return None, path
    from pyarrow import fs as pafs

    return pafs.FileSystem.from_uri(path)


def _open_path(path: str, mode: str = "r"):
    """open() for local paths OR pyarrow.fs URIs — the shared IO hook
    behind every datasource (reference: pyarrow.fs usage across
    data/datasource/). Modes: "r" text, "rb" binary, "csv" text with
    universal-newline handling disabled (the csv module's contract)."""
    fs, fsp = _resolve_fs(path)
    if fs is None:
        if mode == "csv":
            return open(fsp, newline="")
        return open(fsp, mode)
    import io

    stream = fs.open_input_file(fsp)
    if mode == "rb":
        return stream
    return io.TextIOWrapper(stream, newline="" if mode == "csv" else None)


def read_json(paths, *, num_blocks: int = 8) -> Dataset:
    """Line-delimited JSON files → row datasets."""
    import json as _json

    if isinstance(paths, str):
        paths = [paths]

    def source():
        rows = []
        for p in paths:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(_json.loads(line))
        return [b for ds_b in [from_items(rows, num_blocks=num_blocks)
                               ._source_fn()] for b in ds_b]
    return Dataset(source)


def read_csv(paths, *, num_blocks: int = 8) -> Dataset:
    import csv as _csv

    if isinstance(paths, str):
        paths = [paths]

    def source():
        rows = []
        for p in paths:
            with _open_path(p, "csv") as f:
                rows.extend(dict(r) for r in _csv.DictReader(f))
        return from_items(rows, num_blocks=num_blocks)._source_fn()
    return Dataset(source)


# ---------------------------------------------------------------------------
# push-based shuffle (reference: push_based_shuffle_task_scheduler.py,
# toggled by DataContext.use_push_based_shuffle)
# ---------------------------------------------------------------------------

def _shuffle_map_partition(block, n_parts: int, seed):
    """Map stage task: split one block's rows uniformly at random into
    n_parts partition blocks."""
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n_parts, size=n)
    batch = acc.to_batch()
    parts = []
    for p in builtins.range(n_parts):
        idx = np.flatnonzero(assign == p)
        parts.append({k: np.asarray(v)[idx] for k, v in batch.items()})
    return parts


def _shuffle_reduce(seed, *part_blocks):
    """Reduce stage task: concat this partition's pieces and shuffle
    within the partition. Returns (block, (rows, bytes)) as two objects
    so the driver can build a RefBundle from the tiny metadata object
    without pulling the block."""
    merged = concat_blocks(list(part_blocks))
    acc = BlockAccessor.for_block(merged)
    n = acc.num_rows()
    perm = np.random.default_rng(seed).permutation(n) if n else []
    if isinstance(merged, dict):
        block = {k: np.asarray(v)[perm] for k, v in merged.items()}
    else:
        block = [merged[i] for i in perm]
    bacc = BlockAccessor.for_block(block)
    return block, (bacc.num_rows(), bacc.size_bytes())


def _push_shuffle(bundles, seed):
    """Two-stage distributed shuffle: every map task emits one piece per
    reduce partition; reduce tasks concat+shuffle their pieces. Blocks
    move by ObjectRef end to end (task args auto-deref), so the driver
    only ever touches per-partition metadata tuples."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    n_parts = ctx.shuffle_partitions or max(1, len(bundles))
    map_task = ray_tpu.remote(_shuffle_map_partition)
    reduce_task = ray_tpu.remote(_shuffle_reduce)

    piece_refs = []  # piece_refs[map_idx][part] -> ObjectRef of one piece
    i = 0
    for b in bundles:
        for ref in b.refs:
            sub = seed + i if seed is not None else None
            refs = map_task.options(num_returns=n_parts).remote(
                ref, n_parts, sub)
            piece_refs.append([refs] if n_parts == 1 else refs)
            i += 1
    block_refs, meta_refs = [], []
    for p in builtins.range(n_parts):
        pieces = [plist[p] for plist in piece_refs]
        rseed = None if seed is None else seed + 100_003 + p
        bref, mref = reduce_task.options(num_returns=2).remote(
            rseed, *pieces)
        block_refs.append(bref)
        meta_refs.append(mref)
    out = []
    for bref, (n, nbytes) in builtins.zip(block_refs,
                                          ray_tpu.get(meta_refs)):
        if n:
            out.append(RefBundle([bref], num_rows=n, size_bytes=nbytes))
    return out


# ---------------------------------------------------------------------------
# parquet IO (reference: data/datasource/parquet_datasource.py; pyarrow)
# ---------------------------------------------------------------------------

def read_parquet(paths, *, num_blocks: int = 8, columns=None) -> Dataset:
    """Parquet files → column-dict blocks (one or more blocks per file's
    row groups). Paths may be local or pyarrow.fs URIs (``file://``,
    ``s3://``, ``gs://`` — credentials per pyarrow)."""
    import pyarrow.parquet as pq

    if isinstance(paths, str):
        paths = [paths]

    def source():
        out = []
        per_file = max(1, num_blocks // len(paths))
        for p in paths:
            fs, fsp = _resolve_fs(p)
            table = pq.read_table(fsp, columns=columns, filesystem=fs)
            cols = {name: table.column(name).to_numpy(zero_copy_only=False)
                    for name in table.column_names}
            out.extend(_emit_blocks(cols, per_file))
        return out
    return Dataset(source)


def from_arrow(tables, *, num_blocks: int = 8) -> Dataset:
    """pyarrow Table(s) → column-block dataset (reference:
    ``data/read_api.py from_arrow``)."""
    import pyarrow as pa

    if isinstance(tables, pa.Table):
        tables = [tables]
    if not tables:
        return from_items([])
    table = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    cols = {name: table.column(name).to_numpy(zero_copy_only=False)
            for name in table.column_names}
    return from_numpy(cols, num_blocks=num_blocks)


def from_pandas(dfs, *, num_blocks: int = 8) -> Dataset:
    """pandas DataFrame(s) → column-block dataset (reference:
    ``data/read_api.py from_pandas``)."""
    import pandas as pd

    if isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    frames = [df.reset_index(drop=True) for df in dfs]
    if not frames:
        return from_items([])
    merged = frames[0] if len(frames) == 1 else pd.concat(
        frames, ignore_index=True)
    if not len(merged.columns):   # from_numpy({}) would StopIteration
        return from_items([])
    return from_numpy({c: merged[c].to_numpy() for c in merged.columns},
                      num_blocks=num_blocks)


def read_text(paths, *, num_blocks: int = 8, drop_empty: bool = True
              ) -> Dataset:
    """Text files → one row per line, column ``text`` (reference:
    ``data/read_api.py read_text``)."""
    if isinstance(paths, str):
        paths = [paths]

    def source():
        lines = []
        for p in paths:
            with _open_path(p) as f:
                for line in f:
                    line = line.rstrip("\r\n")   # CRLF-safe
                    if line or not drop_empty:
                        lines.append({"text": line})
        return from_items(lines, num_blocks=num_blocks)._source_fn()
    return Dataset(source)


def read_binary_files(paths, *, include_paths: bool = False,
                      num_blocks: int = 8) -> Dataset:
    """Whole files as ``bytes`` rows (reference: ``read_binary_files``)."""
    if isinstance(paths, str):
        paths = [paths]

    def source():
        rows = []
        for p in paths:
            with _open_path(p, "rb") as f:
                row = {"bytes": f.read()}
                if include_paths:
                    row["path"] = p
                rows.append(row)
        return from_items(rows, num_blocks=num_blocks)._source_fn()
    return Dataset(source)


def read_tfrecords(paths, *, num_blocks: int = 8) -> Dataset:
    """TFRecord files of ``tf.train.Example`` records → one dict row per
    record (reference: ``datasource/tfrecords_datasource.py``). Parsed
    WITHOUT tensorflow — see ``ray_tpu.data.tfrecord`` for the wire
    codecs. pyarrow.fs URIs work like every other reader."""
    from ray_tpu.data import tfrecord as _tfr

    if isinstance(paths, str):
        paths = [paths]

    def source():
        rows = []
        for p in paths:
            with _open_path(p, "rb") as f:
                data = f.read()
            for record in _tfr.iter_records(data):
                rows.append(_tfr.parse_example(record))
        return from_items(rows, num_blocks=num_blocks)._source_fn()
    return Dataset(source)


def write_tfrecords_file(rows, path: str):
    """Write dict rows to ONE TFRecord file of tf.train.Example records
    (the reference's ``write_tfrecords`` writes a file per block; a
    single-file helper keeps the API honest without a writer plan)."""
    from ray_tpu.data import tfrecord as _tfr

    with _open_path(path, "wb") as f:
        for row in rows:
            f.write(_tfr.frame_record(_tfr.build_example(row)))


def read_avro(paths, *, num_blocks: int = 8) -> Dataset:
    """Avro object container files → one dict row per record
    (reference: ``datasource/avro_datasource.py``). Decoded WITHOUT an
    avro library — see ``ray_tpu.data.avro`` for the binary codec
    (null + deflate codecs). pyarrow.fs URIs work like every other
    reader."""
    from ray_tpu.data import avro as _avro

    if isinstance(paths, str):
        paths = [paths]

    def source():
        rows = []
        for p in paths:
            with _open_path(p, "rb") as f:
                rows.extend(_avro.iter_avro(f.read()))
        return from_items(rows, num_blocks=num_blocks)._source_fn()
    return Dataset(source)


def write_avro_file(rows, path: str, *, schema: dict | None = None,
                    codec: str = "null"):
    """Write dict rows to ONE avro container file (schema inferred from
    the first row when omitted; single-file helper mirroring
    ``write_tfrecords_file``)."""
    from ray_tpu.data import avro as _avro

    with _open_path(path, "wb") as f:
        f.write(_avro.write_avro(rows, schema, codec=codec))


def read_webdataset(paths, *, num_blocks: int = 8) -> Dataset:
    """WebDataset tar shards → one dict row per sample (reference:
    ``datasource/webdataset_datasource.py``): files grouped by basename
    before the first extension dot; row keys are the extensions plus
    ``__key__``. ``.cls`` decodes to int, ``.txt``/``.json`` to
    str/object; other extensions stay raw bytes."""
    import io
    import json as _json
    import tarfile

    if isinstance(paths, str):
        paths = [paths]

    def _decode(ext: str, data: bytes):
        if ext == "cls":
            return int(data.decode("utf-8").strip())
        if ext in ("txt", "text"):
            return data.decode("utf-8")
        if ext == "json":
            return _json.loads(data.decode("utf-8"))
        return data

    def source():
        rows = []
        for p in paths:
            with _open_path(p, "rb") as f:
                blob = f.read()
            with tarfile.open(fileobj=io.BytesIO(blob)) as tar:
                current_key = None
                row: dict = {}
                for member in tar:
                    if not member.isfile():
                        continue
                    # key = directory + basename-stem (webdataset
                    # convention): the extension split happens on the
                    # BASENAME only — a dot in a directory name must not
                    # corrupt the key — while same basenames in
                    # different directories stay different samples
                    dirpart, _, base = member.name.rpartition("/")
                    stem, _, ext = base.partition(".")
                    key = f"{dirpart}/{stem}" if dirpart else stem
                    if key != current_key:
                        if row:
                            rows.append(row)
                        current_key = key
                        row = {"__key__": key}
                    row[ext] = _decode(
                        ext, tar.extractfile(member).read())
                if row:
                    rows.append(row)
        return from_items(rows, num_blocks=num_blocks)._source_fn()
    return Dataset(source)


_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp", ".tiff")


def _expand_image_paths(paths) -> list[str]:
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, names in sorted(os.walk(p)):
                for n in sorted(names):
                    if n.lower().endswith(_IMAGE_EXTS):
                        files.append(os.path.join(root, n))
        elif os.path.exists(p):
            files.append(p)
        else:
            raise FileNotFoundError(f"no such image file or directory: {p}")
    return files


def read_images(paths, *, size: tuple | None = None, mode: str = "RGB",
                include_paths: bool = False,
                num_blocks: int | None = None) -> Dataset:
    """Image files/directories → rows with an ``image`` ndarray column
    (reference: ``data/datasource/image_datasource.py:41`` — the input
    side of the ViT/CLIP BASELINE config).

    Listing happens on the driver; DECODING happens inside the streaming
    executor's map tasks, so ingest parallelizes across the cluster and
    flows through the byte-budget backpressure like any other operator.

    size: optional (height, width) resize. mode: PIL conversion mode
    ("RGB", "L", ...). Decoded dtype is uint8, shape [H, W, C] ([H, W]
    for mode "L").
    """
    files = _expand_image_paths(paths)
    if not files:
        raise FileNotFoundError(f"no image files under {paths!r}")
    n_blocks = num_blocks or min(len(files), 8)

    def decode(row: dict) -> dict:
        from PIL import Image

        img = Image.open(row["path"])
        if mode:
            img = img.convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))  # PIL takes (w, h)
        import numpy as _np

        out = {"image": _np.asarray(img)}
        if include_paths:
            out["path"] = row["path"]
        return out

    return from_items([{"path": f} for f in files],
                      num_blocks=n_blocks).map(decode)
