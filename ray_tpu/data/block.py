"""Blocks: the unit of data exchanged between operators.

Reference analog: Ray Data blocks (Arrow tables in plasma —
``python/ray/data/_internal/block_builder.py`` etc.). Here a block is a
column dict of numpy arrays (the TPU-idiomatic layout: feeds
``jax.device_put`` without conversion) or a list of Python rows for
non-tabular data. Blocks live in the object store as ObjectRefs.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np


class BlockAccessor:
    """Uniform view over the two block layouts (rows list | column dict)."""

    def __init__(self, block):
        self.block = block

    @staticmethod
    def for_block(block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        b = self.block
        if isinstance(b, dict):
            if not b:
                return 0
            return len(next(iter(b.values())))
        return len(b)

    def size_bytes(self) -> int:
        b = self.block
        if isinstance(b, dict):
            return int(sum(np.asarray(v).nbytes for v in b.values()))
        return int(sum(getattr(x, "nbytes", 64) for x in b)) if b else 0

    def iter_rows(self) -> Iterable[Any]:
        b = self.block
        if isinstance(b, dict):
            keys = list(b)
            n = self.num_rows()
            for i in range(n):
                yield {k: b[k][i] for k in keys}
        else:
            yield from b

    def to_batch(self) -> dict:
        """Column-dict batch (numpy arrays)."""
        b = self.block
        if isinstance(b, dict):
            return {k: np.asarray(v) for k, v in b.items()}
        if not b:
            return {}
        first = b[0]
        if isinstance(first, dict):
            keys = list(first)
            return {k: np.asarray([row[k] for row in b]) for k in keys}
        return {"item": np.asarray(b)}

    def to_rows(self) -> list:
        if isinstance(self.block, dict):
            return list(self.iter_rows())
        return list(self.block)

    def slice(self, start: int, end: int):
        b = self.block
        if isinstance(b, dict):
            return {k: v[start:end] for k, v in b.items()}
        return b[start:end]


def batch_to_block(batch) -> Any:
    """Normalize a user map_batches return into a block."""
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    if isinstance(batch, list):
        return batch
    if isinstance(batch, np.ndarray):
        return {"item": batch}
    raise TypeError(
        f"map_batches must return dict/list/ndarray, got {type(batch)}")


def concat_blocks(blocks: list):
    if not blocks:
        return []
    if isinstance(blocks[0], dict):
        keys = list(blocks[0])
        return {k: np.concatenate([np.asarray(b[k]) for b in blocks])
                for k in keys}
    out = []
    for b in blocks:
        out.extend(b)
    return out
