"""Streaming executor: pull-based operator pipeline with backpressure.

Reference analog: ``python/ray/data/_internal/execution/`` —
``StreamingExecutor`` (streaming_executor.py:49) driving a topology of
physical operators; the scheduling loop is ``select_operator_to_run``
(streaming_executor_state.py:376) choosing, each tick, the runnable
operator with available inputs and budget, preferring operators furthest
downstream (drains the pipeline, bounds memory). Backpressure is a
per-topology cap on in-flight task output bytes (the reference budgets 25%
of the object store — streaming_executor_state.py:39).

Operators launch ray_tpu tasks (``TaskPoolMapOperator``) or use a pool of
reusable actors (``ActorPoolMapOperator`` — map_operator.py:39 analog) so
expensive per-batch state (a jitted function, a loaded model) is paid once.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

import ray_tpu
from ray_tpu.data.block import BlockAccessor, batch_to_block, concat_blocks


@dataclass
class RefBundle:
    """A unit of streamed data: object refs + size metadata."""

    refs: list                      # list[ObjectRef] of blocks
    num_rows: int = 0
    size_bytes: int = 0


@dataclass
class ExecutionOptions:
    max_in_flight_tasks: int = 8        # per operator
    max_buffered_bundles: int = 16      # per operator output queue
    actor_pool_size: int = 2
    # Byte budget for data resident in the topology (queued bundles +
    # in-flight task inputs). None = resolved from DataContext at
    # execution time (fraction of the object store). The most-downstream
    # runnable operator is always allowed to dispatch, so the pipeline
    # drains instead of deadlocking when one bundle exceeds the budget.
    max_in_flight_bytes: int | None = None


def _resolve_byte_budget(options: ExecutionOptions) -> int:
    if options.max_in_flight_bytes is not None:
        return options.max_in_flight_bytes
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    if ctx.execution_budget_bytes is not None:
        return ctx.execution_budget_bytes
    try:
        capacity = ray_tpu.api._runtime().store.stats()["capacity"]
    except Exception:  # noqa: BLE001 - local mode / no store stats
        capacity = 1 << 30
    return max(int(capacity * ctx.execution_budget_fraction), 16 << 20)


class PhysicalOperator:
    """Base: pull input bundles, produce output bundles."""

    def __init__(self, name: str):
        from ray_tpu.data.stats import OperatorStats

        self.name = name
        self.input_queue: deque[RefBundle] = deque()
        self.output_queue: deque[RefBundle] = deque()
        self.inputs_done = False
        self.metrics = {"bundles_in": 0, "bundles_out": 0, "tasks": 0}
        # per-operator execution stats (reference: DatasetStats,
        # data/_internal/stats.py) — filled by the executor as bundles
        # move, and by operators for task wall times
        self.stats = OperatorStats(name)

    # -- scheduling interface -------------------------------------------
    def can_accept_work(self, options: ExecutionOptions) -> bool:
        return (bool(self.input_queue)
                and len(self.output_queue) < options.max_buffered_bundles
                and self.num_active_tasks() < options.max_in_flight_tasks)

    def num_active_tasks(self) -> int:
        return 0

    def outstanding_bytes(self) -> int:
        """Bytes resident in this operator (queued + in-flight inputs) —
        the backpressure accounting unit."""
        return (sum(b.size_bytes for b in self.input_queue)
                + sum(b.size_bytes for b in self.output_queue))

    def dispatch(self, options: ExecutionOptions):
        raise NotImplementedError

    def poll(self):
        """Move finished task results to the output queue."""

    def is_done(self) -> bool:
        return (self.inputs_done and not self.input_queue
                and not self.output_queue and self.num_active_tasks() == 0)

    def all_dispatched(self) -> bool:
        return self.inputs_done and not self.input_queue

    def shutdown(self):
        pass


class InputDataOperator(PhysicalOperator):
    """Source: emits pre-materialized bundles (read tasks already refs)."""

    def __init__(self, bundles: list[RefBundle]):
        super().__init__("Input")
        self.output_queue.extend(bundles)
        self.inputs_done = True

    def can_accept_work(self, options):
        return False

    def dispatch(self, options):
        pass


def _apply_map(map_kind: str, fn, blocks: list):
    """Runs inside a ray_tpu task/actor: apply fn to the blocks."""
    out_blocks = []
    for block in blocks:
        acc = BlockAccessor.for_block(block)
        if map_kind == "batches":
            out = fn(acc.to_batch())
            out_blocks.append(batch_to_block(out))
        elif map_kind == "rows":
            out_blocks.append([fn(r) for r in acc.iter_rows()])
        elif map_kind == "flat":
            rows = []
            for r in acc.iter_rows():
                rows.extend(fn(r))
            out_blocks.append(rows)
        elif map_kind == "filter":
            out_blocks.append([r for r in acc.iter_rows() if fn(r)])
        else:
            raise ValueError(map_kind)
    merged = concat_blocks(out_blocks)
    acc = BlockAccessor.for_block(merged)
    return merged, acc.num_rows(), acc.size_bytes()


class _MapWorker:
    """Actor holding the map fn (jit caches, models survive across calls)."""

    def __init__(self, map_kind: str, fn_factory):
        self._kind = map_kind
        self._fn = fn_factory() if callable(fn_factory) else fn_factory

    def apply(self, *blocks):
        return _apply_map(self._kind, self._fn, list(blocks))


class MapOperator(PhysicalOperator):
    """Task- or actor-pool map over blocks (MapOperator/TaskPool/ActorPool
    analogs). compute="tasks" | "actors".

    Actor pools AUTOSCALE: during execution the pool grows from
    ``actor_pool_size`` up to ``max_actor_pool_size`` while queued work
    outruns it (reference: ``ActorPoolMapOperator`` +
    ``AutoscalingPolicy``); once the operator's input is DRAINED, idle
    actors retire immediately — the pool is ending anyway, and their
    resources unblock downstream operators."""

    def __init__(self, name: str, map_kind: str, fn,
                 compute: str = "tasks", num_cpus: float = 1,
                 actor_pool_size: int = 2,
                 max_actor_pool_size: int | None = None):
        super().__init__(name)
        self.map_kind = map_kind
        self.fn = fn
        self.compute = compute
        self.num_cpus = num_cpus
        self.actor_pool_size = actor_pool_size
        self.max_actor_pool_size = (max_actor_pool_size
                                    or max(actor_pool_size, 8))
        self._active: list[tuple] = []   # (result_ref, bundle, serial|None)
        self._pool: list = []               # (serial, actor) entries
        # load keyed by pool SERIAL, not id(actor): a killed actor's
        # handle can be garbage-collected and its id() reused by a new
        # spawn, so a late poll() decrement for the old actor would hit
        # the new one and drive its in-flight count negative
        self._pool_load: dict = {}          # serial -> in-flight count
        self._pool_serial = 0

    def num_active_tasks(self) -> int:
        return len(self._active)

    def outstanding_bytes(self) -> int:
        return (super().outstanding_bytes()
                + sum(entry[1].size_bytes for entry in self._active))

    def _spawn_actor(self):
        worker_cls = ray_tpu.remote(_MapWorker)
        actor = worker_cls.options(num_cpus=self.num_cpus).remote(
            self.map_kind, self.fn)
        self._pool_serial += 1
        self._pool.append((self._pool_serial, actor))
        self._pool_load[self._pool_serial] = 0
        self.metrics["actors_started"] = (
            self.metrics.get("actors_started", 0) + 1)
        return actor

    def _ensure_pool(self):
        if self._pool or self.compute != "actors":
            return
        for _ in range(self.actor_pool_size):
            self._spawn_actor()

    def _scale_up(self):
        """Every actor busy AND input still queued → add one (up to
        max). Runs at dispatch time only."""
        busy = all(self._pool_load.get(s, 0) > 0 for s, _ in self._pool)
        if (self.input_queue and busy
                and len(self._pool) < self.max_actor_pool_size):
            self._spawn_actor()

    def _scale_down(self):
        """Input drained → retire idle actors (the operator is winding
        down; resources free up for downstream work). Runs at poll time
        only — scale-down at dispatch time could empty the pool with a
        bundle already popped and waiting for an actor."""
        if not self.all_dispatched():
            return
        for entry in [e for e in self._pool
                      if self._pool_load.get(e[0], 0) == 0]:
            self._pool.remove(entry)
            self._pool_load.pop(entry[0], None)
            try:
                ray_tpu.kill(entry[1])
            except Exception:  # noqa: BLE001
                pass

    def dispatch(self, options: ExecutionOptions):
        if not self.input_queue:
            return
        bundle = self.input_queue.popleft()
        self.metrics["bundles_in"] += 1
        self.metrics["tasks"] += 1
        if self.compute == "actors":
            self._ensure_pool()
            if not self._pool:   # fully retired by a previous drain tick
                self._spawn_actor()
            self._scale_up()
            # least-loaded actor (reference: the pool picks by queue depth)
            serial, actor = min(
                self._pool, key=lambda e: self._pool_load.get(e[0], 0))
            self._pool_load[serial] = self._pool_load.get(serial, 0) + 1
            ref = actor.apply.remote(*bundle.refs)
            self._active.append((ref, bundle, serial, time.monotonic()))
            return
        kind, fn = self.map_kind, self.fn
        apply_remote = ray_tpu.remote(
            lambda *blocks: _apply_map(kind, fn, list(blocks))
        ).options(num_cpus=self.num_cpus)
        ref = apply_remote.remote(*bundle.refs)
        self._active.append((ref, bundle, None, time.monotonic()))

    def poll(self):
        still = []
        for ref, bundle, owner, submit_t in self._active:
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if ready:
                block, rows, nbytes = ray_tpu.get(ref)
                if len(self.stats.task_wall_s) < 10_000:
                    self.stats.task_wall_s.append(
                        time.monotonic() - submit_t)
                if owner is not None and owner in self._pool_load:
                    self._pool_load[owner] -= 1
                for out_block, out_rows, out_bytes in _maybe_split(
                        block, rows, nbytes):
                    self.output_queue.append(RefBundle(
                        [ray_tpu.put(out_block)], num_rows=out_rows,
                        size_bytes=out_bytes))
                self.metrics["bundles_out"] += 1
            else:
                still.append((ref, bundle, owner, submit_t))
        self._active = still
        if self.compute == "actors" and self._pool:
            self._scale_down()

    def shutdown(self):
        for _, actor in self._pool:
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001
                pass
        self._pool = []


def _maybe_split(block, rows: int, nbytes: int):
    """Size-based block splitting (reference: DataContext
    target_max_block_size + output splitting in MapOperator): an
    oversized map output becomes several row-sliced blocks so one fat
    block can't blow the byte budget or a downstream consumer's memory."""
    from ray_tpu.data.context import DataContext

    target = DataContext.get_current().target_max_block_size
    if nbytes <= target or rows <= 1:
        return [(block, rows, nbytes)]
    n_chunks = min(rows, -(-nbytes // target))
    per = -(-rows // n_chunks)
    acc = BlockAccessor.for_block(block)
    out = []
    for start in range(0, rows, per):
        piece = acc.slice(start, min(start + per, rows))
        pacc = BlockAccessor.for_block(piece)
        out.append((piece, pacc.num_rows(), pacc.size_bytes()))
    return out


class AllToAllOperator(PhysicalOperator):
    """Barrier operator (shuffle/sort/repartition): consumes ALL input
    bundles, then emits transformed bundles. Reference: push-based shuffle
    scheduler (_internal/planner/exchange/)."""

    def __init__(self, name: str,
                 transform: Callable[[list[RefBundle]], list[RefBundle]]):
        super().__init__(name)
        self.transform = transform
        self._collected: list[RefBundle] = []
        self._emitted = False

    def can_accept_work(self, options) -> bool:
        # collection is cheap — always drain inputs; the barrier fires when
        # upstream is done
        return bool(self.input_queue) or (
            self.inputs_done and not self._emitted)

    def dispatch(self, options: ExecutionOptions):
        while self.input_queue:
            self._collected.append(self.input_queue.popleft())
            self.metrics["bundles_in"] += 1
        if self.inputs_done and not self._emitted:
            self._emitted = True
            for b in self.transform(self._collected):
                self.output_queue.append(b)
                self.metrics["bundles_out"] += 1

    def is_done(self) -> bool:
        return self._emitted and not self.output_queue


class LimitOperator(PhysicalOperator):
    def __init__(self, limit: int):
        super().__init__(f"Limit[{limit}]")
        self.remaining = limit

    def can_accept_work(self, options) -> bool:
        return bool(self.input_queue)

    def dispatch(self, options: ExecutionOptions):
        while self.input_queue:
            bundle = self.input_queue.popleft()
            if self.remaining <= 0:
                continue
            if bundle.num_rows <= self.remaining:
                self.remaining -= bundle.num_rows
                self.output_queue.append(bundle)
            else:
                block = concat_blocks(ray_tpu.get(list(bundle.refs)))
                acc = BlockAccessor.for_block(block)
                sliced = acc.slice(0, self.remaining)
                self.remaining = 0
                sacc = BlockAccessor.for_block(sliced)
                self.output_queue.append(RefBundle(
                    [ray_tpu.put(sliced)], num_rows=sacc.num_rows(),
                    size_bytes=sacc.size_bytes()))


class StreamingExecutor:
    """Drives a linear operator topology to completion, yielding output
    bundles as they materialize (results stream while upstream still runs).
    """

    def __init__(self, operators: list[PhysicalOperator],
                 options: ExecutionOptions | None = None):
        from ray_tpu.data.stats import DatasetStats

        self.operators = operators
        self.options = options or ExecutionOptions()
        self._byte_budget = _resolve_byte_budget(self.options)
        self.stats = DatasetStats()
        self.stats.operators = [op.stats for op in operators]

    @staticmethod
    def _note_moved(up: PhysicalOperator, down: PhysicalOperator | None,
                    bundle: RefBundle):
        now = time.monotonic()
        s = up.stats
        if s.first_activity is None:
            s.first_activity = now
        s.last_activity = now
        s.bundles_out += 1
        s.bytes_out += bundle.size_bytes
        s.rows_out += bundle.num_rows
        if down is not None:
            d = down.stats
            if d.first_activity is None:
                d.first_activity = now
            d.last_activity = now
            d.bundles_in += 1
            d.bytes_in += bundle.size_bytes

    def execute(self) -> Iterator[RefBundle]:
        ops = self.operators
        try:
            while True:
                progressed = False
                # propagate bundles + doneness downstream
                for i in range(len(ops) - 1):
                    up, down = ops[i], ops[i + 1]
                    while up.output_queue:
                        bundle = up.output_queue.popleft()
                        self._note_moved(up, down, bundle)
                        down.input_queue.append(bundle)
                        progressed = True
                    if up.is_done() and not down.inputs_done:
                        down.inputs_done = True
                        progressed = True
                # stream final outputs
                tail = ops[-1]
                while tail.output_queue:
                    progressed = True
                    bundle = tail.output_queue.popleft()
                    self._note_moved(tail, None, bundle)
                    yield bundle
                if tail.is_done():
                    return
                # pick operators to run: furthest-downstream first
                # (select_operator_to_run analog). Byte-budget admission
                # (_execution_allowed analog): once the topology holds
                # more than the budget, only the most-downstream runnable
                # operator may dispatch — it shrinks the resident set;
                # upstream dispatch would grow it.
                over_budget = (sum(op.outstanding_bytes() for op in ops)
                               > self._byte_budget)
                drained_one = False
                for op in reversed(ops):
                    op.poll()
                    while op.can_accept_work(self.options):
                        if over_budget and drained_one:
                            break
                        op.dispatch(self.options)
                        drained_one = True
                        progressed = True
                if not progressed:
                    time.sleep(0.002)
        finally:
            self.stats.end_t = time.monotonic()
            for op in ops:
                op.stats.tasks = op.metrics.get("tasks", 0)
                op.shutdown()
