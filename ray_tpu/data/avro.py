"""Avro Object Container File IO without an avro library.

Reference analog: ``python/ray/data/datasource/avro_datasource.py``
(which binds the ``avro`` package). The container format (spec: Apache
Avro 1.11, "Object Container Files") and the binary encoding are simple
enough to speak directly:

- File = magic ``Obj\\x01`` | metadata map (``avro.schema`` JSON,
  ``avro.codec``) | 16-byte sync marker, then data blocks of
  ``long count | long byte-size | payload | sync``.
- Binary encoding: zigzag-varint ints/longs, little-endian IEEE
  float/double, length-prefixed bytes/UTF-8 strings, records as field
  concatenation, arrays/maps as counted blocks with a 0 terminator,
  unions as branch-index + value, enums as index, fixed as raw bytes.
- Codecs: ``null`` and ``deflate`` (raw zlib, no header — RFC 1951).

The writer infers a record schema from the first row when none is given
(None → nullable union, int → long, float → double, str/bytes/bool as
themselves).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib

import numpy as _np

MAGIC = b"Obj\x01"
SYNC_SIZE = 16


# ---------------------------------------------------------------------------
# primitive binary codec
# ---------------------------------------------------------------------------

def _read_long(buf: io.BytesIO) -> int:
    """Zigzag varint (int and long share the encoding)."""
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated avro varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_long(out: io.BytesIO, n: int):
    n = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            break


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated avro bytes")
    return data


def _write_bytes(out: io.BytesIO, data: bytes):
    _write_long(out, len(data))
    out.write(data)


# ---------------------------------------------------------------------------
# schema-driven decode
# ---------------------------------------------------------------------------

def _decode(schema, buf: io.BytesIO, names: dict):
    """Decode one value of ``schema``. ``names`` maps named-type
    fullnames to their definitions (records/enums/fixed referenced by
    name elsewhere in the schema)."""
    if isinstance(schema, list):                       # union
        idx = _read_long(buf)
        if not 0 <= idx < len(schema):
            raise ValueError(f"union branch {idx} out of range")
        return _decode(schema[idx], buf, names)
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            _register(schema, names)
            return {f["name"]: _decode(f["type"], buf, names)
                    for f in schema["fields"]}
        if t == "enum":
            _register(schema, names)
            return schema["symbols"][_read_long(buf)]
        if t == "fixed":
            _register(schema, names)
            return buf.read(schema["size"])
        if t == "array":
            out = []
            while True:
                count = _read_long(buf)
                if count == 0:
                    return out
                if count < 0:          # block with byte-size prefix
                    count = -count
                    _read_long(buf)    # skip block size
                for _ in range(count):
                    out.append(_decode(schema["items"], buf, names))
        if t == "map":
            out = {}
            while True:
                count = _read_long(buf)
                if count == 0:
                    return out
                if count < 0:
                    count = -count
                    _read_long(buf)
                for _ in range(count):
                    key = _read_bytes(buf).decode("utf-8")
                    out[key] = _decode(schema["values"], buf, names)
        # logical types / wrapped primitives: {"type": "long", ...}
        return _decode(t, buf, names)
    # named-type reference or primitive
    if schema in names:
        return _decode(names[schema], buf, names)
    if schema == "null":
        return None
    if schema == "boolean":
        b = buf.read(1)
        if not b:
            raise EOFError("truncated avro boolean")
        return b == b"\x01"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode("utf-8")
    raise ValueError(f"unsupported avro type {schema!r}")


def _register(schema: dict, names: dict):
    name = schema.get("name")
    if name:
        ns = schema.get("namespace")
        names[f"{ns}.{name}" if ns else name] = schema
        names.setdefault(name, schema)


# ---------------------------------------------------------------------------
# schema-driven encode
# ---------------------------------------------------------------------------

def _encode(schema, value, out: io.BytesIO, names: dict):
    if isinstance(schema, list):                       # union
        for idx, branch in enumerate(schema):
            if _matches(branch, value, names):
                _write_long(out, idx)
                _encode(branch, value, out, names)
                return
        raise TypeError(f"{value!r} matches no union branch {schema}")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            _register(schema, names)
            for f in schema["fields"]:
                _encode(f["type"], value[f["name"]], out, names)
            return
        if t == "enum":
            _write_long(out, schema["symbols"].index(value))
            return
        if t == "fixed":
            if len(value) != schema["size"]:
                raise ValueError(
                    f"fixed {schema.get('name', '?')} wants "
                    f"{schema['size']} bytes, got {len(value)}")
            out.write(value)
            return
        if t == "array":
            if value:
                _write_long(out, len(value))
                for item in value:
                    _encode(schema["items"], item, out, names)
            _write_long(out, 0)
            return
        if t == "map":
            if value:
                _write_long(out, len(value))
                for k, v in value.items():
                    _write_bytes(out, k.encode("utf-8"))
                    _encode(schema["values"], v, out, names)
            _write_long(out, 0)
            return
        _encode(t, value, out, names)
        return
    if schema in names:
        _encode(names[schema], value, out, names)
        return
    if schema == "null":
        return
    if schema == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif schema in ("int", "long"):
        if isinstance(value, _np.integer):
            value = int(value)  # numpy integer scalars are lossless
        if not isinstance(value, int) or isinstance(value, bool):
            # int(2.7) would silently truncate — schema/value drift
            # (e.g. a float in a column inferred as long) must surface
            raise TypeError(
                f"avro {schema} field got {type(value).__name__} "
                f"{value!r}")
        _write_long(out, value)
    elif schema == "float":
        out.write(struct.pack("<f", float(value)))
    elif schema == "double":
        out.write(struct.pack("<d", float(value)))
    elif schema == "bytes":
        _write_bytes(out, bytes(value))
    elif schema == "string":
        _write_bytes(out, str(value).encode("utf-8"))
    else:
        raise ValueError(f"unsupported avro type {schema!r}")


def _matches(schema, value, names) -> bool:
    t = schema["type"] if isinstance(schema, dict) else schema
    if t in names:
        return _matches(names[t], value, names)
    if t == "null":
        return value is None
    if t == "boolean":
        return isinstance(value, (bool, _np.bool_))
    if t in ("int", "long"):
        return (isinstance(value, (int, _np.integer))
                and not isinstance(value, (bool, _np.bool_)))
    if t in ("float", "double"):
        return isinstance(value, (float, _np.floating))
    if t == "bytes" or t == "fixed":
        return isinstance(value, (bytes, bytearray))
    if t == "string":
        return isinstance(value, str)
    if t == "record" or t == "map":
        return isinstance(value, dict)
    if t == "array":
        return isinstance(value, (list, tuple))
    if t == "enum":
        return isinstance(value, str)
    return False


# ---------------------------------------------------------------------------
# container file
# ---------------------------------------------------------------------------

def iter_avro(data: bytes):
    """Yield one dict (or value) per record from container-file bytes."""
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError("not an avro object container file (bad magic)")
    meta = {}
    while True:
        count = _read_long(buf)
        if count == 0:
            break
        if count < 0:
            count = -count
            _read_long(buf)
        for _ in range(count):
            key = _read_bytes(buf).decode("utf-8")
            meta[key] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = buf.read(SYNC_SIZE)
    names: dict = {}
    while True:
        probe = buf.read(1)
        if not probe:
            return
        buf.seek(-1, os.SEEK_CUR)
        count = _read_long(buf)
        size = _read_long(buf)
        payload = buf.read(size)
        if len(payload) != size:
            raise EOFError("truncated avro block")
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        block = io.BytesIO(payload)
        for _ in range(count):
            yield _decode(schema, block, names)
        if buf.read(SYNC_SIZE) != sync:
            raise ValueError("avro sync marker mismatch (corrupt block)")


def infer_schema(row: dict, *, name: str = "row") -> dict:
    """Record schema from a sample row (None → a wide nullable union so
    later rows can hold any primitive; int → long, float → double)."""
    def typeof(v):
        if v is None:
            return ["null", "boolean", "long", "double", "bytes",
                    "string"]
        if isinstance(v, (bool, _np.bool_)):
            return "boolean"
        if isinstance(v, (int, _np.integer)):
            return "long"
        if isinstance(v, (float, _np.floating)):
            return "double"
        if isinstance(v, (bytes, bytearray)):
            return "bytes"
        if isinstance(v, str):
            return "string"
        if isinstance(v, (list, tuple)):
            item = typeof(v[0]) if v else "string"
            return {"type": "array", "items": item}
        if isinstance(v, dict):
            val = typeof(next(iter(v.values()))) if v else "string"
            return {"type": "map", "values": val}
        raise TypeError(f"cannot infer avro type for {type(v).__name__}")

    return {"type": "record", "name": name,
            "fields": [{"name": k, "type": typeof(v)}
                       for k, v in row.items()]}


def write_avro(rows, schema: dict | None = None, *,
               codec: str = "null", sync: bytes = b"\x07" * 16,
               block_records: int = 1000) -> bytes:
    """Encode dict rows into container-file bytes."""
    rows = list(rows)
    if schema is None:
        if not rows:
            raise ValueError("cannot infer a schema from zero rows")
        schema = infer_schema(rows[0])
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8")}
    _write_long(out, len(meta))
    for k, v in meta.items():
        _write_bytes(out, k.encode("utf-8"))
        _write_bytes(out, v)
    _write_long(out, 0)
    out.write(sync)
    names: dict = {}
    for start in range(0, len(rows), block_records):
        chunk = rows[start:start + block_records]
        body = io.BytesIO()
        for row in chunk:
            _encode(schema, row, body, names)
        payload = body.getvalue()
        if codec == "deflate":
            payload = zlib.compress(payload)[2:-4]  # raw RFC1951
        _write_long(out, len(chunk))
        _write_bytes(out, payload)
        out.write(sync)
    return out.getvalue()
