"""MongoDB datasource: ``read_mongo`` / ``write_mongo``.

Reference analog: ``python/ray/data/datasource/mongo_datasource.py`` —
Ray Data's Mongo reader takes connection parameters + db/collection and
materializes documents as rows; the writer inserts rows back.

Connection crosses task boundaries as a FACTORY (live clients aren't
picklable), same contract as ``read_sql``. The factory must return an
object with the pymongo ``Collection`` surface (``find``,
``insert_many``, plus ``database.client.close`` if closable) — pymongo
itself is therefore an optional dependency: anything duck-typing the
Collection API (a test double, a REST shim) works."""

from __future__ import annotations

from typing import Callable

from ray_tpu.data.dataset import Dataset, from_items


def read_mongo(collection_factory: Callable, *,
               query: dict | None = None,
               projection: dict | None = None,
               num_blocks: int = 8) -> Dataset:
    """Materialize ``collection.find(query, projection)`` as a row
    Dataset (reference: ``ray.data.read_mongo``). The ``_id`` field is
    stringified (ObjectId isn't a plain-data type)."""

    def source():
        coll = collection_factory()
        try:
            cursor = (coll.find(query or {}, projection)
                      if projection is not None else coll.find(query or {}))
            rows = []
            for doc in cursor:
                doc = dict(doc)
                if "_id" in doc:
                    doc["_id"] = str(doc["_id"])
                rows.append(doc)
        finally:
            _close(coll)
        return from_items(rows, num_blocks=num_blocks)._source_fn()

    return Dataset(source)


def write_mongo(ds: Dataset, collection_factory: Callable) -> None:
    """Insert every row as a document (reference:
    ``Dataset.write_mongo``): ``insert_many`` per block."""
    from ray_tpu.data.sql import rows_from_batch

    coll = collection_factory()
    try:
        for batch in ds.iter_batches():
            docs = rows_from_batch(batch)
            if docs:
                coll.insert_many(docs)
    finally:
        _close(coll)


def _close(coll):
    try:
        coll.database.client.close()
    except Exception:  # noqa: BLE001 - duck-typed double without close
        pass
