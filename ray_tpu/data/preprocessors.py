"""Preprocessors: fit/transform feature pipelines over Datasets.

Reference analog: ``python/ray/data/preprocessors/`` (Preprocessor base
in ``preprocessor.py``; scalers, encoders, Concatenator, BatchMapper,
Chain). Fitting runs through the distributed aggregate layer
(ray_tpu.data.aggregate) so statistics are computed per block and merged
— the dataset never materializes centrally. Transforms are plain
``map_batches`` so they stream.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.data.aggregate import Max, Mean, Min, Std


class Preprocessor:
    """fit(ds) learns state; transform(ds) applies it lazily."""

    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted and type(self)._fit is not Preprocessor._fit:
            raise RuntimeError(
                f"{type(self).__name__} must be fit before transform")
        return ds.map_batches(self._transform_batch)

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: dict) -> dict:
        """Apply to a single in-memory batch (serving-time path)."""
        return self._transform_batch(dict(batch))

    # -- subclass hooks --------------------------------------------------

    def _fit(self, ds):  # stateless preprocessors skip this
        pass

    def _transform_batch(self, batch: dict) -> dict:
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.stats_: dict = {}

    def _fit(self, ds):
        aggs = []
        for c in self.columns:
            aggs += [Mean(c), Std(c, ddof=0)]
        out = ds.aggregate(*aggs)
        self.stats_ = {
            c: (out[f"mean({c})"], out[f"std({c})"] or 1.0)
            for c in self.columns
        }

    def _transform_batch(self, batch):
        batch = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            std = std if std else 1.0
            batch[c] = (np.asarray(batch[c], dtype=np.float64) - mean) / std
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.stats_: dict = {}

    def _fit(self, ds):
        aggs = []
        for c in self.columns:
            aggs += [Min(c), Max(c)]
        out = ds.aggregate(*aggs)
        self.stats_ = {c: (out[f"min({c})"], out[f"max({c})"])
                       for c in self.columns}

    def _transform_batch(self, batch):
        batch = dict(batch)
        for c in self.columns:
            lo, hi = self.stats_[c]
            denom = (hi - lo) or 1.0
            batch[c] = (np.asarray(batch[c], dtype=np.float64) - lo) / denom
        return batch


class LabelEncoder(Preprocessor):
    """Categorical column -> dense int codes (sorted value order)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: list = []

    def _fit(self, ds):
        self.classes_ = ds.unique(self.label_column)

    def _transform_batch(self, batch):
        batch = dict(batch)
        lookup = {v: i for i, v in enumerate(self.classes_)}
        col = batch[self.label_column]
        batch[self.label_column] = np.asarray(
            [lookup[v.item() if hasattr(v, "item") else v] for v in col],
            dtype=np.int64)
        return batch


class OneHotEncoder(Preprocessor):
    """Categorical columns -> one-hot 0/1 columns named col_value."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.classes_: dict = {}

    def _fit(self, ds):
        self.classes_ = {c: ds.unique(c) for c in self.columns}

    def _transform_batch(self, batch):
        batch = dict(batch)
        for c in self.columns:
            vals = np.asarray(batch.pop(c))
            for cls in self.classes_[c]:
                batch[f"{c}_{cls}"] = (vals == cls).astype(np.int64)
        return batch


class Concatenator(Preprocessor):
    """Merge feature columns into one float matrix column (the layout the
    trainer feeds to the device)."""

    def __init__(self, columns: list[str], output_column_name: str = "features",
                 dtype=np.float32):
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _transform_batch(self, batch):
        batch = dict(batch)
        mats = []
        for c in self.columns:
            col = np.asarray(batch.pop(c))
            mats.append(col[:, None] if col.ndim == 1 else col)
        batch[self.output_column_name] = np.concatenate(
            mats, axis=1).astype(self.dtype)
        return batch


class BatchMapper(Preprocessor):
    """Wrap an arbitrary batch function as a (stateless) preprocessor."""

    def __init__(self, fn):
        self.fn = fn

    def _transform_batch(self, batch):
        return self.fn(dict(batch))


class Chain(Preprocessor):
    """Sequential composition; fit runs left to right on progressively
    transformed data (same as the reference's Chain)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)

    def fit(self, ds):
        cur = ds
        for st in self.stages:
            st.fit(cur)
            cur = st.transform(cur)
        self._fitted = True
        return self

    def _transform_batch(self, batch):
        for st in self.stages:
            batch = st._transform_batch(dict(batch))
        return batch
