"""Lazy DAG authoring via .bind() (reference: ``python/ray/dag/``, P20).

``fn.bind(*args)`` builds a ``FunctionNode`` without executing; nodes
compose into a DAG whose ``execute()`` submits the whole graph as tasks,
wiring upstream results as ObjectRef args (so intermediate values never
materialize on the driver). Used by workflow (durable execution) and by
Serve's graph API in the reference.
"""

from __future__ import annotations

from typing import Any

import ray_tpu


class DAGNode:
    def __init__(self, fn, args, kwargs, *, options=None):
        self._fn = fn
        self._args = args
        self._kwargs = kwargs
        self._options = options or {}

    # -- traversal -------------------------------------------------------
    def _upstream(self) -> list["DAGNode"]:
        out = [a for a in self._args if isinstance(a, DAGNode)]
        out += [v for v in self._kwargs.values() if isinstance(v, DAGNode)]
        return out

    def topo_order(self) -> list["DAGNode"]:
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen.add(id(node))
            for up in node._upstream():
                visit(up)
            order.append(node)

        visit(self)
        return order

    # -- execution -------------------------------------------------------
    def execute(self) -> Any:
        """Submit the DAG; returns the final ObjectRef."""
        refs: dict[int, Any] = {}
        for node in self.topo_order():
            args = [refs[id(a)] if isinstance(a, DAGNode) else a
                    for a in node._args]
            kwargs = {k: refs[id(v)] if isinstance(v, DAGNode) else v
                      for k, v in node._kwargs.items()}
            remote_fn = ray_tpu.remote(node._fn)
            # workflow_* options are consumed by workflow.run's step
            # driver, not the task API
            opts = {k: v for k, v in (node._options or {}).items()
                    if not k.startswith("workflow_")}
            if opts:
                remote_fn = remote_fn.options(**opts)
            refs[id(node)] = remote_fn.remote(*args, **kwargs)
        return refs[id(self)]

    def options(self, **opts) -> "DAGNode":
        return DAGNode(self._fn, self._args, self._kwargs, options=opts)

    def __repr__(self):
        return f"DAGNode({getattr(self._fn, '__name__', '?')})"


class InputNode(DAGNode):
    """Placeholder for runtime input (reference: ``dag/input_node.py``)."""

    def __init__(self):
        super().__init__(None, (), {})
        self._value = None

    def execute(self):
        raise TypeError("InputNode cannot be executed directly")


def bind(fn, *args, **kwargs) -> DAGNode:
    """Functional form: ``dag.bind(f, x)`` == f.bind(x)."""
    options = None
    if hasattr(fn, "underlying_function"):  # RemoteFunction from @remote
        options = getattr(fn, "_options", None)
        fn = fn.underlying_function
    return DAGNode(fn, args, kwargs, options=options)


def execute_with_input(root: DAGNode, input_value) -> Any:
    """Execute a DAG containing an InputNode, substituting the value."""
    refs: dict[int, Any] = {}
    for node in root.topo_order():
        if isinstance(node, InputNode):
            refs[id(node)] = input_value
            continue
        args = [refs[id(a)] if isinstance(a, DAGNode) else a
                for a in node._args]
        kwargs = {k: refs[id(v)] if isinstance(v, DAGNode) else v
                  for k, v in node._kwargs.items()}
        remote_fn = ray_tpu.remote(node._fn)
        if node._options:
            remote_fn = remote_fn.options(**node._options)
        refs[id(node)] = remote_fn.remote(*args, **kwargs)
    return refs[id(root)]
