"""Normalization ops (TPU-first: fp32 accumulation inside bf16 models)."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x, weight, *, eps: float = 1e-5):
    """RMSNorm with float32 statistics regardless of input dtype.

    The variance reduction runs in fp32 (VPU) and the result is cast back, so
    bf16 activations don't lose precision in the norm — the standard TPU
    recipe; XLA fuses the whole thing into one elementwise kernel.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x, weight, bias=None, *, eps: float = 1e-5):
    """LayerNorm, fp32 statistics, optional bias."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    normed = (xf - mean) * lax.rsqrt(var + eps)
    out = normed * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)
