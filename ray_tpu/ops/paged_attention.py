"""Paged KV-cache attention for serving.

Reference: ABSENT from the reference repo (it serves models via user
code in replicas — SURVEY P15); this is the vLLM-style PagedAttention
scheme rebuilt TPU-first: the KV cache is a pool of fixed-size pages,
each sequence owns a page table, and the decode step gathers its pages
with static shapes (gather + mask — XLA-friendly; a Pallas kernel can
swap in later without changing the interface).

Why paging: the slot-based cache (ray_tpu/models/decoding.py KVCache)
reserves max_len per slot — a 2048-token cache for an 80-token chat
wastes 96% of its HBM. Pages allocate on demand, so max_batch scales
with TOKENS in flight, not worst-case sequence length.

Layout:
    k_pages, v_pages: [L, n_pages, page_size, n_kv, head_dim]
    page_table:       [B, max_pages_per_seq] int32 (−1 = unused)
    lengths:          [B] int32 tokens currently cached per sequence
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class PagedKVCache:
    """K/V pages live on device; the page table and lengths are HOST
    numpy — they're scheduler bookkeeping mutated per request per step,
    and keeping them host-side avoids a device round-trip + sync on
    every allocation (they ship to the device per attention call, a few
    hundred bytes)."""

    k_pages: jax.Array       # [L, P, page, nkv, hd]
    v_pages: jax.Array
    page_table: np.ndarray   # [B, max_pages] int32, -1 = hole
    lengths: np.ndarray      # [B] int32


def init_paged_cache(cfg, *, num_pages: int, page_size: int,
                     max_batch: int, max_pages_per_seq: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    nkv = getattr(cfg, "n_kv_heads", None) or cfg.n_heads
    hd = cfg.head_dim
    shape = (cfg.n_layers, num_pages, page_size, nkv, hd)
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        page_table=np.full((max_batch, max_pages_per_seq), -1, np.int32),
        lengths=np.zeros((max_batch,), np.int32),
    )


class PageAllocator:
    """Host-side free-list of page ids (the serving engine's bookkeeping;
    device tensors never see allocation logic)."""

    def __init__(self, num_pages: int):
        self.free = list(range(num_pages - 1, -1, -1))
        self.owned: dict[int, list[int]] = {}  # seq slot -> page ids

    def alloc(self, slot: int, n: int) -> list[int]:
        if len(self.free) < n:
            raise MemoryError(
                f"paged KV cache exhausted: need {n} pages, "
                f"{len(self.free)} free")
        pages = [self.free.pop() for _ in range(n)]
        self.owned.setdefault(slot, []).extend(pages)
        return pages

    def free_slot(self, slot: int):
        for p in self.owned.pop(slot, []):
            self.free.append(p)

    def pages_needed(self, cur_len: int, new_tokens: int,
                     page_size: int) -> int:
        have = (cur_len + page_size - 1) // page_size
        need = (cur_len + new_tokens + page_size - 1) // page_size
        return need - have


def paged_write(cache: PagedKVCache, layer: int, slot, k_new, v_new,
                start) -> PagedKVCache:
    """Append k_new/v_new [T, nkv, hd] for one sequence at position
    `start` (its current length). Positions map to
    (page_table[slot][pos // page], pos % page). A position landing on
    an unassigned table hole (-1) is DROPPED, never written: -1 would
    wrap to the last page and silently corrupt another sequence's KV.

    PERF: each functional .at[].set copies the whole multi-layer page
    pool when run eagerly — call this inside jit with the cache arrays
    donated (XLA then updates in place), or write every layer at once
    with paged_write_all."""
    page_size = cache.k_pages.shape[2]
    num_pages = cache.k_pages.shape[1]
    t = k_new.shape[0]
    pos = start + np.arange(t)
    page_idx = cache.page_table[slot][pos // page_size]  # [T] host
    # holes -> out-of-bounds index + mode="drop" (loud alternative:
    # callers should assign_pages first; see assign_pages' guard)
    page_idx = np.where(page_idx >= 0, page_idx, num_pages)
    in_page = pos % page_size

    k_pages = cache.k_pages.at[layer, jnp.asarray(page_idx),
                               jnp.asarray(in_page)].set(
        k_new.astype(cache.k_pages.dtype), mode="drop")
    v_pages = cache.v_pages.at[layer, jnp.asarray(page_idx),
                               jnp.asarray(in_page)].set(
        v_new.astype(cache.v_pages.dtype), mode="drop")
    return PagedKVCache(k_pages, v_pages, cache.page_table, cache.lengths)


def paged_write_all(cache: PagedKVCache, slot, k_new, v_new,
                    start) -> PagedKVCache:
    """Append k_new/v_new [L, T, nkv, hd] for ALL layers in one indexed
    update per tensor (one pool copy eagerly, in-place under jit) —
    the per-decode-step entry point."""
    page_size = cache.k_pages.shape[2]
    num_pages = cache.k_pages.shape[1]
    t = k_new.shape[1]
    pos = start + np.arange(t)
    page_idx = cache.page_table[slot][pos // page_size]
    page_idx = np.where(page_idx >= 0, page_idx, num_pages)
    in_page = pos % page_size
    k_pages = cache.k_pages.at[:, jnp.asarray(page_idx),
                               jnp.asarray(in_page)].set(
        k_new.astype(cache.k_pages.dtype), mode="drop")
    v_pages = cache.v_pages.at[:, jnp.asarray(page_idx),
                               jnp.asarray(in_page)].set(
        v_new.astype(cache.v_pages.dtype), mode="drop")
    return PagedKVCache(k_pages, v_pages, cache.page_table, cache.lengths)


def paged_attention(q, cache: PagedKVCache, layer: int, *,
                    scale: float | None = None):
    """Decode-step attention: q [B, n_heads, hd] against each sequence's
    paged KV. Gathers each sequence's pages into a contiguous
    [max_pages*page, nkv, hd] view (static shape) and masks beyond
    `lengths`. Supports GQA (n_heads a multiple of n_kv)."""
    b, nh, hd = q.shape
    page_size = cache.k_pages.shape[2]
    nkv = cache.k_pages.shape[3]
    max_pages = cache.page_table.shape[1]
    if scale is None:
        scale = hd ** -0.5
    n_rep = nh // nkv

    # gather pages: [B, max_pages, page, nkv, hd]; holes (-1) clamp to
    # page 0 and are masked out by `lengths`
    table = jnp.maximum(jnp.asarray(cache.page_table), 0)
    k = cache.k_pages[layer][table]
    v = cache.v_pages[layer][table]
    s = max_pages * page_size
    k = k.reshape(b, s, nkv, hd)
    v = v.reshape(b, s, nkv, hd)

    qg = q.reshape(b, nkv, n_rep, hd)
    logits = jnp.einsum("bgrd,bkgd->bgrk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(s)
    lengths = jnp.asarray(cache.lengths)
    mask = kpos[None, :] < lengths[:, None]                # [B, S]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bgrk,bkgd->bgrd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, nh, hd).astype(q.dtype)


def quantize_kv(x):
    """Per-token-per-head symmetric int8 quantization of a K or V tensor
    over its trailing head_dim axis: returns (int8 values, f32 scales
    with the trailing axis dropped). Halves KV HBM (the pool holds 2x
    the tokens) at <1% relative error — the standard serving-engine KV
    compression (w8 KV in vLLM/TGI terms)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kv(q, scale, dtype=jnp.bfloat16):
    """Inverse of quantize_kv (scale broadcast over head_dim)."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def page_hashes(tokens: np.ndarray, page_size: int) -> list[bytes]:
    """Chained content hashes of the FULL pages of a token sequence —
    hash i covers tokens[0 : (i+1)*page_size], so equal hash means equal
    whole prefix (the prefix-cache key; vLLM's automatic prefix caching
    uses the same chained-block-hash scheme). Partial trailing pages are
    never hashed: only fully-written pages are shareable."""
    out: list[bytes] = []
    h = b""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    for i in range(len(toks) // page_size):
        h = hashlib.blake2b(
            h + toks[i * page_size:(i + 1) * page_size].tobytes(),
            digest_size=16).digest()
        out.append(h)
    return out


class PrefixCache:
    """Host-side prefix-page registry: chained page hash -> page id, with
    per-page refcounts and LRU eviction of unreferenced pages.

    A page is in exactly one of three states: SHARED (refs > 0 — mapped
    by at least one live slot's table; never evictable, never written),
    CACHED-IDLE (refs == 0, still holds valid KV; evictable), or gone
    (evicted — the id returned to the allocator's free list and its hash
    mapping dropped, so no future lookup can see stale contents)."""

    def __init__(self):
        self._by_hash: dict[bytes, int] = {}
        self._hash_of: dict[int, bytes] = {}
        self._refs: dict[int, int] = {}
        self._idle: OrderedDict[int, None] = OrderedDict()  # LRU order
        self.hit_pages = 0
        self.miss_pages = 0

    def acquire(self, hashes: list[bytes]) -> list[int]:
        """Longest contiguous run of cached pages for a hash chain; each
        returned page's refcount is bumped (caller owns one release)."""
        pages: list[int] = []
        for hsh in hashes:
            page = self._by_hash.get(hsh)
            if page is None:
                self.miss_pages += 1
                break
            pages.append(page)
            self.hit_pages += 1
            self._refs[page] = self._refs.get(page, 0) + 1
            self._idle.pop(page, None)
        return pages

    def release(self, pages: list[int]):
        """Drop one reference per page; unreferenced pages stay cached
        but become evictable (most recently released = evicted last)."""
        for page in pages:
            n = self._refs.get(page, 0) - 1
            if n > 0:
                self._refs[page] = n
            else:
                self._refs.pop(page, None)
                if page in self._hash_of:
                    self._idle[page] = None
                    self._idle.move_to_end(page)

    def ref(self, page: int):
        self._refs[page] = self._refs.get(page, 0) + 1
        self._idle.pop(page, None)

    def insert(self, hsh: bytes, page: int) -> bool:
        """Register a freshly prefilled full page. False when the hash is
        already cached (a concurrent identical prompt won registration;
        the caller keeps its copy exclusive)."""
        if hsh in self._by_hash:
            return False
        self._by_hash[hsh] = page
        self._hash_of[page] = hsh
        return True

    def evictable(self) -> int:
        return len(self._idle)

    def evict(self, n: int) -> list[int]:
        """Drop up to n least-recently-released idle pages from the
        cache; the returned ids are free for reallocation (their hash
        mappings are gone, so no lookup can alias the recycled page)."""
        out: list[int] = []
        while self._idle and len(out) < n:
            page, _ = self._idle.popitem(last=False)
            hsh = self._hash_of.pop(page)
            self._by_hash.pop(hsh, None)
            out.append(page)
        return out


# ---------------------------------------------------------------------------
# host-side helpers for the serving engine
# ---------------------------------------------------------------------------

def assign_pages(cache: PagedKVCache, allocator: PageAllocator, slot: int,
                 new_tokens: int) -> PagedKVCache:
    """Grow `slot`'s page table to cover new_tokens more positions.
    Raises MemoryError (the allocator's exhaustion contract) when the
    sequence would outgrow max_pages_per_seq — not an opaque numpy
    broadcast error."""
    page_size = cache.k_pages.shape[2]
    max_pages = cache.page_table.shape[1]
    cur = int(cache.lengths[slot])
    n_new = allocator.pages_needed(cur, new_tokens, page_size)
    if n_new == 0:
        return cache
    have = (cur + page_size - 1) // page_size
    if have + n_new > max_pages:
        raise MemoryError(
            f"sequence in slot {slot} needs {have + n_new} pages, over "
            f"max_pages_per_seq={max_pages}")
    pages = allocator.alloc(slot, n_new)
    cache.page_table[slot, have:have + n_new] = pages  # host, in place
    return cache


def release_slot(cache: PagedKVCache, allocator: PageAllocator,
                 slot: int) -> PagedKVCache:
    allocator.free_slot(slot)
    cache.page_table[slot, :] = -1
    cache.lengths[slot] = 0
    return cache
