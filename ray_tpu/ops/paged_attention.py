"""Paged KV-cache attention for serving.

Reference: ABSENT from the reference repo (it serves models via user
code in replicas — SURVEY P15); this is the vLLM-style PagedAttention
scheme rebuilt TPU-first: the KV cache is a pool of fixed-size pages,
each sequence owns a page table, and the decode step gathers its pages
with static shapes (gather + mask — XLA-friendly; a Pallas kernel can
swap in later without changing the interface).

Why paging: the slot-based cache (ray_tpu/models/decoding.py KVCache)
reserves max_len per slot — a 2048-token cache for an 80-token chat
wastes 96% of its HBM. Pages allocate on demand, so max_batch scales
with TOKENS in flight, not worst-case sequence length.

Layout:
    k_pages, v_pages: [L, n_pages, page_size, n_kv, head_dim]
    page_table:       [B, max_pages_per_seq] int32 (−1 = unused)
    lengths:          [B] int32 tokens currently cached per sequence
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class PagedKVCache:
    """K/V pages live on device; the page table and lengths are HOST
    numpy — they're scheduler bookkeeping mutated per request per step,
    and keeping them host-side avoids a device round-trip + sync on
    every allocation (they ship to the device per attention call, a few
    hundred bytes)."""

    k_pages: jax.Array       # [L, P, page, nkv, hd]
    v_pages: jax.Array
    page_table: np.ndarray   # [B, max_pages] int32, -1 = hole
    lengths: np.ndarray      # [B] int32


def init_paged_cache(cfg, *, num_pages: int, page_size: int,
                     max_batch: int, max_pages_per_seq: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    nkv = getattr(cfg, "n_kv_heads", None) or cfg.n_heads
    hd = cfg.head_dim
    shape = (cfg.n_layers, num_pages, page_size, nkv, hd)
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        page_table=np.full((max_batch, max_pages_per_seq), -1, np.int32),
        lengths=np.zeros((max_batch,), np.int32),
    )


class PageAllocator:
    """Host-side free-list of page ids (the serving engine's bookkeeping;
    device tensors never see allocation logic)."""

    def __init__(self, num_pages: int):
        self.free = list(range(num_pages - 1, -1, -1))
        self.owned: dict[int, list[int]] = {}  # seq slot -> page ids

    def alloc(self, slot: int, n: int) -> list[int]:
        if len(self.free) < n:
            raise MemoryError(
                f"paged KV cache exhausted: need {n} pages, "
                f"{len(self.free)} free")
        pages = [self.free.pop() for _ in range(n)]
        self.owned.setdefault(slot, []).extend(pages)
        return pages

    def free_slot(self, slot: int):
        for p in self.owned.pop(slot, []):
            self.free.append(p)

    def pages_needed(self, cur_len: int, new_tokens: int,
                     page_size: int) -> int:
        have = (cur_len + page_size - 1) // page_size
        need = (cur_len + new_tokens + page_size - 1) // page_size
        return need - have


def paged_write(cache: PagedKVCache, layer: int, slot, k_new, v_new,
                start) -> PagedKVCache:
    """Append k_new/v_new [T, nkv, hd] for one sequence at position
    `start` (its current length). Positions map to
    (page_table[slot][pos // page], pos % page). A position landing on
    an unassigned table hole (-1) is DROPPED, never written: -1 would
    wrap to the last page and silently corrupt another sequence's KV.

    PERF: each functional .at[].set copies the whole multi-layer page
    pool when run eagerly — call this inside jit with the cache arrays
    donated (XLA then updates in place), or write every layer at once
    with paged_write_all."""
    page_size = cache.k_pages.shape[2]
    num_pages = cache.k_pages.shape[1]
    t = k_new.shape[0]
    pos = start + np.arange(t)
    page_idx = cache.page_table[slot][pos // page_size]  # [T] host
    # holes -> out-of-bounds index + mode="drop" (loud alternative:
    # callers should assign_pages first; see assign_pages' guard)
    page_idx = np.where(page_idx >= 0, page_idx, num_pages)
    in_page = pos % page_size

    k_pages = cache.k_pages.at[layer, jnp.asarray(page_idx),
                               jnp.asarray(in_page)].set(
        k_new.astype(cache.k_pages.dtype), mode="drop")
    v_pages = cache.v_pages.at[layer, jnp.asarray(page_idx),
                               jnp.asarray(in_page)].set(
        v_new.astype(cache.v_pages.dtype), mode="drop")
    return PagedKVCache(k_pages, v_pages, cache.page_table, cache.lengths)


def paged_write_all(cache: PagedKVCache, slot, k_new, v_new,
                    start) -> PagedKVCache:
    """Append k_new/v_new [L, T, nkv, hd] for ALL layers in one indexed
    update per tensor (one pool copy eagerly, in-place under jit) —
    the per-decode-step entry point."""
    page_size = cache.k_pages.shape[2]
    num_pages = cache.k_pages.shape[1]
    t = k_new.shape[1]
    pos = start + np.arange(t)
    page_idx = cache.page_table[slot][pos // page_size]
    page_idx = np.where(page_idx >= 0, page_idx, num_pages)
    in_page = pos % page_size
    k_pages = cache.k_pages.at[:, jnp.asarray(page_idx),
                               jnp.asarray(in_page)].set(
        k_new.astype(cache.k_pages.dtype), mode="drop")
    v_pages = cache.v_pages.at[:, jnp.asarray(page_idx),
                               jnp.asarray(in_page)].set(
        v_new.astype(cache.v_pages.dtype), mode="drop")
    return PagedKVCache(k_pages, v_pages, cache.page_table, cache.lengths)


def paged_attention(q, cache: PagedKVCache, layer: int, *,
                    scale: float | None = None):
    """Decode-step attention: q [B, n_heads, hd] against each sequence's
    paged KV. Gathers each sequence's pages into a contiguous
    [max_pages*page, nkv, hd] view (static shape) and masks beyond
    `lengths`. Supports GQA (n_heads a multiple of n_kv)."""
    b, nh, hd = q.shape
    page_size = cache.k_pages.shape[2]
    nkv = cache.k_pages.shape[3]
    max_pages = cache.page_table.shape[1]
    if scale is None:
        scale = hd ** -0.5
    n_rep = nh // nkv

    # gather pages: [B, max_pages, page, nkv, hd]; holes (-1) clamp to
    # page 0 and are masked out by `lengths`
    table = jnp.maximum(jnp.asarray(cache.page_table), 0)
    k = cache.k_pages[layer][table]
    v = cache.v_pages[layer][table]
    s = max_pages * page_size
    k = k.reshape(b, s, nkv, hd)
    v = v.reshape(b, s, nkv, hd)

    qg = q.reshape(b, nkv, n_rep, hd)
    logits = jnp.einsum("bgrd,bkgd->bgrk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(s)
    lengths = jnp.asarray(cache.lengths)
    mask = kpos[None, :] < lengths[:, None]                # [B, S]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bgrk,bkgd->bgrd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, nh, hd).astype(q.dtype)


# ---------------------------------------------------------------------------
# host-side helpers for the serving engine
# ---------------------------------------------------------------------------

def assign_pages(cache: PagedKVCache, allocator: PageAllocator, slot: int,
                 new_tokens: int) -> PagedKVCache:
    """Grow `slot`'s page table to cover new_tokens more positions.
    Raises MemoryError (the allocator's exhaustion contract) when the
    sequence would outgrow max_pages_per_seq — not an opaque numpy
    broadcast error."""
    page_size = cache.k_pages.shape[2]
    max_pages = cache.page_table.shape[1]
    cur = int(cache.lengths[slot])
    n_new = allocator.pages_needed(cur, new_tokens, page_size)
    if n_new == 0:
        return cache
    have = (cur + page_size - 1) // page_size
    if have + n_new > max_pages:
        raise MemoryError(
            f"sequence in slot {slot} needs {have + n_new} pages, over "
            f"max_pages_per_seq={max_pages}")
    pages = allocator.alloc(slot, n_new)
    cache.page_table[slot, have:have + n_new] = pages  # host, in place
    return cache


def release_slot(cache: PagedKVCache, allocator: PageAllocator,
                 slot: int) -> PagedKVCache:
    allocator.free_slot(slot)
    cache.page_table[slot, :] = -1
    cache.lengths[slot] = 0
    return cache
