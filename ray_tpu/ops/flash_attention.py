"""Flash attention for TPU in Pallas.

Online-softmax blocked attention: O(seq) memory instead of the O(seq^2)
logits tensor, KV streamed through VMEM block by block. Grid is
(batch, heads, q_blocks, kv_blocks) with the kv axis innermost; running max,
denominator and the output accumulator live in VMEM scratch that persists
across the kv iterations of one q block (sequential grid execution on TPU).

GQA reads each KV head once via the BlockSpec index map (no host-side
repeat). The backward pass currently recomputes through the reference
einsum attention via custom_vjp (correct; a dedicated backward kernel is a
planned optimization — forward is the inference/serving hot path).

Kernel design follows the public flash-attention-on-TPU recipe (see
/opt/skills/guides/pallas_guide.md patterns; reference framework has no TPU
attention kernels at all — SURVEY.md §2c "Ring attention: no").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")


def _fwd_kernel(
    q_ref,      # [1, 1, bq, d]
    k_ref,      # [1, 1, bk, d]
    v_ref,      # [1, 1, bk, d]
    o_ref,      # [1, 1, bq, d]
    m_scratch,  # [bq, 128] f32 running row max
    l_scratch,  # [bq, 128] f32 running denominator
    acc_scratch,  # [bq, d] f32 output accumulator
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # causal: process only kv blocks whose start is <= this q block's end
    should_run = True
    if causal:
        should_run = kj * block_k <= qi * block_q + (block_q - 1)

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0]  # [bq, d]
        k = k_ref[0, 0]  # [bk, d]
        v = v_ref[0, 0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        s = s * scale

        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)

        m_prev = m_scratch[:, :1]                       # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        # exp(-inf - -inf) guard: rows with no valid keys yet stay at 0
        p = jnp.exp(s - m_new)                          # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                  # [bq, 1]
        l_new = l_scratch[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)

        acc = acc_scratch[:] * corr
        acc = acc + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scratch[:] = acc
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scratch[:, :1]
        # guard fully-masked rows (shouldn't occur with causal diag present)
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    skv = k.shape[2]
    n_rep = h // hk
    grid = (b, h, sq // block_q, skv // block_k)

    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // n_rep, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // n_rep, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q_k, interpret):
    block_q, block_k = block_q_k
    return _flash_fwd(q, k, v, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, interpret=interpret)


def _flash_vjp_fwd(q, k, v, scale, causal, block_q_k, interpret):
    out = _flash(q, k, v, scale, causal, block_q_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(scale, causal, block_q_k, interpret, res, g):
    """Backward via the reference attention's VJP (recompute; no O(s^2)
    residuals are saved in the forward)."""
    from ray_tpu.ops.attention import reference_attention

    q, k, v = res

    def ref(q_, k_, v_):
        # reference expects [b, s, h, d]
        o = reference_attention(
            q_.transpose(0, 2, 1, 3), k_.transpose(0, 2, 1, 3),
            v_.transpose(0, 2, 1, 3), causal=causal, scale=scale,
        )
        return o.transpose(0, 2, 1, 3)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q, k, v, *, causal: bool = True, scale: float | None = None,
    block_q: int = 256, block_k: int = 256, interpret: bool = False,
):
    """Flash attention. q/k/v: [batch, seq, heads, head_dim] (same layout as
    ``reference_attention``); returns [batch, seq, heads, head_dim].
    """
    b, sq, h, d = q.shape
    skv = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    if sq % block_q or skv % block_k:
        raise ValueError(
            f"seq lengths ({sq}, {skv}) must be divisible by blocks "
            f"({block_q}, {block_k})"
        )
    # kernel layout: [b, h, s, d]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, scale, causal, (block_q, block_k), interpret)
    return out.transpose(0, 2, 1, 3)
