"""Flash attention for TPU in Pallas — forward AND backward kernels.

Online-softmax blocked attention: O(seq) memory instead of the O(seq^2)
logits tensor, KV streamed through VMEM block by block. Grid is
(batch, heads, q_blocks, kv_blocks) with the kv axis innermost; running max,
denominator and the output accumulator live in VMEM scratch that persists
across the kv iterations of one q block (sequential grid execution on TPU).

The forward also emits the log-sum-exp per row; the backward is two more
blocked kernels (dq over kv blocks; dk/dv over q blocks) that recompute
P = exp(S - lse) blockwise — no O(seq^2) tensor is ever materialized in
either direction, which is what frees the HBM for larger batches at long
sequence length.

GQA reads each KV head once via the BlockSpec index map (no host-side
repeat); the dkv backward fuses (gqa rep, q block) into one grid axis so
dk/dv accumulate across the whole GQA group in VMEM — outputs are KV-head
shaped with no host-side group sum.

Kernel design follows the public flash-attention-on-TPU recipe (see
/opt/skills/guides/pallas_guide.md patterns; reference framework has no TPU
attention kernels at all — SURVEY.md §2c "Ring attention: no").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")


def _masked_scores(q, k, qi, kj, *, scale, causal, block_q, block_k):
    """scale * Q K^T with the causal block mask — THE score definition,
    shared by the forward and both backward kernels so mask/scale changes
    (sliding window, soft-cap, ...) can never diverge between them."""
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        q_pos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    return s


def _fwd_kernel(
    q_ref,      # [1, 1, bq, d]
    k_ref,      # [1, 1, bk, d]
    v_ref,      # [1, 1, bk, d]
    o_ref,      # [1, 1, bq, d]
    *rest,      # [lse_ref] (training only) + m/l/acc scratch
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    with_lse: bool,
):
    # lse_ref: [1, 1, bq, 8] f32 log-sum-exp, lane-broadcast (Mosaic needs
    # the last two block dims tiled; 8 lanes is the cheapest legal layout
    # for a per-row scalar). Only emitted when the backward will need it —
    # inference calls skip the extra HBM stream entirely.
    if with_lse:
        lse_ref, m_scratch, l_scratch, acc_scratch = rest
    else:
        (m_scratch, l_scratch, acc_scratch), lse_ref = rest, None
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # causal: process only kv blocks whose start is <= this q block's end
    should_run = True
    if causal:
        should_run = kj * block_k <= qi * block_q + (block_q - 1)

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0]  # [bq, d]
        k = k_ref[0, 0]  # [bk, d]
        v = v_ref[0, 0]
        s = _masked_scores(q, k, qi, kj, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k)  # [bq, bk]

        m_prev = m_scratch[:, :1]                       # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        # exp(-inf - -inf) guard: rows with no valid keys yet stay at 0
        p = jnp.exp(s - m_new)                          # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                  # [bq, 1]
        l_new = l_scratch[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)

        acc = acc_scratch[:] * corr
        acc = acc + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scratch[:] = acc
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scratch[:, :1]
        # guard fully-masked rows (shouldn't occur with causal diag present)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            m = m_scratch[:, :1]
            lse = jnp.where(l == 0.0, NEG_INF,
                            m + jnp.log(l_safe))   # [bq, 1]
            lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _flash_fwd(q, k, v, *, scale, causal, block_q, block_k, interpret,
               with_lse):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    skv = k.shape[2]
    n_rep = h // hk
    grid = (b, h, sq // block_q, skv // block_k)

    out_specs = [pl.BlockSpec((1, 1, block_q, d),
                              lambda b_, h_, i, j: (b_, h_, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((b, h, sq, d), q.dtype)]
    if with_lse:
        out_specs.append(pl.BlockSpec((1, 1, block_q, 8),
                                      lambda b_, h_, i, j: (b_, h_, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b, h, sq, 8), jnp.float32))

    res = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, with_lse=with_lse,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // n_rep, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // n_rep, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return (res[0], res[1]) if with_lse else (res[0], None)


# ---------------------------------------------------------------------------
# Backward kernels. Standard flash gradient identities, recomputed blockwise
# from the saved lse (P never materialized globally):
#   S = scale * Q K^T (masked), P = exp(S - lse)
#   delta_i = sum_d dO_id * O_id
#   dV = P^T dO
#   dS = P * (dO V^T - delta)
#   dQ = scale * dS K ;  dK = scale * dS^T Q
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref,         # [1,1,bq,d] / [1,1,bk,d] x2 / [1,1,bq,d]
    lse_ref, delta_ref,                  # [1,1,bq,8] f32 (lane-broadcast)
    dq_ref,                              # [1,1,bq,d]
    dq_scratch,                          # [bq,d] f32
    *, scale, causal, block_q, block_k,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    should_run = True
    if causal:
        should_run = kj * block_k <= qi * block_q + (block_q - 1)

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]         # [bq, 1]
        delta = delta_ref[0, 0][:, :1]     # [bq, 1]

        s = _masked_scores(q, k, qi, kj, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k)
        p = jnp.exp(s - lse)               # masked/-inf rows -> 0
        dov = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)  # [bq,bk]
        ds = p * (dov - delta) * scale
        dq_scratch[:] = dq_scratch[:] + lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scratch[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref,         # q/do: [1,1,bq,d]; k/v: [1,1,bk,d]
    lse_ref, delta_ref,                  # [1,1,bq,8] f32 (lane-broadcast)
    dk_ref, dv_ref,                      # [1,1,bk,d] (per KV head)
    dk_scratch, dv_scratch,              # [bk,d] f32
    *, scale, causal, block_q, block_k, n_q_blocks,
):
    # inner grid axis t fuses (gqa rep, q block): rep = t // n_q_blocks,
    # qi = t % n_q_blocks — so ALL q-heads of one kv head revisit the same
    # dk/dv output block consecutively and accumulate in scratch (no
    # per-q-head HBM buffers, no host-side group sum)
    kj = pl.program_id(2)
    t = pl.program_id(3)
    nt = pl.num_programs(3)
    qi = t % n_q_blocks

    @pl.when(t == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    should_run = True
    if causal:
        # q block contributes iff its END reaches this kv block's start
        should_run = qi * block_q + (block_q - 1) >= kj * block_k

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]

        s = _masked_scores(q, k, qi, kj, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k)
        p = jnp.exp(s - lse)                                    # [bq,bk]
        # dV += P^T dO
        dv_scratch[:] = dv_scratch[:] + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dov = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        ds = p * (dov - delta) * scale                          # [bq,bk]
        # dK += dS^T Q
        dk_scratch[:] = dk_scratch[:] + lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scratch[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, *, scale, causal, block_q, block_k,
               interpret):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    skv = k.shape[2]
    n_rep = h // hk
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                    # [b,h,sq]
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 8))

    qd_spec = pl.BlockSpec((1, 1, block_q, d),
                           lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d),
                           lambda b_, h_, i, j: (b_, h_ // n_rep, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 8),
                            lambda b_, h_, i, j: (b_, h_, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, sq // block_q, skv // block_k),
        in_specs=[qd_spec, kv_spec, kv_spec, qd_spec, row_spec, row_spec],
        out_specs=qd_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # kv-head-major grid; inner axis fuses (gqa rep, q block) so dk/dv
    # accumulate across the whole GQA group in VMEM scratch
    nq = sq // block_q
    qd_spec2 = pl.BlockSpec(
        (1, 1, block_q, d),
        lambda b_, hk_, j, t: (b_, hk_ * n_rep + t // nq, t % nq, 0))
    kv_spec2 = pl.BlockSpec((1, 1, block_k, d),
                            lambda b_, hk_, j, t: (b_, hk_, j, 0))
    row_spec2 = pl.BlockSpec(
        (1, 1, block_q, 8),
        lambda b_, hk_, j, t: (b_, hk_ * n_rep + t // nq, t % nq, 0))
    dkv_spec = pl.BlockSpec((1, 1, block_k, d),
                            lambda b_, hk_, j, t: (b_, hk_, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q_blocks=nq),
        grid=(b, hk, skv // block_k, n_rep * nq),
        in_specs=[qd_spec2, kv_spec2, kv_spec2, qd_spec2, row_spec2,
                  row_spec2],
        out_specs=[dkv_spec, dkv_spec],
        out_shape=[jax.ShapeDtypeStruct((b, hk, skv, d), k.dtype),
                   jax.ShapeDtypeStruct((b, hk, skv, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q_k, interpret):
    block_q, block_k = block_q_k
    out, _ = _flash_fwd(q, k, v, scale=scale, causal=causal,
                        block_q=block_q, block_k=block_k,
                        interpret=interpret, with_lse=False)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal, block_q_k, interpret):
    block_q, block_k = block_q_k
    out, lse = _flash_fwd(q, k, v, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, with_lse=True)
    # named checkpoint targets: under jax.checkpoint with the
    # "attn"/"dots_attn" policies (models/llama.py) these residuals are
    # SAVED, so the backward never re-runs this kernel — the O(seq^2)
    # forward otherwise recomputes inside every remat backward, the
    # round-3 long-context MFU gap
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, block_q_k, interpret, res, g):
    q, k, v, out, lse = res
    block_q, block_k = block_q_k
    return _flash_bwd(q, k, v, out, lse, g, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, interpret=interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _fit_block(seq: int, want: int) -> int:
    """Largest block <= ``want`` that divides ``seq``: a 128-multiple when
    the length allows, else the whole sequence as a single block (the only
    layout Mosaic accepts for odd lengths)."""
    blk = min(want, seq)
    if seq % 128 == 0 and blk >= 128:
        blk -= blk % 128
        while seq % blk:
            blk -= 128
        return blk
    while seq % blk:
        blk -= 1
    if blk < seq and seq % 128:
        raise ValueError(
            f"sequence length {seq} must be a multiple of 128, or "
            f"block_q/block_k must cover the whole sequence (>= {seq})")
    return blk


def flash_attention(
    q, k, v, *, causal: bool = True, scale: float | None = None,
    block_q: int | None = None, block_k: int | None = None,
    interpret: bool = False,
):
    # default blocks from v5e FULL-gradient in-graph sweeps (d=128,
    # fwd + dq + dk/dv kernels): (512,1024) wins at s=2048/b=8
    # (16.3ms vs 19.6 for bq=1024); at s=16k/b=1 the larger q block
    # wins ((1024,1024): 39.8ms vs 43.3) — more rows per grid step
    # amortize scratch when many kv blocks stream per q block
    """Flash attention. q/k/v: [batch, seq, heads, head_dim] (same layout as
    ``reference_attention``); returns [batch, seq, heads, head_dim].
    """
    b, sq, h, d = q.shape
    skv = k.shape[1]
    if block_q is None:
        block_q = 1024 if sq >= 8192 else 512
    if block_k is None:
        block_k = 1024
    scale = scale if scale is not None else d ** -0.5
    block_q = _fit_block(sq, block_q)
    block_k = _fit_block(skv, block_k)
    # kernel layout: [b, h, s, d]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, scale, causal, (block_q, block_k), interpret)
    return out.transpose(0, 2, 1, 3)
