"""Attention ops: GQA/MHA causal attention with fp32 softmax.

The default implementation is pure-XLA einsum attention — on TPU, XLA fuses
the QK^T → softmax → PV chain reasonably well at small/medium sequence
lengths. The Pallas flash kernel (``ray_tpu.ops.flash_attention``) replaces it
on TPU for long sequences; ``attention()`` dispatches.

Conventions: q/k/v are [batch, seq, heads, head_dim]; GQA is expressed by
n_kv_heads < n_heads with n_heads % n_kv_heads == 0.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from einops import rearrange


def _repeat_kv(k, n_rep: int):
    if n_rep == 1:
        return k
    return jnp.repeat(k, n_rep, axis=2)


def reference_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    segment_ids=None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
):
    """Einsum attention with fp32 logits/softmax.

    ``segment_ids`` ([batch, seq], int) masks cross-segment attention —
    used for sequence packing.
    """
    b, sq, nh, hd = q.shape
    _, skv, nkv, _ = k.shape
    n_rep = nh // nkv
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = scale if scale is not None else hd ** -0.5

    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale
    if logits_soft_cap is not None:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)

    mask = None
    if causal:
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(skv)[None, :]
        # allow decode: query block sits at the END of the kv window
        mask = kpos <= qpos + (skv - sq)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        seg_mask = seg_mask[:, None, :, :]  # [b, 1, q, k]
        mask = seg_mask if mask is None else (mask[None, None] & seg_mask)
    elif mask is not None:
        mask = mask[None, None]

    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def attention(q, k, v, *, causal=True, segment_ids=None,
              logits_soft_cap=None, impl: str = "auto"):
    """Dispatching entry point. ``impl``: auto | reference | flash."""
    if impl == "auto":
        impl = "flash" if _flash_supported(q, segment_ids, logits_soft_cap, causal) else "reference"
    if impl == "flash":
        if segment_ids is not None or logits_soft_cap is not None:
            raise ValueError(
                "impl='flash' does not support segment_ids/logits_soft_cap "
                "yet; use impl='reference'"
            )
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    from jax.ad_checkpoint import checkpoint_name

    # save point for the "attn"/"dots_attn" remat policies (the flash
    # impl names its kernel residuals instead — _flash_vjp_fwd)
    return checkpoint_name(
        reference_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap,
        ),
        "attn_out")


def _flash_supported(q, segment_ids, logits_soft_cap, causal) -> bool:
    if segment_ids is not None or logits_soft_cap is not None or not causal:
        return False
    # works under tracing: dispatch on the process-level default backend
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    # flash kernel block constraints
    b, s, h, d = q.shape
    return s >= 256 and s % 128 == 0 and d in (64, 128, 256)
