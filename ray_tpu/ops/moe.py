"""Mixture-of-Experts ops: top-k router + capacity-based expert dispatch.

GShard/Switch-style MoE, the TPU-idiomatic formulation: dispatch and combine
are einsums against one-hot capacity tensors (static shapes, MXU-friendly,
no gathers), and expert parallelism is pure sharding — with the expert dim of
``wi``/``wo`` sharded on the ``ep`` mesh axis, XLA's SPMD partitioner emits
the token all-to-all automatically. (Reference has NO MoE implementation —
SURVEY.md §2c row EP; Mixtral is a BASELINE.json target config.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def router_topk(
    logits,             # [T, E] fp32
    *,
    top_k: int,
    capacity: int,
):
    """Top-k gating with per-expert capacity (GShard algorithm).

    Returns (dispatch [T, E, C] bool-ish float, combine [T, E, C] float,
    aux_loss scalar). Tokens over capacity are dropped (their combine weight
    is 0 — the residual stream carries them unchanged).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # top-k experts per token
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)     # [T, K]
    # renormalize the chosen gates
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # position of each token within its expert's queue, per choice slot
    dispatch = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    combine = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    # running per-expert counts; iterate over the k slots (k is tiny/static)
    counts = jnp.zeros((e,), dtype=jnp.int32)
    for slot in range(top_k):
        idx = gate_idx[:, slot]                            # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)   # [T, E]
        # position within expert queue = tokens for same expert before me
        pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot  # [T, E]
        pos = jnp.sum(pos_in_expert * onehot, axis=1) + counts[idx]  # [T]
        keep = pos < capacity
        pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [T, C]
        contrib = (
            onehot.astype(jnp.float32)[:, :, None]
            * pos_oh[:, None, :]
            * keep.astype(jnp.float32)[:, None, None]
        )
        dispatch = dispatch + contrib
        combine = combine + contrib * gate_vals[:, slot][:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)

    # Load-balancing auxiliary loss (GShard/Mixtral): E * sum(f_i * p_i)
    # where f_i counts ALL top-k assignments, not just slot 0 — an expert
    # that is systematically every token's second choice must still feel
    # gradient pressure.
    me = jnp.mean(probs, axis=0)                            # mean router prob
    ce = jnp.mean(
        jnp.sum(jax.nn.one_hot(gate_idx, e, dtype=jnp.float32), axis=1),
        axis=0,
    ) / top_k                                               # fraction routed
    aux_loss = e * jnp.sum(me * ce)
    return dispatch, combine, aux_loss


def moe_ffn(
    x,                  # [T, D] tokens (flattened batch*seq)
    router_w,           # [D, E]
    wi_gate,            # [E, D, F]
    wi_up,              # [E, D, F]
    wo,                 # [E, F, D]
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
):
    """SwiGLU expert FFN with top-k routing. Returns (out [T, D], aux_loss).

    All expert compute is einsum over the expert dim; shard wi/wo on
    ``ep`` to get expert parallelism (all-to-all inserted by XLA).
    """
    t, d = x.shape
    e = router_w.shape[1]
    capacity = max(1, int(capacity_factor * t * top_k / e))

    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))
    dispatch, combine, aux = router_topk(
        logits, top_k=top_k, capacity=capacity
    )

    dtype = x.dtype
    expert_in = jnp.einsum("td,tec->ecd", x, dispatch.astype(dtype),
                           preferred_element_type=jnp.float32).astype(dtype)
    h = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, wi_gate,
                   preferred_element_type=jnp.float32)
    ) * jnp.einsum("ecd,edf->ecf", expert_in, wi_up,
                   preferred_element_type=jnp.float32)
    h = h.astype(dtype)
    expert_out = jnp.einsum("ecf,efd->ecd", h, wo,
                            preferred_element_type=jnp.float32).astype(dtype)
    out = jnp.einsum("ecd,tec->td", expert_out, combine.astype(dtype),
                     preferred_element_type=jnp.float32)
    return out.astype(dtype), aux
