"""Rotary position embeddings (RoPE), Llama-3 style with NTK scaling hooks."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, *, theta: float = 500000.0,
                     dtype=jnp.float32):
    """Inverse frequencies for the rotary embedding, [head_dim // 2]."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return (1.0 / (theta ** exponent)).astype(dtype)


def rope_sin_cos(positions, head_dim: int, *, theta: float = 500000.0):
    """(sin, cos) tables for integer positions [...]. Returned in fp32;
    callers cast after rotation for bf16 accuracy."""
    inv_freq = rope_frequencies(head_dim, theta=theta)
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., hd/2]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x, sin, cos):
    """Rotate q or k: x is [..., seq, heads, head_dim]; sin/cos are
    [..., seq, head_dim//2] (broadcast over the heads axis).

    Uses the split-half convention (first/second half pairs) which lowers to
    two multiplies + adds on the VPU — no gather, XLA-friendly.
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    sin = sin[..., None, :]  # broadcast over heads
    cos = cos[..., None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = xf1 * cos - xf2 * sin
    out2 = xf2 * cos + xf1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
