"""Device mesh construction and registry.

The TPU-native replacement for the reference's process-group world
(``train/torch/config.py:63`` ``dist.init_process_group`` and
``util/collective/collective.py:40`` ``GroupManager``): instead of NCCL
communicators keyed by group name, we build `jax.sharding.Mesh`es over the
device torus and register them by name. All parallelism (DP/FSDP/TP/SP/EP/PP)
is expressed as axes of one mesh; XLA inserts the collectives.

Axis convention (outer → inner, slowest → fastest varying):

    pp   — pipeline stages (DCN or ICI, coarse)
    dp   — pure data parallelism (gradient all-reduce; can ride DCN)
    fsdp — sharded data parallelism (param/grad/optimizer sharding, ICI)
    ep   — expert parallelism for MoE (ICI)
    sp   — sequence/context parallelism (ICI, ring collectives)
    tp   — tensor/model parallelism (innermost: highest-bandwidth ICI)

Inner axes get ICI-contiguous device assignment via
``jax.experimental.mesh_utils.create_device_mesh``, which optimizes placement
for the physical torus topology. Cross-slice (DCN) meshes use
``create_hybrid_device_mesh`` with dcn axes outermost.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Canonical axis order, outer to inner. Meshes may use any subset.
AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name -> size. Size -1 means "absorb all
    remaining devices" (at most one axis may be -1)."""

    axes: dict[str, int] = field(default_factory=dict)

    def resolved(self, n_devices: int) -> dict[str, int]:
        axes = dict(self.axes)
        wild = [k for k, v in axes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one axis may be -1, got {wild}")
        fixed = math.prod(v for v in axes.values() if v != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {axes}"
                )
            axes[wild[0]] = n_devices // fixed
        else:
            if fixed != n_devices:
                raise ValueError(
                    f"Mesh axes {axes} require {fixed} devices, have {n_devices}"
                )
        # order axes canonically; unknown axes go last in given order
        known = [a for a in AXIS_ORDER if a in axes]
        extra = [a for a in axes if a not in AXIS_ORDER]
        return {a: axes[a] for a in known + extra}


def create_mesh(
    axes: dict[str, int] | MeshSpec,
    *,
    devices=None,
    allow_split_physical_axes: bool = True,
) -> Mesh:
    """Build a Mesh with ICI-topology-aware device assignment.

    ``axes`` maps axis name -> size; one axis may be -1 (remaining devices).
    On TPU the device order comes from ``mesh_utils.create_device_mesh`` so
    that inner mesh axes map to physically adjacent chips (wrong assignment
    silently halves collective bandwidth — SURVEY.md §7 hard parts).
    """
    if devices is None:
        devices = jax.devices()
    spec = axes if isinstance(axes, MeshSpec) else MeshSpec(dict(axes))
    resolved = spec.resolved(len(devices))
    shape = tuple(resolved.values())
    names = tuple(resolved.keys())
    if devices and devices[0].platform == "tpu":
        device_array = mesh_utils.create_device_mesh(
            shape, devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    else:
        # CPU/GPU or virtual devices: logical row-major assignment.
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, axis_names=names)


def create_hybrid_mesh(
    ici_axes: dict[str, int],
    dcn_axes: dict[str, int],
    *,
    devices=None,
) -> Mesh:
    """Multi-slice mesh: ``dcn_axes`` (outermost, cross-slice — usually
    ``{"dp": n_slices}`` or ``{"pp": n_slices}``) × ``ici_axes`` (within a
    slice). Analog of the reference's multi-node NCCL world, except the
    slow/fast network split is explicit in the mesh so XLA routes gradient
    all-reduce over DCN and param all-gather over ICI.
    """
    if devices is None:
        devices = jax.devices()
    if not devices:
        raise ValueError("create_hybrid_mesh: no devices")
    n = len(devices)
    dcn_shape = tuple(dcn_axes.values())
    n_slices = math.prod(dcn_shape)
    if n % n_slices != 0:
        raise ValueError(
            f"{n} devices not divisible by dcn axes {dcn_axes} "
            f"({n_slices} slices)"
        )
    per_slice = n // n_slices
    ici_resolved = MeshSpec(dict(ici_axes)).resolved(per_slice)
    names = tuple(dcn_axes.keys()) + tuple(ici_resolved.keys())
    if devices[0].platform == "tpu":
        device_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=tuple(ici_resolved.values()),
            dcn_mesh_shape=dcn_shape,
            devices=devices,
        )
    else:
        device_array = np.asarray(devices).reshape(
            dcn_shape + tuple(ici_resolved.values())
        )
    return Mesh(device_array, axis_names=names)


class MeshRegistry:
    """Named meshes (analog of the reference's collective ``GroupManager``,
    ``util/collective/collective.py:40``, which keys NCCL groups by name)."""

    def __init__(self):
        self._meshes: dict[str, Mesh] = {}
        self._lock = threading.Lock()

    def register(self, name: str, mesh: Mesh, *, overwrite: bool = False):
        with self._lock:
            return self._register_locked(name, mesh, overwrite)

    def _register_locked(self, name: str, mesh: Mesh, overwrite: bool):
        if name in self._meshes and not overwrite:
            raise ValueError(f"Mesh {name!r} already registered")
        self._meshes[name] = mesh
        return mesh

    def get(self, name: str) -> Mesh:
        with self._lock:
            if name not in self._meshes:
                raise KeyError(
                    f"No mesh named {name!r}; registered: {list(self._meshes)}"
                )
            return self._meshes[name]

    def get_or_create(self, name: str, axes: dict[str, int], **kwargs) -> Mesh:
        # Single critical section: a concurrent creator must get the winner's
        # mesh back, not a ValueError from a lost register race.
        with self._lock:
            if name in self._meshes:
                return self._meshes[name]
            mesh = create_mesh(axes, **kwargs)
            return self._register_locked(name, mesh, overwrite=False)

    def remove(self, name: str):
        with self._lock:
            self._meshes.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            return list(self._meshes)


_registry = MeshRegistry()


def mesh_registry() -> MeshRegistry:
    return _registry


def slice_topology() -> dict:
    """Describe the local TPU slice (chip count, platform, coords if TPU).
    Analog of the reference's TPU autodetect (``_private/accelerator.py``)."""
    devices = jax.devices()
    info = {
        "platform": devices[0].platform if devices else "none",
        "num_devices": len(devices),
        "num_hosts": max((d.process_index for d in devices), default=0) + 1,
    }
    if devices and devices[0].platform == "tpu":
        try:
            coords = [getattr(d, "coords", None) for d in devices]
            info["coords"] = coords
            info["device_kind"] = devices[0].device_kind
        except Exception:  # noqa: BLE001
            pass
    return info
