"""Ulysses sequence parallelism: attention via head/sequence all-to-all.

Reference: ABSENT from the reference repo (SURVEY.md §2c/§5 — "Ulysses
(attn all-to-all): no"); this is net-new first-class capability. The
DeepSpeed-Ulysses scheme (Jacobs et al. 2023): activations are sharded
on the SEQUENCE axis everywhere except inside attention; at the
attention boundary an all-to-all re-shards to the HEAD axis (each device
sees the FULL sequence for its subset of heads), dense attention runs
locally, and a second all-to-all restores sequence sharding.

vs ring attention (ray_tpu/parallel/ring_attention.py): Ulysses moves
2 all-to-alls of the activations (cheap on ICI, O(S·H·D/P) per device)
and keeps attention dense; ring keeps activations put and rotates K/V
around the ring. Ulysses requires heads % sp == 0; ring has no head
constraint but pays P ppermute steps. Both are exposed so models pick by
shape.

Use inside shard_map over the ``sp`` axis (the provided
``ulysses_attention_sharded`` wraps that), with inputs sharded
[B, S/sp, H, D].
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import reference_attention
from ray_tpu.parallel.collectives import all_to_all, axis_size


def ulysses_attention(q, k, v, *, axis: str = "sp", causal: bool = True,
                      scale: float | None = None):
    """Inside shard_map: q/k/v are the LOCAL sequence shard
    [B, S/sp, H, D]; returns the local output shard with full-sequence
    attention semantics. H must be divisible by the sp axis size."""
    sp = axis_size(axis)
    b, s_local, h, d = q.shape
    for name, x in (("q", q), ("k", k), ("v", v)):
        if x.shape[2] % sp != 0:
            raise ValueError(
                f"Ulysses requires {name} heads ({x.shape[2]}) divisible "
                f"by sp axis ({sp}) — GQA kv-head counts below sp can't "
                "re-shard by head; use ring attention instead")
    if scale is None:
        scale = d ** -0.5

    # [B, S/sp, H, D] -> [B, S, H/sp, D]: scatter heads, gather sequence
    def to_heads(x):
        return all_to_all(x, axis, split_axis=2, concat_axis=1)

    def to_seq(x):
        return all_to_all(x, axis, split_axis=1, concat_axis=2)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = reference_attention(qh, kh, vh, causal=causal, scale=scale)
    return to_seq(out)


def ulysses_attention_sharded(mesh: Mesh, q, k, v, *, axis: str = "sp",
                              causal: bool = True,
                              scale: float | None = None):
    """Driver-level entry: shards [B, S, H, D] inputs on the sequence
    axis over ``axis`` and runs ulysses_attention under shard_map."""
    spec = P(None, axis, None, None)
    fn = jax.shard_map(
        partial(ulysses_attention, axis=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v)
