"""Logical-axis sharding rules → GSPMD partition specs.

The TPU-native replacement for the reference's wrapper-level sharded
parallelism (DDP/FSDP wraps in ``train/torch/train_loop_utils.py:74,246``):
instead of wrapping modules, every array in the model pytree carries *logical*
axis names, and a rule table maps logical axes onto mesh axes. Changing the
parallelism strategy = swapping the rule table; the model code never changes.

Logical axes used by the model library:

    batch    — per-example batch dim        → dp/fsdp (data parallel)
    seq      — sequence/token dim           → sp (sequence/context parallel)
    embed    — model (d_model) dim          → fsdp sharding of activations/params
    heads    — attention heads              → tp
    kv_heads — kv heads (GQA)               → tp
    mlp      — FFN hidden dim               → tp
    vocab    — vocabulary dim               → tp
    expert   — MoE expert dim               → ep
    layers   — stacked layer dim            → pp (pipeline parallel)
    stage    — pipeline stage dim           → pp
    (None)   — replicated
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Axes(tuple):
    """Marker type for a logical-axes annotation leaf. Distinguishable from
    namedtuples (e.g. optax states) when used as a pytree leaf predicate."""

    __slots__ = ()


def is_axes_leaf(x) -> bool:
    """True for annotation leaves: an ``Axes`` marker, or a plain tuple of
    axis entries (str/None/tuple-of-str). Namedtuple containers (e.g. optax
    states) are NOT leaves even though they subclass tuple."""
    if isinstance(x, Axes):
        return True
    if isinstance(x, tuple) and not hasattr(x, "_fields"):
        return all(
            e is None or isinstance(e, str)
            or (isinstance(e, (tuple, list))
                and all(isinstance(s, str) for s in e))
            for e in x
        )
    return False


@dataclass(frozen=True)
class ShardingRules:
    """Mapping from logical axis name to mesh axis (or tuple of mesh axes,
    or None for replicated)."""

    batch: Any = ("dp", "fsdp")
    seq: Any = None
    embed: Any = None
    heads: Any = None
    kv_heads: Any = None
    mlp: Any = None
    vocab: Any = None
    expert: Any = None
    layers: Any = None
    stage: Any = None

    def mesh_axes(self, logical: tuple) -> P:
        out = []
        for ax in logical:
            if ax is None:
                out.append(None)
            else:
                out.append(getattr(self, ax))
        return P(*out)

    def with_overrides(self, **kw) -> "ShardingRules":
        return replace(self, **kw)


# --- presets (the §2c parallelism inventory as one-liners) ---

# Pure data parallel: params replicated, batch split.
DP_RULES = ShardingRules(batch=("dp", "fsdp"))

# Fully-sharded data parallel (ZeRO-3 analog): params/grads/optimizer sharded
# on fsdp axis; batch split over dp×fsdp.
FSDP_RULES = ShardingRules(batch=("dp", "fsdp"), embed="fsdp")

# Megatron-style tensor parallel: heads/mlp/vocab split on tp.
TP_RULES = ShardingRules(batch=("dp", "fsdp"), heads="tp", kv_heads="tp",
                         mlp="tp", vocab="tp")

# FSDP × TP (the common 2D layout for 7B+ on a slice).
FSDP_TP_RULES = ShardingRules(
    batch=("dp", "fsdp"), embed="fsdp", heads="tp", kv_heads="tp", mlp="tp",
    vocab="tp",
)

# + sequence parallel: activations sharded along seq on the sp axis.
FSDP_TP_SP_RULES = FSDP_TP_RULES.with_overrides(seq="sp")

# MoE: experts split on ep, everything else as FSDP×TP.
MOE_RULES = FSDP_TP_RULES.with_overrides(expert="ep")

# Pipeline parallel: the stacked layer dim split over pp (contiguous layer
# groups = stages), everything else FSDP×TP (tp entries drop out on meshes
# without a tp axis via _filter_spec_for_mesh).
PP_FSDP_RULES = FSDP_TP_RULES.with_overrides(layers="pp")

PRESETS = {
    "dp": DP_RULES,
    "fsdp": FSDP_RULES,
    "tp": TP_RULES,
    "fsdp_tp": FSDP_TP_RULES,
    "fsdp_tp_sp": FSDP_TP_SP_RULES,
    "moe": MOE_RULES,
    "pp_fsdp": PP_FSDP_RULES,
}


def _filter_spec_for_mesh(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes the mesh doesn't have (so FSDP_TP rules work on a
    dp-only mesh: tp entries become replicated), and drop repeated uses of a
    mesh axis (first dim wins): one array can map each mesh axis to at most
    one positional dimension — e.g. activations [batch(dp,fsdp), embed(fsdp)]
    keep fsdp on batch and replicate embed."""
    names = set(mesh.axis_names)
    used: set = set()

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = []
            for e in entry:
                if e in names and e not in used:
                    used.add(e)
                    kept.append(e)
            return tuple(kept) if kept else None
        if entry in names and entry not in used:
            used.add(entry)
            return entry
        return None

    return P(*(keep(e) for e in spec))


def logical_sharding(logical: tuple, mesh: Mesh, rules: ShardingRules) -> NamedSharding:
    """NamedSharding for one array annotated with logical axis names."""
    spec = _filter_spec_for_mesh(rules.mesh_axes(logical), mesh)
    return NamedSharding(mesh, spec)


def tree_shardings(logical_tree, mesh: Mesh, rules: ShardingRules):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings.
    ``logical_tree`` leaves are tuples like ("embed", "mlp") or ``Axes``."""
    return jax.tree.map(
        lambda logical: logical_sharding(tuple(logical), mesh, rules),
        logical_tree,
        is_leaf=is_axes_leaf,
    )


def shard_tree(tree, logical_tree, mesh: Mesh, rules: ShardingRules):
    """Device-put a pytree according to its logical annotations."""
    shardings = tree_shardings(logical_tree, mesh, rules)
    return jax.device_put(tree, shardings)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, rules: ShardingRules, ndim: int = 2,
                   *, shard_seq: bool = True) -> NamedSharding:
    """Sharding for an input batch [batch, seq, ...]: batch axis split per
    rules, sequence split if sp is active, rest replicated.

    ``shard_seq=False`` keeps the seq dim replicated — used for raw token
    batches of length S+1 (the shifted-target column makes S+1 typically
    indivisible by sp; ring attention's shard_map introduces the seq
    sharding inside the step instead)."""
    logical = ("batch", "seq" if shard_seq else None) + (None,) * (ndim - 2)
    return logical_sharding(logical[:max(ndim, 0)], mesh, rules)
