"""ray_tpu.parallel: device-plane parallelism (net-new vs the reference —
SURVEY §2c): meshes, logical shardings, XLA collectives, ring/Ulysses
sequence parallelism, pipeline schedules."""

from ray_tpu.parallel.mesh import (MeshSpec, create_hybrid_mesh, create_mesh,
                                   mesh_registry, slice_topology)
from ray_tpu.parallel.ring_attention import ring_attention
from ray_tpu.parallel.sharding import Axes, ShardingRules
from ray_tpu.parallel.ulysses import (ulysses_attention,
                                      ulysses_attention_sharded)

__all__ = [
    "Axes",
    "MeshSpec",
    "ShardingRules",
    "create_hybrid_mesh",
    "create_mesh",
    "mesh_registry",
    "ring_attention",
    "slice_topology",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
