"""Pipeline parallelism: stage-sharded layers + microbatch rotation.

Net-new capability vs. the reference (SURVEY.md §2c: pipeline parallel is
ABSENT there). TPU-idiomatic GPipe: the stacked layer arrays are split into
``n_stages`` contiguous groups sharded over the ``pp`` mesh axis; microbatches
flow through the stage ring via ``lax.ppermute``. Each tick every stage runs
its layer group on its current microbatch while the permute moves activations
to the next stage — compute and ICI transfer overlap, and the whole schedule
is one jit-compiled ``lax.scan`` (bubble fraction (S-1)/(M+S-1), the GPipe
formula).

The backward pass is jax.grad through the scan: XLA reverses the schedule
automatically (reverse pipeline with the same overlap). 1F1B memory
scheduling is a planned refinement; GPipe semantics are exact.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def split_stages(stacked_params, n_stages: int):
    """Reshape stacked per-layer params [L, ...] -> [n_stages, L/ns, ...]."""

    def reshape(a):
        l = a.shape[0]
        if l % n_stages:
            raise ValueError(
                f"{l} layers not divisible by {n_stages} pipeline stages"
            )
        return a.reshape(n_stages, l // n_stages, *a.shape[1:])

    return jax.tree.map(reshape, stacked_params)


def pipeline_apply(
    stage_fn: Callable,   # (stage_params, x) -> x, applied by every stage
    stage_params,         # pytree, leaves [n_stages, L/ns, ...]
    x_micro,              # [M, mb, ...] microbatched activations
    *,
    mesh: Mesh,
    axis: str = "pp",
):
    """Run the GPipe schedule. Returns [M, mb, ...] outputs (replicated over
    the pp axis)."""
    n_stages = mesh.shape[axis]
    m = x_micro.shape[0]
    if m < n_stages:
        raise ValueError(
            f"need at least {n_stages} microbatches to fill the pipeline, "
            f"got {m}"
        )
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def device_body(stage_params_local, xm):
        sid = lax.axis_index(axis)
        # drop the sharded leading stage dim (local size 1)
        sp = jax.tree.map(lambda a: a[0], stage_params_local)
        buf = jnp.zeros_like(xm[0])
        outs = jnp.zeros_like(xm)

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t (clamped once the input is drained)
            feed = xm[jnp.minimum(t, m - 1)]
            inp = jnp.where(sid == 0, feed, buf)
            y = stage_fn(sp, inp)
            # last stage emits microbatch t-(n_stages-1)
            out_t = t - (n_stages - 1)
            write = jnp.logical_and(sid == n_stages - 1, out_t >= 0)
            updated = lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.maximum(out_t, 0), 0
            )
            outs = jnp.where(write, updated, outs)
            buf = lax.ppermute(y, axis, perm)
            return (buf, outs), None

        (buf, outs), _ = lax.scan(
            tick, (buf, outs), jnp.arange(m + n_stages - 1)
        )
        # broadcast the last stage's outputs to every pp rank
        mask = (sid == n_stages - 1).astype(outs.dtype)
        return lax.psum(outs * mask, axis)

    fn = jax.shard_map(
        device_body, mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stage_params, x_micro)


# ---------------------------------------------------------------------------
# Llama integration
# ---------------------------------------------------------------------------


def llama_forward_pipelined(
    cfg,
    params: dict,
    tokens,                    # [batch, seq]
    *,
    mesh: Mesh,
    axis: str = "pp",
    n_microbatches: int | None = None,
    attn_impl: str = "auto",
):
    """Llama forward with the layer stack pipelined over ``axis``.

    Embedding and the LM head run outside the pipelined region under plain
    GSPMD (they live on every stage; their cost is O(vocab) once, not per
    layer). Default positions only (no packing/segment support in v1).
    """
    from ray_tpu.models.llama import _block
    from ray_tpu.ops.norms import rms_norm
    from ray_tpu.ops.rope import rope_sin_cos

    n_stages = mesh.shape[axis]
    m = n_microbatches or n_stages
    b, s = tokens.shape
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")

    x = params["embedding"][tokens]  # [b, s, d]
    positions = jnp.arange(s, dtype=jnp.int32)[None, :]
    sin, cos = rope_sin_cos(positions, cfg.head_dim, theta=cfg.rope_theta)

    def stage_fn(stage_blocks, xm):
        body = partial(_block, cfg, sin=sin, cos=cos, segment_ids=None,
                       attn_impl=attn_impl)
        if cfg.remat == "full":
            body = jax.checkpoint(body)
        elif cfg.remat == "dots":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
            )

        def scan_fn(x, layer_params):
            return body(x, layer_params), None

        out, _ = lax.scan(scan_fn, xm, stage_blocks)
        return out

    stage_params = split_stages(params["blocks"], n_stages)
    x_micro = x.reshape(m, b // m, s, x.shape[-1])
    out = pipeline_apply(stage_fn, stage_params, x_micro, mesh=mesh,
                         axis=axis)
    x = out.reshape(b, s, x.shape[-1])

    x = rms_norm(x, params["final_norm"], eps=cfg.rms_eps)
    from ray_tpu.models import llama

    head = llama.lm_head_weights(cfg, params)
    return jnp.einsum("bsd,dv->bsv", x, head,
                      preferred_element_type=jnp.float32)
