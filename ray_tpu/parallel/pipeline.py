"""Pipeline parallelism: stage-sharded layers + microbatch rotation.

Net-new capability vs. the reference (SURVEY.md §2c: pipeline parallel is
ABSENT there). TPU-idiomatic: the stacked layer arrays are split into
``n_stages`` contiguous groups sharded over the ``pp`` mesh axis; microbatches
flow through the stage ring via ``lax.ppermute``. Each tick every stage runs
its layer group on its current microbatch while the permute moves activations
to the next stage — compute and ICI transfer overlap, and the whole schedule
is one jit-compiled ``lax.scan``. The shard_map is partially manual
(``axis_names={pp}``): only the pp axis is hand-scheduled; dp/fsdp/tp stay
under GSPMD, so stage-internal matmuls keep their tensor/FSDP shardings and
XLA still inserts those collectives automatically.

Two schedules:

- ``pipeline_apply`` — GPipe forward; the backward is jax.grad through the
  scan (XLA reverses the schedule into the mirror-image reverse pipeline).
  Bubble fraction (S-1)/(M+S-1) each direction; activation stash grows with
  M (one stage-input per tick, rematerialized inside the stage).
- ``pipeline_value_and_grad`` — 1F1B: forward and backward interleaved in
  ONE lockstep scan, with the loss/head computed per-microbatch on the last
  stage so microbatch m's backward starts S-1 ticks after its forward. The
  stage-input stash is a ring buffer of 2·S entries — O(pipeline depth)
  instead of O(microbatches) — which is what lets M (and therefore bubble
  amortization M/(M+2S-2)) scale without activation memory scaling with it.
  Returns grads directly (it implements backprop; it is not differentiated
  through).

Lockstep-SPMD honesty note: every device executes the full tick body with
inactive slots masked out (``jnp.where``), because data-dependent branches
around GSPMD-inserted collectives would deadlock the mesh. The warmup /
cooldown bubbles therefore burn flops rather than idling — same wall-clock
as the classic async schedule, simpler program, one compiled step.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def split_stages(stacked_params, n_stages: int):
    """Reshape stacked per-layer params [L, ...] -> [n_stages, L/ns, ...]."""

    def reshape(a):
        l = a.shape[0]
        if l % n_stages:
            raise ValueError(
                f"{l} layers not divisible by {n_stages} pipeline stages"
            )
        return a.reshape(n_stages, l // n_stages, *a.shape[1:])

    return jax.tree.map(reshape, stacked_params)


def pipeline_apply(
    stage_fn: Callable,   # (stage_params, x) -> x, applied by every stage
    stage_params,         # pytree, leaves [n_stages, L/ns, ...]
    x_micro,              # [M, mb, ...] microbatched activations
    *,
    mesh: Mesh,
    axis: str = "pp",
):
    """Run the GPipe schedule. Returns [M, mb, ...] outputs (replicated over
    the pp axis)."""
    n_stages = mesh.shape[axis]
    m = x_micro.shape[0]
    if m < n_stages:
        raise ValueError(
            f"need at least {n_stages} microbatches to fill the pipeline, "
            f"got {m}"
        )
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def device_body(stage_params_local, xm):
        sid = lax.axis_index(axis)
        # drop the sharded leading stage dim (local size 1)
        sp = jax.tree.map(lambda a: a[0], stage_params_local)
        buf = jnp.zeros_like(xm[0])
        outs = jnp.zeros_like(xm)

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t (clamped once the input is drained)
            feed = xm[jnp.minimum(t, m - 1)]
            inp = jnp.where(sid == 0, feed, buf)
            y = stage_fn(sp, inp)
            # last stage emits microbatch t-(n_stages-1)
            out_t = t - (n_stages - 1)
            write = jnp.logical_and(sid == n_stages - 1, out_t >= 0)
            updated = lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.maximum(out_t, 0), 0
            )
            outs = jnp.where(write, updated, outs)
            buf = lax.ppermute(y, axis, perm)
            return (buf, outs), None

        (buf, outs), _ = lax.scan(
            tick, (buf, outs), jnp.arange(m + n_stages - 1)
        )
        # broadcast the last stage's outputs to every pp rank
        mask = (sid == n_stages - 1).astype(outs.dtype)
        return lax.psum(outs * mask, axis)

    fn = jax.shard_map(
        device_body, mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        axis_names={axis},
        check_vma=False,
    )
    return fn(stage_params, x_micro)


def pipeline_value_and_grad(
    stage_fn,        # (stage_params, x[mb,s,d]) -> y[mb,s,d]
    head_fn,         # (io_params, y[mb,s,d], tgt[mb,s], msk[mb,s])
                     #   -> (loss_sum, weight_sum) scalars, fp32
    stage_params,    # pytree, leaves [n_stages, L/ns, ...]
    io_params,       # pytree (replicated over pp): head weights, and the
                     # embedding when embed_fn is given
    x_micro,         # [M, mb, s, d] activations — or [M, mb, s] tokens
                     # when embed_fn is given
    tgt_micro,       # [M, mb, s] int targets
    msk_micro,       # [M, mb, s] {0,1} loss mask
    *,
    mesh: Mesh,
    axis: str = "pp",
    embed_fn=None,   # optional (io_params, tokens[mb,s]) -> x[mb,s,d]:
                     # runs in stage 0's forward slot, its vjp in stage 0's
                     # backward slot, so the embedding grad accumulates
                     # inside the schedule like every other grad
):
    """1F1B pipelined loss + backprop in one lockstep scan.

    Returns ``(loss_sum, weight_sum), (d_stage_params, d_io_params,
    d_x_micro)`` where the grads are of ``loss_sum`` (scale by
    ``1/weight_sum`` outside for mean-loss grads — the weight does not
    depend on params, so scaling commutes). ``d_x_micro`` is None when
    ``embed_fn`` is given (tokens have no gradient; the embedding grad is
    folded into ``d_io_params``).

    Schedule (S stages, M microbatches, tick t, stage s):
      forward slot:  microbatch  f = t - s            (stage 0 ingests f=t)
      loss slot:     last stage runs head_fn + its vjp on this tick's y
      backward slot: microbatch  b = t - 2(S-1) + s   (last stage: b = f)
    so grads for microbatch m leave the last stage at tick m+S-1 and reach
    stage s at tick m + 2(S-1) - s: T = M + 2(S-1) ticks total. Each
    backward slot re-runs its stage forward from the stashed input
    (``jax.vjp``), i.e. rematerialization is built in; only stage INPUTS
    are stashed, in a 2-S-slot ring buffer (max in-flight span at stage 0
    is 2(S-1) ticks).

    The head (and embedding) run masked on every stage each tick (uniform
    SPMD — see module docstring); with a tp-sharded vocab their flops
    divide by tp.
    """
    n_stages = mesh.shape[axis]
    m = x_micro.shape[0]
    window = 2 * n_stages
    ticks = m + 2 * (n_stages - 1)
    perm_f = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    perm_b = [(i, (i - 1) % n_stages) for i in range(n_stages)]

    def device_body(stage_params_local, io_params, xm, tgt, msk):
        sid = lax.axis_index(axis)
        last = sid == n_stages - 1
        first = sid == 0
        sp = jax.tree.map(lambda a: a[0], stage_params_local)
        if embed_fn is None:
            act0 = jnp.zeros_like(xm[0])
        else:
            act_s = jax.eval_shape(embed_fn, io_params, xm[0])
            act0 = jnp.zeros(act_s.shape, act_s.dtype)

        carry0 = dict(
            fbuf=act0,
            bbuf=act0,
            stash=jnp.zeros((window,) + act0.shape, act0.dtype),
            d_sp=jax.tree.map(jnp.zeros_like, sp),
            d_io=jax.tree.map(jnp.zeros_like, io_params),
            loss=jnp.float32(0.0),
            weight=jnp.float32(0.0),
        )
        if embed_fn is None:
            carry0["d_x"] = jnp.zeros_like(xm)

        def tick(c, t):
            # --- forward slot: mb f flows down the ring ---
            f = t - sid
            f_on = jnp.logical_and(f >= 0, f < m)
            fc = jnp.clip(f, 0, m - 1)
            x_f = xm[fc] if embed_fn is None else embed_fn(io_params, xm[fc])
            inp = jnp.where(first, x_f, c["fbuf"])
            slot = fc % window
            prev = lax.dynamic_index_in_dim(c["stash"], slot, 0,
                                            keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                c["stash"], jnp.where(f_on, inp, prev), slot, 0)
            y = stage_fn(sp, inp)

            # --- loss slot: last stage turns y around into a grad ---
            (l_mb, w_mb), head_vjp = jax.vjp(
                lambda hp, yy: head_fn(hp, yy, tgt[fc], msk[fc]),
                io_params, y)
            d_io_mb, dy = head_vjp((jnp.float32(1.0), jnp.float32(0.0)))
            turn_f = jnp.logical_and(last, f_on).astype(jnp.float32)
            loss = c["loss"] + turn_f * l_mb
            weight = c["weight"] + turn_f * w_mb
            d_io = jax.tree.map(
                lambda acc, g: acc
                + (g.astype(jnp.float32) * turn_f).astype(acc.dtype),
                c["d_io"], d_io_mb)

            # --- backward slot: mb b flows back up the ring ---
            b = t - 2 * (n_stages - 1) + sid
            b_on = jnp.logical_and(b >= 0, b < m)
            bc = jnp.clip(b, 0, m - 1)
            g_in = jnp.where(last, dy.astype(act0.dtype), c["bbuf"])
            x_in = lax.dynamic_index_in_dim(stash, bc % window, 0,
                                            keepdims=False)
            _, stage_vjp = jax.vjp(stage_fn, sp, x_in)
            d_sp_mb, dx = stage_vjp(g_in)
            b_on_f = b_on.astype(jnp.float32)
            d_sp = jax.tree.map(
                lambda acc, g: acc
                + (g.astype(jnp.float32) * b_on_f).astype(acc.dtype),
                c["d_sp"], d_sp_mb)
            nc = dict(
                fbuf=lax.ppermute(y, axis, perm_f),
                bbuf=lax.ppermute(dx, axis, perm_b),
                stash=stash, d_sp=d_sp, loss=loss, weight=weight,
            )
            if embed_fn is None:
                d_x_upd = lax.dynamic_update_index_in_dim(
                    c["d_x"], dx.astype(c["d_x"].dtype), bc, 0)
                nc["d_x"] = jnp.where(jnp.logical_and(b_on, first), d_x_upd,
                                      c["d_x"])
                nc["d_io"] = d_io
            else:
                # stage 0 converts its input-grad into an embedding grad
                _, embed_vjp = jax.vjp(
                    lambda io: embed_fn(io, xm[bc]), io_params)
                (d_io_emb,) = embed_vjp(dx.astype(act0.dtype))
                gate = jnp.logical_and(b_on, first).astype(jnp.float32)
                nc["d_io"] = jax.tree.map(
                    lambda acc, g: acc
                    + (g.astype(jnp.float32) * gate).astype(acc.dtype),
                    d_io, d_io_emb)
            return nc, None

        c, _ = lax.scan(tick, carry0, jnp.arange(ticks))
        # Per-stage grads stay stage-sharded. Grads living on one stage
        # (io on first/last, d_x on first) are returned STAGE-STACKED
        # (out_spec P(axis)) and reduced by the caller: an in-region psum
        # of these carry-accumulated pytrees trips an XLA partitioner
        # crash ("Invalid binary instruction opcode copy") under
        # partially-manual shard_map.
        stack = lambda tree: jax.tree.map(  # noqa: E731
            lambda a: jnp.expand_dims(a, 0), tree)
        out = (
            lax.psum(c["loss"], axis),
            lax.psum(c["weight"], axis),
            stack(c["d_sp"]),
            stack(c["d_io"]),
        )
        if embed_fn is None:
            out = out + (stack(c["d_x"]),)
        return out

    out_specs = (P(), P(), P(axis), P(axis))
    if embed_fn is None:
        out_specs = out_specs + (P(axis),)
    fn = jax.shard_map(
        device_body, mesh=mesh,
        in_specs=(P(axis), P(), P(), P(), P()),
        out_specs=out_specs,
        axis_names={axis},
        check_vma=False,
    )
    out = fn(stage_params, io_params, x_micro, tgt_micro, msk_micro)
    loss, weight, d_sp, d_io_stacked = out[:4]
    # cross-stage reduce of the single-stage grads (only one stage's slot
    # is nonzero, but summing is uniform and cheap)
    unstack = lambda tree: jax.tree.map(  # noqa: E731
        lambda a: jnp.sum(a, axis=0), tree)
    d_io = unstack(d_io_stacked)
    d_x = unstack(out[4]) if embed_fn is None else None
    return (loss, weight), (d_sp, d_io, d_x)


# ---------------------------------------------------------------------------
# Llama integration
# ---------------------------------------------------------------------------


def make_llama_stage_fn(cfg, sin, cos, attn_impl: str = "auto"):
    """(stage_blocks [L/ns, ...], x [mb, s, d]) -> x: one pipeline stage =
    a scan over its contiguous layer group, honoring cfg.remat."""
    from ray_tpu.models.llama import _block

    def stage_fn(stage_blocks, xm):
        body = partial(_block, cfg, sin=sin, cos=cos, segment_ids=None,
                       attn_impl=attn_impl)
        if cfg.remat == "full":
            body = jax.checkpoint(body)
        elif cfg.remat == "dots":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
            )

        def scan_fn(x, layer_params):
            return body(x, layer_params), None

        out, _ = lax.scan(scan_fn, xm, stage_blocks)
        return out

    return stage_fn


def make_llama_head_fn(cfg):
    """(head_params, y [mb,s,d], tgt [mb,s], msk [mb,s]) ->
    (loss_sum, weight_sum): final norm + LM head + masked CE sums, for the
    1F1B loss slot. head_params = {"final_norm", "embedding"|"lm_head"}."""
    from ray_tpu.ops.norms import rms_norm

    def head_fn(hp, y, tgt, msk):
        h = rms_norm(y, hp["final_norm"], eps=cfg.rms_eps)
        head = (hp["embedding"].T if cfg.tie_embeddings else hp["lm_head"])
        logits = jnp.einsum("bsd,dv->bsv", h, head,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(
            logits, jnp.maximum(tgt, 0)[..., None], axis=-1).squeeze(-1)
        mk = msk.astype(jnp.float32)
        return jnp.sum((lse - tl) * mk), jnp.sum(mk)

    return head_fn


def llama_forward_pipelined(
    cfg,
    params: dict,
    tokens,                    # [batch, seq]
    *,
    mesh: Mesh,
    axis: str = "pp",
    n_microbatches: int | None = None,
    attn_impl: str = "auto",
):
    """Llama forward with the layer stack pipelined over ``axis``.

    Embedding and the LM head run outside the pipelined region under plain
    GSPMD (they live on every stage; their cost is O(vocab) once, not per
    layer). Default positions only (no packing/segment support in v1).
    """
    from ray_tpu.ops.norms import rms_norm
    from ray_tpu.ops.rope import rope_sin_cos

    n_stages = mesh.shape[axis]
    m = n_microbatches or n_stages
    b, s = tokens.shape
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")

    x = params["embedding"][tokens]  # [b, s, d]
    positions = jnp.arange(s, dtype=jnp.int32)[None, :]
    sin, cos = rope_sin_cos(positions, cfg.head_dim, theta=cfg.rope_theta)

    stage_fn = make_llama_stage_fn(cfg, sin, cos, attn_impl)
    stage_params = split_stages(params["blocks"], n_stages)
    x_micro = x.reshape(m, b // m, s, x.shape[-1])
    out = pipeline_apply(stage_fn, stage_params, x_micro, mesh=mesh,
                         axis=axis)
    x = out.reshape(b, s, x.shape[-1])

    x = rms_norm(x, params["final_norm"], eps=cfg.rms_eps)
    from ray_tpu.models import llama

    head = llama.lm_head_weights(cfg, params)
    return jnp.einsum("bsd,dv->bsv", x, head,
                      preferred_element_type=jnp.float32)
