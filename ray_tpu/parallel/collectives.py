"""Collective communication.

Two planes, mirroring SURVEY.md §5's breakdown:

1. **Device plane (ICI/DCN)** — XLA collectives inside jit/shard_map. These
   are thin wrappers over ``jax.lax`` primitives; XLA compiles them onto the
   torus. This replaces the reference's NCCL groups entirely.

2. **Host plane (CPU tensors, control data)** — an actor-group collective API
   with the same surface as the reference's ``ray.util.collective``
   (``collective.py:120 init_collective_group``, ``:258 allreduce``,
   ``:531 send``): declarative groups keyed by name, ranks are actors. The
   local-mode backend reduces via the object store (Gloo analog); a C++
   backend can slot in underneath without changing the API.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import jax
import numpy as np
from jax import lax

# ---------------------------------------------------------------------------
# Device plane: in-jit collectives (use inside shard_map/pjit)
# ---------------------------------------------------------------------------


def psum(x, axis: str):
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis_name=axis)

def pmax(x, axis: str):
    return lax.pmax(x, axis_name=axis)


def all_gather(x, axis: str, *, gather_axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name=axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0, tiled: bool = True):
    return lax.psum_scatter(
        x, axis_name=axis, scatter_dimension=scatter_axis, tiled=tiled
    )


def ppermute(x, axis: str, perm):
    return lax.ppermute(x, axis_name=axis, perm=perm)


def ring_shift(x, axis: str, *, shift: int = 1):
    """Shift values around the mesh-axis ring (building block of ring
    attention / pipeline microbatch rotation)."""
    n = lax.psum(1, axis_name=axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int, tiled: bool = True):
    return lax.all_to_all(
        x, axis_name=axis, split_axis=split_axis, concat_axis=concat_axis,
        tiled=tiled,
    )


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.psum(1, axis_name=axis)


# ---------------------------------------------------------------------------
# Host plane: actor collective groups (reference: ray.util.collective)
# ---------------------------------------------------------------------------


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MAX = "max"
    MIN = "min"


_REDUCERS = {
    ReduceOp.SUM: lambda arrs: np.sum(arrs, axis=0),
    ReduceOp.PRODUCT: lambda arrs: np.prod(arrs, axis=0),
    ReduceOp.MAX: lambda arrs: np.max(arrs, axis=0),
    ReduceOp.MIN: lambda arrs: np.min(arrs, axis=0),
}


def _private_copy(x):
    """Copy combine() results so each rank owns its buffer (in-place math on
    one rank's result must not corrupt another's)."""
    if isinstance(x, np.ndarray):
        return x.copy()
    if isinstance(x, list):
        return [_private_copy(e) for e in x]
    return x


@dataclass
class _GroupState:
    name: str
    world_size: int
    backend: str
    lock: threading.Lock
    cv: threading.Condition
    # per-collective rendezvous state, keyed by op sequence number
    contributions: dict
    results: dict
    seq: dict


class GroupManager:
    """Host-collective group registry (reference: ``GroupManager`` at
    ``util/collective/collective.py:40``). Local-mode rendezvous barrier +
    numpy reduction; ranks may be any threads/actors in this process."""

    def __init__(self):
        self._groups: dict[str, _GroupState] = {}
        self._lock = threading.Lock()

    def create(self, name: str, world_size: int, backend: str = "local"):
        with self._lock:
            if name in self._groups:
                raise ValueError(f"Collective group {name!r} already exists")
            lock = threading.Lock()
            self._groups[name] = _GroupState(
                name=name, world_size=world_size, backend=backend, lock=lock,
                cv=threading.Condition(lock), contributions={}, results={},
                seq={},
            )

    def get(self, name: str) -> _GroupState:
        with self._lock:
            if name not in self._groups:
                raise KeyError(f"No collective group {name!r}")
            return self._groups[name]

    def destroy(self, name: str):
        with self._lock:
            self._groups.pop(name, None)

    def _rendezvous(self, group: str, rank: int, key: str, value, combine,
                    timeout: float = 60.0):
        """Generic barrier: all ranks contribute; `combine` runs once on the
        full contribution dict; every rank receives a private copy of the
        result (NCCL/gloo semantics: each rank owns its output buffer).

        Each rank's n-th call with a given `key` joins epoch n, so
        back-to-back collectives on the same group can't cross-talk even if
        a fast rank starts the next op before slow ranks finish this one.
        On timeout the rank withdraws its contribution and rolls back its
        epoch, so a retry re-joins the same epoch instead of desynchronizing
        the group.
        """
        g = self.get(group)
        with g.cv:
            epoch = g.seq.get((key, rank), 0)
            g.seq[(key, rank)] = epoch + 1
            op_id = (key, epoch)
            bucket = g.contributions.setdefault(op_id, {})
            if rank in bucket:
                raise RuntimeError(
                    f"rank {rank} contributed twice to {op_id} in {group!r}"
                )
            bucket[rank] = value
            if len(bucket) == g.world_size:
                g.results[op_id] = [combine(bucket), 0]
                del g.contributions[op_id]
                g.cv.notify_all()
            else:
                while op_id not in g.results:
                    if not g.cv.wait(timeout=timeout):
                        # withdraw cleanly so a retry can rejoin this epoch
                        still = g.contributions.get(op_id)
                        if still is not None:
                            still.pop(rank, None)
                            if not still:
                                del g.contributions[op_id]
                        g.seq[(key, rank)] = epoch
                        raise TimeoutError(
                            f"collective {key!r} timed out in group "
                            f"{group!r} (rank {rank}, epoch {epoch}, "
                            f"{len(g.contributions.get(op_id, {}))}/"
                            f"{g.world_size} arrived)"
                        )
            slot = g.results[op_id]
            slot[1] += 1
            if slot[1] == g.world_size:  # last rank out frees the slot
                del g.results[op_id]
            return _private_copy(slot[0])


_group_manager = GroupManager()


def group_manager() -> GroupManager:
    return _group_manager


def init_collective_group(world_size: int, rank: int, *,
                          group_name: str = "default", backend: str = "local"):
    """Declarative group creation (reference ``collective.py:120``). Safe to
    call from every rank; first caller creates the group."""
    try:
        _group_manager.create(group_name, world_size, backend)
    except ValueError:
        pass
    return rank


def destroy_collective_group(group_name: str = "default"):
    _group_manager.destroy(group_name)


def allreduce(tensor, rank: int, *, group_name: str = "default",
              op: str = ReduceOp.SUM):
    arr = np.asarray(tensor)
    result = _group_manager._rendezvous(
        group_name, rank, f"allreduce_{op}",
        arr, lambda bucket: _REDUCERS[op](np.stack(list(bucket.values()))),
    )
    return result


def allgather(tensor, rank: int, *, group_name: str = "default"):
    arr = np.asarray(tensor)
    return _group_manager._rendezvous(
        group_name, rank, "allgather",
        arr, lambda bucket: [bucket[r] for r in sorted(bucket)],
    )


def broadcast(tensor, rank: int, *, src_rank: int = 0,
              group_name: str = "default"):
    arr = np.asarray(tensor) if tensor is not None else None
    return _group_manager._rendezvous(
        group_name, rank, "broadcast",
        arr, lambda bucket: bucket[src_rank],
    )


def reducescatter(tensor, rank: int, *, group_name: str = "default",
                  op: str = ReduceOp.SUM):
    arr = np.asarray(tensor)

    def combine(bucket):
        full = _REDUCERS[op](np.stack(list(bucket.values())))
        return np.array_split(full, len(bucket), axis=0)

    chunks = _group_manager._rendezvous(
        group_name, rank, f"reducescatter_{op}", arr, combine
    )
    return chunks[rank]

def barrier(rank: int, *, group_name: str = "default"):
    _group_manager._rendezvous(group_name, rank, "barrier", None,
                               lambda bucket: True)
