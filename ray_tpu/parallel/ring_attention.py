"""Ring attention: exact attention over sequence-sharded inputs.

Net-new capability vs. the reference (SURVEY.md §2c: sequence/context
parallelism and ring attention are ABSENT there — verified by repo grep).
Design: the sequence axis is sharded over the ``sp`` mesh axis; each device
holds a contiguous [b, s/n, h, d] chunk of q/k/v. KV chunks rotate around the
ICI ring via ``lax.ppermute`` while every device accumulates blockwise
attention for its local queries with an online log-sum-exp merge — O(s/n)
memory per device, full-sequence exactness, and the KV transfer overlaps the
attention compute of the previous step (XLA schedules the ppermute
asynchronously with the matmuls).

Causality over the ring: with contiguous layout, a KV chunk that originated
on source device ``src`` relative to my index ``idx``:
    src <  idx  → all keys precede all my queries → full (unmasked) block
    src == idx  → the diagonal block → causal mask
    src >  idx  → all keys follow my queries → skipped (no compute)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _chunk_attention(q, k, v, *, scale, mask):
    """Blockwise attention returning (o_unnormalized_by_softmax_merge, lse).

    q: [b, sq, h, d]; k/v: [b, sk, hk, d] (GQA repeat applied here).
    Returns o: [b, sq, h, d] (already divided by this block's denominator)
    and lse: [b, sq, h] log-sum-exp of this block's logits.
    """
    b, sq, h, d = q.shape
    hk = k.shape[2]
    if hk != h:
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    lse = jax.nn.logsumexp(logits, axis=-1)           # [b, h, sq]
    probs = jnp.exp(logits - lse[..., None])
    probs = jnp.where(jnp.isfinite(lse)[..., None], probs, 0.0)
    o = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.astype(jnp.float32), lse.transpose(0, 2, 1)  # lse: [b, sq, h]


def _merge(o1, lse1, o2, lse2):
    """Merge two partial attention results (log-sum-exp weighted)."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    w1 = jnp.where(jnp.isfinite(lse1)[..., None], w1, 0.0)
    w2 = jnp.where(jnp.isfinite(lse2)[..., None], w2, 0.0)
    return o1 * w1 + o2 * w2, lse


def _ring_body(axis_name: str, n: int, scale: float, causal: bool,
               q, k0, v0):
    """Per-device ring loop. q/k0/v0: local chunks [b, sc, h|hk, d]."""
    idx = lax.axis_index(axis_name)
    b, sc, h, d = q.shape

    perm = [(i, (i + 1) % n) for i in range(n)]  # send to next rank

    def step(carry, r):
        o, lse, k, v = carry
        src = (idx - r) % n  # originating device of the current kv chunk

        def attend(_):
            if causal:
                qpos = jnp.arange(sc)[:, None]
                kpos = jnp.arange(sc)[None, :]
                diag_mask = (kpos <= qpos)[None, None]
                mask = jnp.where(src == idx, diag_mask,
                                 jnp.ones_like(diag_mask))
                mask = mask & (src <= idx)
            else:
                mask = None
            return _chunk_attention(q, k, v, scale=scale, mask=mask)

        def skip(_):
            return (jnp.zeros((b, sc, h, d), jnp.float32),
                    jnp.full((b, sc, h), -jnp.inf, jnp.float32))

        if causal:
            o_r, lse_r = lax.cond(src <= idx, attend, skip, None)
        else:
            o_r, lse_r = attend(None)
        o, lse = _merge(o, lse, o_r, lse_r)
        # rotate kv to the next device (overlaps with next step's compute)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return (o, lse, k, v), None

    o0 = jnp.zeros((b, sc, h, d), jnp.float32)
    lse0 = jnp.full((b, sc, h), -jnp.inf, jnp.float32)
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k0, v0), jnp.arange(n))
    return o.astype(q.dtype)


def ring_attention(
    q, k, v, *, mesh: Mesh, axis: str = "sp", causal: bool = True,
    scale: float | None = None, batch_axes=("dp", "fsdp"),
    head_axis: str = "tp",
):
    """Exact attention with the sequence axis sharded over ``axis``.

    q/k/v: [batch, seq, heads, head_dim] GLOBAL arrays (sharded or not —
    shard_map re-shards per in_specs). Returns same-shape output sharded the
    same way. Callable inside jit.

    The batch dim stays sharded over ``batch_axes`` and heads over
    ``head_axis`` (when present on the mesh and divisible) so the shard_map
    region does NOT replicate compute across non-sp mesh axes — the ring
    only rotates along ``axis``; all other axes partition independent work.
    """
    if mesh is None:
        raise ValueError("ring_attention requires mesh=")
    b, s, h, d = q.shape
    hk = k.shape[2]
    n = mesh.shape[axis]
    if s % n:
        raise ValueError(f"seq {s} not divisible by {axis} size {n}")
    scale = scale if scale is not None else d ** -0.5

    import math

    b_ax = tuple(
        a for a in batch_axes
        if a in mesh.axis_names and mesh.shape[a] > 1
    )
    if b_ax and b % math.prod(mesh.shape[a] for a in b_ax):
        b_ax = ()
    h_ax = (
        head_axis
        if head_axis in mesh.axis_names and mesh.shape[head_axis] > 1
        and h % mesh.shape[head_axis] == 0 and hk % mesh.shape[head_axis] == 0
        else None
    )

    body = partial(_ring_body, axis, n, scale, causal)
    spec = P(b_ax or None, axis, h_ax, None)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
