"""Ring attention: exact attention over sequence-sharded inputs.

Net-new capability vs. the reference (SURVEY.md §2c: sequence/context
parallelism and ring attention are ABSENT there — verified by repo grep).
Design: the sequence axis is sharded over the ``sp`` mesh axis; each device
holds a contiguous [b, s/n, h, d] chunk of q/k/v. KV chunks rotate around the
ICI ring via ``lax.ppermute`` while every device accumulates blockwise
attention for its local queries with an online log-sum-exp merge — O(s/n)
memory per device, full-sequence exactness, and the KV transfer overlaps the
attention compute of the previous step (XLA schedules the ppermute
asynchronously with the matmuls).

Causality over the ring: with contiguous layout, a KV chunk that originated
on source device ``src`` relative to my index ``idx``:
    src <  idx  → all keys precede all my queries → full (unmasked) block
    src == idx  → the diagonal block → causal mask
    src >  idx  → all keys follow my queries → skipped (no compute)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _chunk_attention(q, k, v, *, scale, mask):
    """Blockwise attention returning (o_unnormalized_by_softmax_merge, lse).

    q: [b, sq, h, d]; k/v: [b, sk, hk, d] (GQA repeat applied here).
    Returns o: [b, sq, h, d] (already divided by this block's denominator)
    and lse: [b, sq, h] log-sum-exp of this block's logits.
    """
    b, sq, h, d = q.shape
    hk = k.shape[2]
    if hk != h:
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    lse = jax.nn.logsumexp(logits, axis=-1)           # [b, h, sq]
    probs = jnp.exp(logits - lse[..., None])
    probs = jnp.where(jnp.isfinite(lse)[..., None], probs, 0.0)
    o = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.astype(jnp.float32), lse.transpose(0, 2, 1)  # lse: [b, sq, h]


def _merge(o1, lse1, o2, lse2):
    """Merge two partial attention results (log-sum-exp weighted)."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    w1 = jnp.where(jnp.isfinite(lse1)[..., None], w1, 0.0)
    w2 = jnp.where(jnp.isfinite(lse2)[..., None], w2, 0.0)
    return o1 * w1 + o2 * w2, lse


def _ring_body(axis_name: str, n: int, scale: float, causal: bool,
               q, k0, v0):
    """Per-device ring loop. q/k0/v0: local chunks [b, sc, h|hk, d]."""
    idx = lax.axis_index(axis_name)
    b, sc, h, d = q.shape

    perm = [(i, (i + 1) % n) for i in range(n)]  # send to next rank

    def step(carry, r):
        o, lse, k, v = carry
        src = (idx - r) % n  # originating device of the current kv chunk

        def attend(_):
            if causal:
                qpos = jnp.arange(sc)[:, None]
                kpos = jnp.arange(sc)[None, :]
                diag_mask = (kpos <= qpos)[None, None]
                mask = jnp.where(src == idx, diag_mask,
                                 jnp.ones_like(diag_mask))
                mask = mask & (src <= idx)
            else:
                mask = None
            return _chunk_attention(q, k, v, scale=scale, mask=mask)

        def skip(_):
            return (jnp.zeros((b, sc, h, d), jnp.float32),
                    jnp.full((b, sc, h), -jnp.inf, jnp.float32))

        if causal:
            o_r, lse_r = lax.cond(src <= idx, attend, skip, None)
        else:
            o_r, lse_r = attend(None)
        o, lse = _merge(o, lse, o_r, lse_r)
        # rotate kv to the next device (overlaps with next step's compute)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return (o, lse, k, v), None

    o0 = jnp.zeros((b, sc, h, d), jnp.float32)
    lse0 = jnp.full((b, sc, h), -jnp.inf, jnp.float32)
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k0, v0), jnp.arange(n))
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Fused Pallas ring: per-step flash kernels for BOTH directions.
#
# Forward: each ring step runs the flash forward kernel (with LSE out) on
# the resident KV chunk; partial results merge online exactly like the
# reference-math path. Backward is a custom VJP implementing the ring
# itself: the flash backward kernels recompute P from the FINAL merged
# lse (the blockwise-global form — no per-step dlse term exists), dq
# accumulates locally, and (k, v, dk, dv) travel the ring together so a
# chunk's grads come home after n hops. Memory stays O(s/n) per device;
# every matmul is an MXU-tiled Pallas block.
# ---------------------------------------------------------------------------


def _ring_flash_steps(qt, k0, v0, axis_name, n, scale, causal, blocks,
                      interpret):
    """Forward ring in kernel layout [b, h, s, d]; returns (o f32, lse
    f32 [b,h,s])."""
    from ray_tpu.ops.flash_attention import _fit_block, _flash_fwd

    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    sc = qt.shape[2]
    bq = _fit_block(sc, blocks[0])
    bk = _fit_block(sc, blocks[1])

    # r = 0: the diagonal chunk — STATICALLY causal (kernel-level mask)
    o, lse8 = _flash_fwd(qt, k0, v0, scale=scale, causal=causal,
                         block_q=bq, block_k=bk, interpret=interpret,
                         with_lse=True)
    o = o.astype(jnp.float32)
    lse = lse8[..., 0]
    k = lax.ppermute(k0, axis_name, perm)
    v = lax.ppermute(v0, axis_name, perm)

    def step(carry, r):
        o, lse, k, v = carry

        def attend(_):
            o_r, lse_r = _flash_fwd(qt, k, v, scale=scale, causal=False,
                                    block_q=bq, block_k=bk,
                                    interpret=interpret, with_lse=True)
            return o_r.astype(jnp.float32), lse_r[..., 0]

        def skip(_):
            return (jnp.zeros_like(o),
                    jnp.full_like(lse, -jnp.inf))

        if causal:
            # chunk from src=(idx-r)%n precedes my queries iff idx >= r
            o_r, lse_r = lax.cond(idx >= r, attend, skip, None)
        else:
            o_r, lse_r = attend(None)
        o, lse = _merge(o, lse, o_r, lse_r)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return (o, lse, k, v), None

    if n > 1:
        (o, lse, _, _), _ = lax.scan(step, (o, lse, k, v),
                                     jnp.arange(1, n))
    return o, lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(qt, k0, v0, axis_name, n, scale, causal, blocks,
                interpret):
    o, _ = _ring_flash_steps(qt, k0, v0, axis_name, n, scale, causal,
                             blocks, interpret)
    return o.astype(qt.dtype)


def _ring_flash_vjp_fwd(qt, k0, v0, axis_name, n, scale, causal, blocks,
                        interpret):
    o, lse = _ring_flash_steps(qt, k0, v0, axis_name, n, scale, causal,
                               blocks, interpret)
    o = o.astype(qt.dtype)
    return o, (qt, k0, v0, o, lse)


def _ring_flash_vjp_bwd(axis_name, n, scale, causal, blocks, interpret,
                        res, do):
    from ray_tpu.ops.flash_attention import _fit_block, _flash_bwd

    qt, k0, v0, o, lse = res
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    sc = qt.shape[2]
    bq = _fit_block(sc, blocks[0])
    bk = _fit_block(sc, blocks[1])
    lse8 = jnp.broadcast_to(lse[..., None], (*lse.shape, 8))
    do = do.astype(qt.dtype)

    # r = 0: own (diagonal) chunk, statically causal kernels
    dq_acc, dk, dv = _flash_bwd(qt, k0, v0, o, lse8, do, scale=scale,
                                causal=causal, block_q=bq, block_k=bk,
                                interpret=interpret)
    dq_acc = dq_acc.astype(jnp.float32)
    # (k, v, dk, dv) ride the ring together: after n hops each chunk's
    # accumulated grads are home
    k = lax.ppermute(k0, axis_name, perm)
    v = lax.ppermute(v0, axis_name, perm)
    dk = lax.ppermute(dk.astype(jnp.float32), axis_name, perm)
    dv = lax.ppermute(dv.astype(jnp.float32), axis_name, perm)

    def step(carry, r):
        dq_acc, k, v, dk, dv = carry

        def compute(_):
            dq_r, dk_r, dv_r = _flash_bwd(
                qt, k, v, o, lse8, do, scale=scale, causal=False,
                block_q=bq, block_k=bk, interpret=interpret)
            return (dq_r.astype(jnp.float32), dk_r.astype(jnp.float32),
                    dv_r.astype(jnp.float32))

        def skip(_):
            return (jnp.zeros_like(dq_acc), jnp.zeros_like(dk),
                    jnp.zeros_like(dv))

        if causal:
            dq_r, dk_r, dv_r = lax.cond(idx >= r, compute, skip, None)
        else:
            dq_r, dk_r, dv_r = compute(None)
        dq_acc = dq_acc + dq_r
        dk = lax.ppermute(dk + dk_r, axis_name, perm)
        dv = lax.ppermute(dv + dv_r, axis_name, perm)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return (dq_acc, k, v, dk, dv), None

    if n > 1:
        (dq_acc, _, _, dk, dv), _ = lax.scan(
            step, (dq_acc, k, v, dk, dv), jnp.arange(1, n))
    return (dq_acc.astype(qt.dtype), dk.astype(k0.dtype),
            dv.astype(v0.dtype))


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def _ring_flash_body(axis_name, n, scale, causal, blocks, interpret,
                     q, k0, v0):
    """shard_map body adapter: [b, sc, h, d] boundary layout <-> the
    kernels' [b, h, s, d]."""
    qt = q.transpose(0, 2, 1, 3)
    kt = k0.transpose(0, 2, 1, 3)
    vt = v0.transpose(0, 2, 1, 3)
    out = _ring_flash(qt, kt, vt, axis_name, n, scale, causal, blocks,
                      interpret)
    return out.transpose(0, 2, 1, 3)


def ring_attention(
    q, k, v, *, mesh: Mesh, axis: str = "sp", causal: bool = True,
    scale: float | None = None, batch_axes=("dp", "fsdp"),
    head_axis: str = "tp", impl: str = "auto",
    block_q: int = 512, block_k: int = 1024,
):
    """Exact attention with the sequence axis sharded over ``axis``.

    q/k/v: [batch, seq, heads, head_dim] GLOBAL arrays (sharded or not —
    shard_map re-shards per in_specs). Returns same-shape output sharded the
    same way. Callable inside jit.

    The batch dim stays sharded over ``batch_axes`` and heads over
    ``head_axis`` (when present on the mesh and divisible) so the shard_map
    region does NOT replicate compute across non-sp mesh axes — the ring
    only rotates along ``axis``; all other axes partition independent work.
    """
    if mesh is None:
        raise ValueError("ring_attention requires mesh=")
    b, s, h, d = q.shape
    hk = k.shape[2]
    n = mesh.shape[axis]
    if s % n:
        raise ValueError(f"seq {s} not divisible by {axis} size {n}")
    scale = scale if scale is not None else d ** -0.5

    import math

    b_ax = tuple(
        a for a in batch_axes
        if a in mesh.axis_names and mesh.shape[a] > 1
    )
    if b_ax and b % math.prod(mesh.shape[a] for a in b_ax):
        b_ax = ()
    h_ax = (
        head_axis
        if head_axis in mesh.axis_names and mesh.shape[head_axis] > 1
        and h % mesh.shape[head_axis] == 0 and hk % mesh.shape[head_axis] == 0
        else None
    )

    if impl not in ("auto", "flash", "reference"):
        raise ValueError(
            f"ring_attention impl must be 'auto', 'flash' or 'reference', "
            f"got {impl!r}")
    use_flash = impl == "flash" or (
        impl == "auto" and jax.devices()[0].platform == "tpu")
    if use_flash:
        # interpret-mode keeps the fused path testable off-TPU
        interpret = jax.devices()[0].platform != "tpu"
        body = partial(_ring_flash_body, axis, n, scale, causal,
                       (block_q, block_k), interpret)
    else:
        body = partial(_ring_body, axis, n, scale, causal)
    spec = P(b_ax or None, axis, h_ax, None)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
