"""Public API: init / remote / get / put / wait / kill / cancel / actors.

Analog of the reference's ``python/ray/_private/worker.py`` public surface
(``init:1139``, ``get:2461``, ``put:2590``, ``wait:2653``, ``remote:3027``)
plus ``remote_function.py`` and ``actor.py``. Semantics match the reference:

- ``@remote`` on a function -> ``f.remote(*args)`` returns ObjectRef(s).
- ``@remote`` on a class -> ``Cls.remote(*args)`` returns an ActorHandle;
  ``handle.method.remote(...)`` returns ObjectRefs; calls on one handle with
  ``max_concurrency=1`` execute in submission order.
- ObjectRefs passed as top-level arguments are resolved to values before the
  task body runs.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Sequence

from ray_tpu.runtime import core as _core
from ray_tpu.runtime.object_ref import ObjectRef
from ray_tpu.runtime.task_spec import (
    ResourceSet,
    SchedulingStrategy,
    TaskSpec,
    TaskType,
)
from ray_tpu.utils.config import Config, get_config, reset_config
from ray_tpu.utils.ids import ActorID, TaskID


# ---------------------------------------------------------------------------
# init / shutdown
# ---------------------------------------------------------------------------

def init(
    *,
    address=None,
    resources: dict | None = None,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    system_config: dict | None = None,
    ignore_reinit_error: bool = True,
    namespace: str | None = None,
    log_to_driver: bool = True,
):
    """Start the runtime (reference: ``ray.init``, ``worker.py:1139``).

    In-process local cluster by default; TPU devices visible to JAX are
    registered as a ``TPU`` resource. Pass ``address=(host, port)`` (a GCS
    address, e.g. ``cluster_utils.Cluster().gcs_address``) or
    ``"host:port"`` to connect to a running cluster instead.
    """
    if _core.is_initialized():
        if ignore_reinit_error:
            return _core.get_runtime()
        raise RuntimeError("ray_tpu.init() called twice")
    if address is not None:
        from ray_tpu.client import ClientRuntime, parse_client_address
        from ray_tpu.runtime.driver import ClusterRuntime

        client_addr = parse_client_address(address) \
            if isinstance(address, str) else None
        if client_addr is not None:
            rt = ClientRuntime(client_addr)
            _core.install_runtime(rt)
            return rt
        if isinstance(address, str):
            host, sep, port = address.rpartition(":")
            if not sep or not port.isdigit():
                raise ValueError(
                    f"address must be 'host:port' or a (host, port) tuple, "
                    f"got {address!r}")
            address = (host or "127.0.0.1", int(port))
        rt = ClusterRuntime(address, namespace=namespace,
                            log_to_driver=log_to_driver)
        _core.install_runtime(rt)
        return rt
    from ray_tpu._private.usage_stats import record_extra_usage_tag

    record_extra_usage_tag("init_count")
    reset_config()
    config = get_config().apply_overrides(system_config)
    res = dict(resources or {})
    if num_cpus is not None:
        res["CPU"] = float(num_cpus)
    if num_tpus is not None:
        res["TPU"] = float(num_tpus)
    else:
        res.setdefault("TPU", float(_autodetect_tpu_count()))
    return _core.init_runtime(config=config, resources=res,
                              namespace=namespace)


def _autodetect_tpu_count() -> int:
    """TPU autodetect (reference: ``_private/accelerator.py:20,35`` probes GCE
    metadata; here we ask JAX directly, without forcing a backend init)."""
    import os

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return 0
    try:
        import jax

        return sum(1 for d in jax.devices() if d.platform == "tpu")
    except Exception:  # noqa: BLE001 - no TPU runtime present
        return 0


def shutdown():
    _core.shutdown_runtime()


def is_initialized() -> bool:
    return _core.is_initialized()


def _runtime() -> _core.Runtime:
    if not _core.is_initialized():
        import os

        gcs_host = os.environ.get("RAY_TPU_GCS_HOST")
        if gcs_host:
            # inside a cluster worker: connect to this node's raylet
            # (nested task/actor submission from tasks)
            from ray_tpu.runtime.driver import ClusterRuntime

            rt = ClusterRuntime(
                (gcs_host, int(os.environ["RAY_TPU_GCS_PORT"])),
                raylet_address=(os.environ["RAY_TPU_RAYLET_HOST"],
                                int(os.environ["RAY_TPU_RAYLET_PORT"])),
            )
            _core.install_runtime(rt)
        else:
            init()
    return _core.get_runtime()


# ---------------------------------------------------------------------------
# Object API
# ---------------------------------------------------------------------------

def put(value) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed.")
    return _runtime().put(value)


def get(refs, timeout: float | None = None):
    rt = _runtime()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() list elements must be ObjectRefs, got {type(r)}")
    return rt.get(list(refs), timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return _runtime().wait(list(refs), num_returns=num_returns, timeout=timeout)


def cancel(ref: ObjectRef, *, force: bool = False):
    _runtime().cancel(ref, force=force)


# ---------------------------------------------------------------------------
# Remote functions
# ---------------------------------------------------------------------------

class RemoteFunction:
    """Wrapper created by ``@remote`` (reference: ``remote_function.py``)."""

    def __init__(self, fn, options: dict):
        self._fn = fn
        self._options = options
        # submit-invariant fields parsed ONCE (options() returns a fresh
        # RemoteFunction, so these never change for this instance) — at
        # 10k submits/s the per-call ResourceSet/strategy/env re-parse
        # was a measurable slice of the owner's submit loop
        self._resources = ResourceSet.from_options(
            num_cpus=options.get("num_cpus"),
            num_tpus=options.get("num_tpus"),
            memory=options.get("memory"),
            resources=options.get("resources"),
        )
        self._strategy = _parse_strategy(options)
        self._runtime_env = _normalize_runtime_env(
            options.get("runtime_env"))
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__!r} cannot be called directly; "
            f"use {self._fn.__name__}.remote()."
        )

    def options(self, **overrides) -> "RemoteFunction":
        bad = set(overrides) - _TASK_OPTION_KEYS
        if bad:
            raise ValueError(f"Invalid task options: {sorted(bad)}")
        merged = {**self._options, **overrides}
        return RemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        rt = _runtime()
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        if not (isinstance(num_returns, int)
                or num_returns in ("streaming", "dynamic")):
            # reference: _private/ray_option_utils.py:251-253 accepts an
            # int or the literals "dynamic" / "streaming"
            raise ValueError(
                f'num_returns must be an int, "dynamic" or "streaming", '
                f"got {num_returns!r}")
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            task_type=TaskType.NORMAL_TASK,
            function=self._fn,
            function_name=self._fn.__qualname__,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            resources=self._resources,
            scheduling_strategy=self._strategy,
            max_retries=opts.get("max_retries", 0),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            runtime_env=self._runtime_env,
            trace_ctx=_trace_ctx(self._fn.__qualname__),
        )
        refs = rt.submit_task(spec)
        rt.note_return_owner(spec)
        if num_returns == 1 or not isinstance(num_returns, int):
            return refs[0]   # single ref, or the ObjectRefGenerator
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: ``dag_node.py`` .bind)."""
        from ray_tpu.dag import DAGNode

        return DAGNode(self._fn, args, kwargs, options=self._options)

    @property
    def underlying_function(self):
        return self._fn


def _parse_strategy(opts: dict) -> SchedulingStrategy:
    s = opts.get("scheduling_strategy")
    if s is None:
        return SchedulingStrategy()
    if isinstance(s, SchedulingStrategy):
        return s
    if s == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if s == "DEFAULT":
        return SchedulingStrategy()
    # PlacementGroupSchedulingStrategy (duck-typed to avoid an import cycle
    # with ray_tpu.util.placement_group)
    if hasattr(s, "placement_group"):
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            placement_group_id=s.placement_group.id,
            bundle_index=getattr(s, "bundle_index", -1))
    raise ValueError(f"Unknown scheduling strategy: {s!r}")


# ---------------------------------------------------------------------------
# Actors
# ---------------------------------------------------------------------------

class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str):
        self._handle = handle
        self._method_name = method_name

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(self._method_name, args, kwargs)

    def options(self, **overrides):
        # per-call overrides (num_returns etc.)
        bad = set(overrides) - {"num_returns"}
        if bad:
            raise ValueError(f"Invalid actor-method options: {sorted(bad)}")
        handle = self._handle
        name = self._method_name

        class _Bound:
            def remote(self, *args, **kwargs):
                return handle._submit_method(name, args, kwargs, overrides)

        return _Bound()

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly; "
            f"use .remote()."
        )


class ActorHandle:
    """Client-side handle to an actor (reference: ``actor.py`` ActorHandle).
    Pickles by actor id, so handles can be passed to other tasks."""

    def __init__(self, actor_id: ActorID, class_name: str):
        self._actor_id = actor_id
        self._class_name = class_name

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        # cache on the instance: `a.m.remote()` in a tight loop must not
        # allocate a fresh ActorMethod per call (__getattr__ only fires
        # on misses, so the cached attribute short-circuits next time)
        method = ActorMethod(self, name)
        object.__setattr__(self, name, method)
        return method

    def _submit_method(self, method_name, args, kwargs, overrides=None):
        rt = _runtime()
        opts = overrides or {}
        num_returns = opts.get("num_returns", 1)
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            task_type=TaskType.ACTOR_TASK,
            function=None,
            function_name=f"{self._class_name}.{method_name}",
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            actor_id=self._actor_id,
            actor_method_name=method_name,
            trace_ctx=_trace_ctx(f"{self._class_name}.{method_name}"),
        )
        refs = rt.submit_task(spec)
        rt.note_return_owner(spec)
        if num_returns == 1 or not isinstance(num_returns, int):
            return refs[0]   # single ref, or the ObjectRefGenerator
        return refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"


class ActorClass:
    """Created by ``@remote`` on a class (reference: ``actor.py`` ActorClass,
    ``ActorClass.remote:524``)."""

    def __init__(self, cls, options: dict):
        self._cls = cls
        self._options = options
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote()."
        )

    def options(self, **overrides) -> "ActorClass":
        bad = set(overrides) - _ACTOR_OPTION_KEYS
        if bad:
            raise ValueError(f"Invalid actor options: {sorted(bad)}")
        return ActorClass(self._cls, {**self._options, **overrides})

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = _runtime()
        opts = self._options
        max_concurrency = opts.get("max_concurrency")
        if max_concurrency is None:
            # reference default: async actors (any ``async def`` method)
            # get high concurrency (calls interleave at awaits); threaded
            # actors stay strictly serial
            import inspect

            is_async = any(
                inspect.iscoroutinefunction(getattr(self._cls, n, None))
                for n in dir(self._cls))
            max_concurrency = 1000 if is_async else 1
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            task_type=TaskType.ACTOR_CREATION_TASK,
            function=self._cls,
            function_name=f"{self._cls.__name__}.__init__",
            args=args,
            kwargs=kwargs,
            num_returns=1,
            resources=ResourceSet.from_options(
                num_cpus=opts.get("num_cpus"),
                num_tpus=opts.get("num_tpus"),
                memory=opts.get("memory"),
                resources=opts.get("resources"),
            ),
            max_concurrency=max_concurrency,
            max_restarts=opts.get("max_restarts", 0),
            runtime_env=_normalize_runtime_env(opts.get("runtime_env")),
        )
        lifetime = opts.get("lifetime")
        if lifetime not in (None, "detached", "non_detached"):
            raise ValueError(
                f"lifetime must be None, 'detached' or 'non_detached', "
                f"got {lifetime!r}")
        actor_id = rt.create_actor(
            spec, name=opts.get("name"), namespace=opts.get("namespace"),
            lifetime=None if lifetime == "non_detached" else lifetime)
        return ActorHandle(actor_id, self._cls.__name__)


def kill(handle: ActorHandle, *, no_restart: bool = True):
    if not isinstance(handle, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    _runtime().kill_actor(handle.actor_id, no_restart=no_restart)


def get_actor(name: str, namespace: str | None = None) -> ActorHandle:
    """Look up a named actor (reference: ``worker.py:2784`` — scoped to
    the caller's namespace unless one is given explicitly)."""
    rt = _runtime()
    try:
        actor_id = rt.get_actor(name, namespace)
    except TypeError:
        actor_id = rt.get_actor(name)   # runtimes without namespaces
    state = rt.actor_state(actor_id)
    cls_name = state.creation_spec.function.__name__ if state else "Actor"
    return ActorHandle(actor_id, cls_name)


# ---------------------------------------------------------------------------
# @remote decorator
# ---------------------------------------------------------------------------

# ``lifetime``: owner-scoped actor lifetime (reference: actor.py:524 +
# gcs_actor_manager.cc:632). Default: the actor dies when its owning
# client (the creating driver/worker runtime) disconnects or misses
# heartbeats; ``lifetime="detached"`` opts the actor out — it survives
# until killed explicitly or its process dies.
_ACTOR_OPTION_KEYS = {
    "name", "namespace", "max_concurrency", "max_restarts", "num_cpus",
    "num_tpus", "memory", "resources", "lifetime", "runtime_env",
}
_TASK_OPTION_KEYS = {
    "num_returns", "num_cpus", "num_tpus", "memory", "resources",
    "max_retries", "retry_exceptions", "scheduling_strategy", "runtime_env",
}


def _trace_ctx(function_name: str):
    """Capture the tracing context at submission time (None when tracing
    is disabled — zero overhead on the default path)."""
    from ray_tpu.util import tracing

    if not tracing.is_enabled():
        return None
    return tracing.submission_context(function_name)


def _normalize_runtime_env(env):
    """Accept RuntimeEnv or plain dict; validate dicts through RuntimeEnv
    so unsupported fields (conda/container) fail at submission, not on the
    worker."""
    if env is None:
        return None
    from ray_tpu.runtime_env import RuntimeEnv

    if isinstance(env, RuntimeEnv):
        return env.to_dict()
    return RuntimeEnv(**env).to_dict()


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=2, ...)`` on functions and classes."""

    def decorate(target):
        if isinstance(target, type):
            bad = set(kwargs) - _ACTOR_OPTION_KEYS
            if bad:
                raise ValueError(f"Invalid actor options: {sorted(bad)}")
            return ActorClass(target, dict(kwargs))
        if callable(target):
            bad = set(kwargs) - _TASK_OPTION_KEYS
            if bad:
                raise ValueError(f"Invalid task options: {sorted(bad)}")
            return RemoteFunction(target, dict(kwargs))
        raise TypeError(f"@remote target must be a function or class: {target}")

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return decorate(args[0])
    if args:
        raise TypeError("@remote accepts only keyword options")
    return decorate


# ---------------------------------------------------------------------------
# Introspection
# ---------------------------------------------------------------------------

def timeline(filename: str | None = None) -> list:
    """Task timeline in chrome://tracing format (reference:
    ``ray.timeline()`` from ``_private/profiling.py:84``).

    Events carry wall-clock timestamps (``wall_start``/``wall_end``,
    anchored at record time in each worker) so they share a clock domain
    with ``ray_tpu.util.tracing`` spans — see
    ``tracing.export_chrome_trace`` for the merged view. pid is the OS
    pid of the executing process; tid is the executing thread."""
    rt = _runtime()
    if hasattr(rt, "task_events"):
        events = rt.task_events()
    else:
        # cluster mode: the GCS task-event sink (same source as the
        # state API / dashboard)
        from ray_tpu.util import state as _state

        events = [e for e in _state.list_tasks()
                  if "start" in e and "end" in e]
    trace = [
        {
            "name": e["name"],
            "cat": "task",
            "ph": "X",
            # wall stamps when present (events recorded before the
            # anchor existed fall back to raw monotonic values)
            "ts": e.get("wall_start", e["start"]) * 1e6,
            "dur": (e["end"] - e["start"]) * 1e6,
            "pid": e.get("pid", 0),
            "tid": e.get("thread", "worker"),
            "args": {"task_id": e["task_id"], "state": e["state"]},
        }
        for e in events
    ]
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


def cluster_resources() -> dict:
    return _runtime().cluster_resources()


def available_resources() -> dict:
    return _runtime().available_resources_snapshot()
