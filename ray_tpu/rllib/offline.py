"""Offline RL: logged-experience IO + algorithms that train from it.

Reference analog: ``rllib/offline/`` (JsonWriter/JsonReader sample-batch
IO, BC/CQL/MARWIL offline algorithms). TPU-first differences: shards are
columnar ``.npz`` (numpy arrays map straight into jit inputs, no
row-json decode), and the learners are single jitted SGD programs.

- :class:`DatasetWriter` / :class:`OfflineDataset` — shard transitions
  to a directory / load + minibatch them.
- ``collect_dataset`` — roll a behavior policy in an env and persist.
- :class:`BC` — behavior cloning (maximize log pi(a|s) on the data).
- :class:`CQL` — discrete conservative Q-learning: DQN TD loss plus the
  CQL(H) regularizer alpha * (logsumexp_a Q(s,a) - Q(s, a_data)) that
  penalizes out-of-distribution action values.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, replace

import numpy as np

from ray_tpu.rllib.env import make_env

_FIELDS = ("obs", "actions", "rewards", "next_obs", "dones")


class DatasetWriter:
    """Append transition batches as columnar .npz shards."""

    def __init__(self, path: str, shard_size: int = 4096):
        self.path = path
        self.shard_size = shard_size
        os.makedirs(path, exist_ok=True)
        self._buf: dict[str, list] = {k: [] for k in _FIELDS}
        self._buffered = 0
        self._n_shards = 0

    def write(self, batch: dict):
        n = len(batch["obs"])
        for k in _FIELDS:
            self._buf[k].append(np.asarray(batch[k]))
        self._buffered += n
        while self._buffered >= self.shard_size:
            self._flush_shard()

    def _cat(self):
        return {k: np.concatenate(v) if v else np.zeros((0,))
                for k, v in self._buf.items()}

    def _flush_shard(self):
        cat = self._cat()
        head = {k: v[:self.shard_size] for k, v in cat.items()}
        rest = {k: [v[self.shard_size:]] for k, v in cat.items()}
        self._write_file(head)
        self._buf = rest
        self._buffered = len(rest["obs"][0])

    def _write_file(self, arrays: dict):
        fname = os.path.join(self.path, f"shard-{self._n_shards:05d}.npz")
        np.savez_compressed(fname, **arrays)
        self._n_shards += 1

    def close(self):
        if self._buffered:
            self._write_file(self._cat())
            self._buf = {k: [] for k in _FIELDS}
            self._buffered = 0
        meta = {"num_shards": self._n_shards, "fields": list(_FIELDS)}
        with open(os.path.join(self.path, "meta.json"), "w") as f:
            json.dump(meta, f)


class OfflineDataset:
    """Load every shard in a directory into columnar arrays."""

    def __init__(self, path: str):
        shards = sorted(
            f for f in os.listdir(path) if f.endswith(".npz"))
        if not shards:
            raise FileNotFoundError(f"no .npz shards under {path}")
        cols: dict[str, list] = {k: [] for k in _FIELDS}
        for s in shards:
            with np.load(os.path.join(path, s)) as z:
                for k in _FIELDS:
                    cols[k].append(z[k])
        self.data = {k: np.concatenate(v) for k, v in cols.items()}
        self.size = len(self.data["obs"])

    def minibatches(self, batch_size: int, rng: np.random.Generator):
        idx = rng.permutation(self.size)
        for start in range(0, self.size - batch_size + 1, batch_size):
            sel = idx[start:start + batch_size]
            yield {k: v[sel] for k, v in self.data.items()}


def collect_dataset(env_name, path: str, *, num_steps: int,
                    policy=None, seed: int = 0) -> str:
    """Roll a behavior policy (default: uniform random) and persist the
    transitions — the offline-RL input fixture (reference:
    ``rllib/offline/json_writer.py`` usage in offline examples)."""
    env = make_env(env_name, seed=seed)
    rng = np.random.default_rng(seed)
    if policy is None:
        def policy(obs):
            return int(rng.integers(env.n_actions))
    writer = DatasetWriter(path)
    obs = env.reset()
    rows = {k: [] for k in _FIELDS}
    for _ in range(num_steps):
        action = policy(obs)
        next_obs, reward, done, _ = env.step(action)
        rows["obs"].append(obs)
        rows["actions"].append(action)
        rows["rewards"].append(reward)
        rows["next_obs"].append(next_obs)
        rows["dones"].append(float(done))
        obs = env.reset() if done else next_obs
    writer.write({k: np.asarray(v) for k, v in rows.items()})
    writer.close()
    return path


# ---------------------------------------------------------------------------
# Behavior cloning
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BCConfig:
    env: str = "CartPole-v1"      # only for obs/action space + evaluation
    input_path: str = ""
    lr: float = 1e-3
    train_batch_size: int = 256
    hidden: int = 64
    seed: int = 0

    def environment(self, env) -> "BCConfig":
        return replace(self, env=env)

    def offline_data(self, input_path: str) -> "BCConfig":
        return replace(self, input_path=input_path)

    def training(self, **kw) -> "BCConfig":
        return replace(self, **kw)

    def build(self) -> "BC":
        return BC(self)


class BC:
    """Behavior cloning: supervised log-likelihood on logged actions
    (reference: ``rllib/algorithms/bc``)."""

    def __init__(self, config: BCConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.ppo import forward_module, init_module

        self.config = config
        env = make_env(config.env, seed=config.seed)
        self._forward = forward_module
        self.params = init_module(
            jax.random.key(config.seed), env.obs_dim, env.n_actions,
            config.hidden)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.dataset = OfflineDataset(config.input_path)
        self.rng = np.random.default_rng(config.seed)
        self.iteration = 0

        def _update(params, opt_state, obs, actions):
            def loss_fn(p):
                logits, _ = forward_module(p, obs)
                logp = jax.nn.log_softmax(logits)
                taken = jnp.take_along_axis(
                    logp, actions[:, None].astype(jnp.int32), axis=1)[:, 0]
                return -taken.mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = jax.jit(_update)

    def train(self) -> dict:
        """One epoch over the dataset."""
        losses = []
        for batch in self.dataset.minibatches(
                self.config.train_batch_size, self.rng):
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, batch["obs"],
                batch["actions"])
            losses.append(float(loss))
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "loss": float(np.mean(losses)) if losses else float("nan"),
                "num_samples_trained": self.dataset.size}

    def compute_action(self, obs) -> int:
        import jax.numpy as jnp

        logits, _ = self._forward(self.params,
                                  jnp.asarray(obs, jnp.float32)[None])
        return int(np.argmax(np.asarray(logits)[0]))

    def evaluate(self, num_episodes: int = 10) -> dict:
        env = make_env(self.config.env, seed=self.config.seed + 999)
        returns = []
        for _ in range(num_episodes):
            obs, done, total = env.reset(), False, 0.0
            while not done:
                obs, r, done, _ = env.step(self.compute_action(obs))
                total += r
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns))}

    def stop(self):
        pass


# ---------------------------------------------------------------------------
# Conservative Q-learning (discrete)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CQLConfig:
    env: str = "CartPole-v1"
    input_path: str = ""
    lr: float = 1e-3
    gamma: float = 0.99
    train_batch_size: int = 256
    cql_alpha: float = 1.0        # weight of the conservative regularizer
    target_update_every: int = 8  # minibatches between target syncs
    hidden: int = 64
    seed: int = 0

    def environment(self, env) -> "CQLConfig":
        return replace(self, env=env)

    def offline_data(self, input_path: str) -> "CQLConfig":
        return replace(self, input_path=input_path)

    def training(self, **kw) -> "CQLConfig":
        return replace(self, **kw)

    def build(self) -> "CQL":
        return CQL(self)


class CQL:
    """Discrete CQL(H) (reference: ``rllib/algorithms/cql``): standard
    TD(0) target plus ``alpha * (logsumexp_a Q(s,a) - Q(s, a_data))`` —
    Q-values of actions the dataset never took are pushed down, so the
    greedy policy stays inside the data distribution."""

    def __init__(self, config: CQLConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.dqn import init_qnet, q_forward

        self.config = config
        env = make_env(config.env, seed=config.seed)
        self._q_forward = q_forward
        self.params = init_qnet(jax.random.key(config.seed), env.obs_dim,
                                env.n_actions, config.hidden)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.dataset = OfflineDataset(config.input_path)
        self.rng = np.random.default_rng(config.seed)
        self.iteration = 0
        self._updates = 0
        gamma, alpha = config.gamma, config.cql_alpha

        def _update(params, opt_state, target_params, batch):
            def loss_fn(p):
                q = q_forward(p, batch["obs"])            # [B, A]
                q_data = jnp.take_along_axis(
                    q, batch["actions"][:, None].astype(jnp.int32),
                    axis=1)[:, 0]
                q_next = q_forward(target_params, batch["next_obs"])
                target = batch["rewards"] + gamma * (
                    1.0 - batch["dones"]) * q_next.max(axis=1)
                td = jnp.mean(
                    (q_data - jax.lax.stop_gradient(target)) ** 2)
                conservative = jnp.mean(
                    jax.scipy.special.logsumexp(q, axis=1) - q_data)
                return td + alpha * conservative, (td, conservative)

            (loss, (td, cons)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td, cons

        self._update = jax.jit(_update)

    def train(self) -> dict:
        import jax

        losses, tds, conss = [], [], []
        for batch in self.dataset.minibatches(
                self.config.train_batch_size, self.rng):
            self.params, self.opt_state, loss, td, cons = self._update(
                self.params, self.opt_state, self.target_params, batch)
            losses.append(float(loss))
            tds.append(float(td))
            conss.append(float(cons))
            self._updates += 1
            if self._updates % self.config.target_update_every == 0:
                self.target_params = jax.tree.map(
                    lambda x: x, self.params)
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "loss": float(np.mean(losses)) if losses else float("nan"),
                "td_loss": float(np.mean(tds)) if tds else float("nan"),
                "cql_loss": float(np.mean(conss)) if conss else
                float("nan"),
                "num_samples_trained": self.dataset.size}

    def compute_action(self, obs) -> int:
        import jax.numpy as jnp

        q = self._q_forward(self.params,
                            jnp.asarray(obs, jnp.float32)[None])
        return int(np.argmax(np.asarray(q)[0]))

    def evaluate(self, num_episodes: int = 10) -> dict:
        env = make_env(self.config.env, seed=self.config.seed + 999)
        returns = []
        for _ in range(num_episodes):
            obs, done, total = env.reset(), False, 0.0
            while not done:
                obs, r, done, _ = env.step(self.compute_action(obs))
                total += r
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns))}

    def stop(self):
        pass


# re-exported field list for writers built outside collect_dataset
FIELDS = _FIELDS
