"""Gymnasium bridge: any ``gymnasium.Env`` plugs into the rollout/learner
stack (reference: ``rllib/env/env_runner.py`` consuming gym-API envs;
BASELINE config 5 names Atari/MuJoCo-class envs, which ship as gymnasium
environments).

The framework's internal env protocol is 4-tuple classic-gym style
(``reset() -> obs``, ``step(a) -> (obs, reward, done, info)``) with
``obs_dim``/``n_actions`` (discrete) or ``action_dim``/``action_low``/
``action_high``/``continuous`` attributes — this adapter derives those
from gymnasium spaces and folds ``terminated|truncated`` into ``done``.
"""

from __future__ import annotations

import numpy as np


def _flat_dim(space) -> int:
    import gymnasium.spaces as sp

    if isinstance(space, sp.Box):
        return int(np.prod(space.shape))
    if isinstance(space, sp.Discrete):
        return int(space.n)
    raise ValueError(f"unsupported observation space {space!r}")


class GymEnvAdapter:
    """Wrap a gymnasium env (instance or id) into the internal env API.

    Observations are flattened to float32 vectors; Discrete observations
    become one-hot. Discrete action spaces expose ``n_actions``; Box
    action spaces expose ``action_dim``/bounds with ``continuous=True``.
    """

    def __init__(self, env_or_id, seed: int | None = None, **make_kwargs):
        import gymnasium as gym
        import gymnasium.spaces as sp

        if isinstance(env_or_id, str):
            self.env = gym.make(env_or_id, **make_kwargs)
        else:
            self.env = env_or_id
        self._seed = seed
        self._needs_seed = True
        obs_space = self.env.observation_space
        act_space = self.env.action_space
        self._discrete_obs = isinstance(obs_space, sp.Discrete)
        self.obs_dim = _flat_dim(obs_space)
        if isinstance(act_space, sp.Discrete):
            self.continuous = False
            self.n_actions = int(act_space.n)
        elif isinstance(act_space, sp.Box):
            self.continuous = True
            self.action_dim = int(np.prod(act_space.shape))
            self.action_low = float(np.min(act_space.low))
            self.action_high = float(np.max(act_space.high))
            self._act_shape = act_space.shape
            self._act_dtype = act_space.dtype
        else:
            raise ValueError(f"unsupported action space {act_space!r}")

    def _obs(self, raw):
        if self._discrete_obs:
            onehot = np.zeros(self.obs_dim, dtype=np.float32)
            onehot[int(raw)] = 1.0
            return onehot
        return np.asarray(raw, dtype=np.float32).reshape(-1)

    def reset(self):
        # seed exactly once at first reset (gymnasium seeding protocol);
        # later resets continue the env's own rng stream
        if self._needs_seed and self._seed is not None:
            raw, _ = self.env.reset(seed=int(self._seed))
            self._needs_seed = False
        else:
            raw, _ = self.env.reset()
        return self._obs(raw)

    def step(self, action):
        if self.continuous:
            act = np.asarray(action, dtype=self._act_dtype).reshape(
                self._act_shape)
        else:
            act = int(np.asarray(action).reshape(-1)[0])
        raw, reward, terminated, truncated, info = self.env.step(act)
        # exposed for consumers that must distinguish time-limit
        # truncation from termination (value bootstrapping)
        self.truncated = bool(truncated and not terminated)
        return (self._obs(raw), float(reward),
                bool(terminated or truncated), info)

    def close(self):
        self.env.close()


def try_make_gym_env(name: str, seed=None):
    """Resolve an unknown env name through gymnasium (used as the
    fallback in ``make_env``); returns None when gymnasium is absent or
    doesn't know the id."""
    try:
        import gymnasium as gym
    except ImportError:
        return None
    try:
        gym.spec(name)
    except Exception:  # noqa: BLE001 - unknown id
        return None
    return GymEnvAdapter(name, seed=seed)
