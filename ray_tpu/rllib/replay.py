"""Replay-buffer components: prioritized replay and n-step returns.

Reference analogs: ``rllib/utils/replay_buffers/prioritized_replay_buffer.py``
(proportional prioritization on a segment tree, importance-sampling
weights with beta annealing — Schaul et al. 2015) and the n-step
return folding RLlib applies before insertion (``n_step`` in DQN-family
configs). Host-side numpy, like the reference keeps replay on CPU: it
is bandwidth-light bookkeeping feeding the jitted TD update.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.dqn import ReplayBuffer


class SumTree:
    """Flat-array binary segment tree over ``capacity`` priorities.

    ``prefix_search(masses)`` is vectorized: all queries descend the
    tree together, one level per iteration (O(batch * log n))."""

    def __init__(self, capacity: int):
        self.capacity = 1
        while self.capacity < capacity:
            self.capacity *= 2
        self.tree = np.zeros(2 * self.capacity, np.float64)

    def set(self, idx: np.ndarray, priority: np.ndarray):
        """Set leaf priorities and repair the path to the root."""
        idx = np.asarray(idx, np.int64)
        if idx.size == 0:
            return
        pos = idx + self.capacity
        self.tree[pos] = priority
        pos //= 2
        while pos[0] >= 1:
            # recompute parents from children (dedup keeps it correct
            # when two updated leaves share a parent)
            pos = np.unique(pos)
            self.tree[pos] = self.tree[2 * pos] + self.tree[2 * pos + 1]
            pos //= 2
            if pos[0] == 0:
                break

    @property
    def total(self) -> float:
        return float(self.tree[1])

    def prefix_search(self, masses: np.ndarray) -> np.ndarray:
        """For each mass m in [0, total), find the leaf where the
        running prefix sum crosses m."""
        idx = np.ones(len(masses), np.int64)
        m = np.asarray(masses, np.float64).copy()
        while idx[0] < self.capacity:
            left = self.tree[2 * idx]
            go_right = m >= left
            m = np.where(go_right, m - left, m)
            idx = 2 * idx + go_right
        return idx - self.capacity


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay: P(i) ∝ priority_i^alpha, with
    IS weights w_i = (N * P(i))^-beta normalized by max w. Storage and
    the ring-insert live in the uniform ``ReplayBuffer``; this subclass
    adds only the sum-tree priority bookkeeping."""

    def __init__(self, capacity: int, obs_dim: int, *,
                 alpha: float = 0.6, action_shape: tuple = (),
                 action_dtype=np.int32, eps: float = 1e-6,
                 gamma: float = 0.99):
        super().__init__(capacity, obs_dim, action_shape=action_shape,
                         action_dtype=action_dtype, gamma=gamma)
        self.alpha = alpha
        self.eps = eps
        self._tree = SumTree(capacity)
        self._max_priority = 1.0

    def add_batch(self, batch: dict):
        pos_before = self.pos
        super().add_batch(batch)
        n = min(len(batch["obs"]), self.capacity)
        idx = (pos_before + np.arange(n)) % self.capacity
        # new samples enter at max priority so everything is seen once
        self._tree.set(idx, np.full(n, self._max_priority ** self.alpha))

    def sample(self, batch_size: int, rng, *, beta: float = 0.4) -> dict:
        total = self._tree.total
        # stratified masses: one uniform draw per equal segment
        bounds = np.linspace(0.0, total, batch_size + 1)
        masses = rng.uniform(bounds[:-1], bounds[1:])
        idx = self._tree.prefix_search(masses)
        idx = np.minimum(idx, self.size - 1)
        prios = self._tree.tree[idx + self._tree.capacity]
        probs = prios / max(total, 1e-12)
        weights = (self.size * probs + 1e-12) ** -beta
        weights = (weights / weights.max()).astype(np.float32)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx],
                "rewards": self.rewards[idx], "dones": self.dones[idx],
                "weights": weights, "idx": idx,
                "discounts": self.discounts[idx]}

    def update_priorities(self, idx: np.ndarray, td_errors: np.ndarray):
        priority = (np.abs(td_errors) + self.eps) ** self.alpha
        self._tree.set(np.asarray(idx), priority)
        self._max_priority = max(self._max_priority,
                                 float(np.abs(td_errors).max()) + self.eps)


def nstep_batch(batch: dict, n_step: int, gamma: float) -> dict:
    """Fold a TIME-ORDERED transition batch into n-step transitions:
    reward_t <- sum_{i<h} gamma^i r_{t+i}, next_obs_t <- obs after the
    horizon, done_t <- any done within it, and ``discounts_t`` <- the
    BOOTSTRAP factor gamma^h (0 when the horizon hit a terminal), so the
    TD target is simply ``reward + discounts * Q(next_obs)`` even where
    the horizon h was clipped short. Clipping happens at episode ends
    and at the fragment boundary (same as the reference applies at
    episode ends). Works for n_step=1 too (discounts = gamma*(1-done))."""
    t = len(batch["obs"])
    if n_step <= 1:
        out = dict(batch)
        out["discounts"] = (gamma * (1.0 - batch["dones"])
                            ).astype(np.float32)
        return out
    rewards = np.zeros(t, np.float32)
    next_obs = np.empty_like(batch["next_obs"])
    dones = np.zeros(t, np.float32)
    discounts = np.zeros(t, np.float32)
    for i in range(t):
        acc, discount = 0.0, 1.0
        j = i
        while True:
            acc += discount * batch["rewards"][j]
            last = j
            if batch["dones"][j] or j == t - 1 or j - i + 1 >= n_step:
                break
            discount *= gamma
            j += 1
        h = last - i + 1
        rewards[i] = acc
        next_obs[i] = batch["next_obs"][last]
        terminal = batch["dones"][i:last + 1].max()
        dones[i] = terminal
        discounts[i] = 0.0 if terminal else gamma ** h
    out = dict(batch)
    out["rewards"] = rewards
    out["next_obs"] = next_obs
    out["dones"] = dones
    out["discounts"] = discounts
    return out
