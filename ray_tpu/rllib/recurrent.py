"""Recurrent (GRU/LSTM) policy cores + sequence-aware PPO.

Reference analog: ``rllib/models/torch/recurrent_net.py:25`` (LSTM
wrapper adding memory to any policy net, driven by ``max_seq_len``
fragments with stored initial state) and the sequence handling in
``rllib/policy/rnn_sequencing.py`` (pad_batch_to_sequences_of_same_size:
fragments carry their initial recurrent state; padding is masked out of
the loss).

TPU-first shape: the time axis is a ``lax.scan`` inside ONE jitted
update — [B, T] fragments, static shapes, the MXU sees the cell's fused
matmuls batched over B. Episode boundaries INSIDE a fragment reset the
carried state via a per-step done mask (no dynamic control flow).

Rollout workers run the cell step in numpy (envs are host-bound); each
collected fragment stores the state vector the worker carried at its
first step (``h0``) so the learner's scan replays exactly what the
behavior policy saw.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import _sample_actions, _softmax_rows


# ---------------------------------------------------------------------------
# Cells
# ---------------------------------------------------------------------------

def _dense(key, fan_in, fan_out):
    import jax

    scale = (2.0 / fan_in) ** 0.5
    return {"w": jax.random.normal(key, (fan_in, fan_out)) * scale,
            "b": jax.numpy.zeros((fan_out,))}


def init_recurrent_module(key, obs_dim: int, n_actions: int,
                          hidden: int = 64, cell: str = "gru") -> dict:
    """Encoder -> GRU/LSTM cell -> pi/vf heads. The cell's gate matmuls
    are fused into single [in+hidden, k*hidden] products (one MXU call
    per gate block per step)."""
    import jax

    if cell not in ("gru", "lstm"):
        raise ValueError(f"cell must be 'gru' or 'lstm', got {cell!r}")
    k_enc, k_cell, k_pi, k_vf = jax.random.split(key, 4)
    gates = 3 if cell == "gru" else 4
    return {
        "cell_type": cell,
        "enc": _dense(k_enc, obs_dim, hidden),
        # one fused weight for all gates: [enc+hidden, gates*hidden]
        "cell": _dense(k_cell, 2 * hidden, gates * hidden),
        "pi": _dense(k_pi, hidden, n_actions),
        "vf": _dense(k_vf, hidden, 1),
    }


def state_size(params) -> int:
    h = params["cell"]["w"].shape[0] // 2
    return 2 * h if params["cell_type"] == "lstm" else h


def zero_state(params, batch: int) -> np.ndarray:
    return np.zeros((batch, state_size(params)), np.float32)


def _cell_step(params, x, state, np_mod):
    """One recurrent step. ``x``: [B, H] encoded obs; ``state``: [B, S].
    Shared between jax (np_mod=jnp) and numpy (np_mod=np) callers —
    the rollout worker must replay bit-for-bit what the learner scans."""
    np_ = np_mod
    hidden = params["enc"]["w"].shape[1]
    if params["cell_type"] == "gru":
        h = state
        zin = np_.concatenate([x, h], axis=-1)
        g = zin @ params["cell"]["w"] + params["cell"]["b"]
        z = _sigmoid(g[:, :hidden], np_)
        r = _sigmoid(g[:, hidden:2 * hidden], np_)
        # candidate uses the RESET-gated hidden: recompute its block
        # with r*h (the fused matmul covers z/r; the candidate's hidden
        # half re-projects through the same weight slice)
        w_xc = params["cell"]["w"][:hidden, 2 * hidden:]
        w_hc = params["cell"]["w"][hidden:, 2 * hidden:]
        c = np_.tanh(x @ w_xc + (r * h) @ w_hc
                     + params["cell"]["b"][2 * hidden:])
        h_new = (1 - z) * h + z * c
        return h_new, h_new
    # lstm: state = [h | c]
    h, c = state[:, :hidden], state[:, hidden:]
    zin = np_.concatenate([x, h], axis=-1)
    g = zin @ params["cell"]["w"] + params["cell"]["b"]
    i = _sigmoid(g[:, :hidden], np_)
    f = _sigmoid(g[:, hidden:2 * hidden] + 1.0, np_)   # forget bias 1
    o = _sigmoid(g[:, 2 * hidden:3 * hidden], np_)
    cand = np_.tanh(g[:, 3 * hidden:])
    c_new = f * c + i * cand
    h_new = o * np_.tanh(c_new)
    return h_new, np_.concatenate([h_new, c_new], axis=-1)


def _sigmoid(x, np_):
    return 1.0 / (1.0 + np_.exp(-x))


def forward_recurrent_seq(params, obs_seq, h0, dones):
    """Jitted sequence forward: ``obs_seq`` [B, T, obs], ``h0`` [B, S],
    ``dones`` [B, T] (1.0 AFTER the step at t ended an episode — the
    carried state is zeroed before step t+1). Returns (logits [B,T,A],
    values [B,T], h_final [B,S]) via one ``lax.scan`` over T."""
    import jax
    import jax.numpy as jnp

    x = jnp.tanh(obs_seq @ params["enc"]["w"] + params["enc"]["b"])

    def step(state, xs):
        xt, done_prev = xs                      # [B, H], [B]
        state = state * (1.0 - done_prev)[:, None]
        h, state = _cell_step(params, xt, state, jnp)
        return state, h

    # done BEFORE each step: shift the per-step dones right by one
    done_prev = jnp.concatenate(
        [jnp.zeros_like(dones[:, :1]), dones[:, :-1]], axis=1)
    x_t = jnp.swapaxes(x, 0, 1)                 # [T, B, H]
    d_t = jnp.swapaxes(done_prev, 0, 1)         # [T, B]
    h_final, hs = jax.lax.scan(step, h0, (x_t, d_t))
    hs = jnp.swapaxes(hs, 0, 1)                 # [B, T, H]
    logits = hs @ params["pi"]["w"] + params["pi"]["b"]
    values = (hs @ params["vf"]["w"] + params["vf"]["b"]).squeeze(-1)
    return logits, values, h_final


def np_recurrent_step(params, obs, state):
    """Rollout-side single step (numpy): [B, obs] x [B, S] ->
    (logits [B, A], values [B], new_state [B, S])."""
    x = np.tanh(obs @ params["enc"]["w"] + params["enc"]["b"])
    h, state = _cell_step(params, x, state, np)
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    values = (h @ params["vf"]["w"] + params["vf"]["b"]).squeeze(-1)
    return logits, values, state


# ---------------------------------------------------------------------------
# Memory envs (POMDPs)
# ---------------------------------------------------------------------------

class MemoryCueEnv:
    """T-maze-style memory probe: step 0 shows a cue (+1/-1), steps
    1..delay show zeros, and on the LAST step the agent must pick the
    action matching the cue. A memoryless policy earns 0.5 on average;
    remembering the cue earns 1.0 — clean, fast signal for recurrent
    policies (reference: the LSTM-requiring debug envs,
    rllib/examples/env/stateless_cartpole.py class of tests)."""

    obs_dim = 2
    n_actions = 2

    def __init__(self, seed: int | None = None, delay: int = 3):
        self.rng = np.random.default_rng(seed)
        self.delay = delay
        self.t = 0
        self.cue = 1.0

    def reset(self):
        self.t = 0
        self.cue = float(self.rng.choice([-1.0, 1.0]))
        return np.array([self.cue, 0.0], np.float32)

    def step(self, action: int):
        self.t += 1
        last = self.t >= self.delay
        if last:
            reward = 1.0 if (self.cue > 0) == (int(action) == 1) else 0.0
            return np.zeros(2, np.float32), reward, True, {}
        # countdown channel so the step index is observable (the TASK
        # stays memoryful: the cue itself is long gone)
        return np.array([0.0, (self.delay - self.t) / self.delay],
                        np.float32), 0.0, False, {}


class StatelessCartPole:
    """CartPole with the velocity components masked out (reference:
    ``rllib/examples/env/stateless_cartpole.py``): position + angle
    only — balancing requires estimating velocities from history."""

    obs_dim = 2
    n_actions = 2

    def __init__(self, seed: int | None = None):
        from ray_tpu.rllib.env import CartPole

        self.env = CartPole(seed=seed)

    def reset(self):
        return self.env.reset()[[0, 2]]

    def step(self, action):
        obs, r, d, i = self.env.step(action)
        self.truncated = self.env.truncated
        return obs[[0, 2]], r, d, i


# ---------------------------------------------------------------------------
# Recurrent PPO
# ---------------------------------------------------------------------------

class _RecurrentRolloutWorker:
    """Collects FRAGMENTS of up to ``max_seq_len`` steps, each carrying
    the recurrent state at its first step (reference: rnn_sequencing's
    seq_lens + state_in batches). Fragments never cross episode ends;
    short fragments are zero-padded and masked."""

    def __init__(self, env_name, seed: int, max_seq_len: int):
        self.env = make_env(env_name, seed=seed)
        self.rng = np.random.default_rng(seed)
        self.max_seq_len = max_seq_len

    def sample(self, params_np: dict, num_steps: int, gamma: float,
               lam: float):
        from ray_tpu.rllib.ppo import _gae

        env = self.env
        T = self.max_seq_len
        frags = []     # dicts of [T, ...] padded columns
        episode_returns = []
        obs = env.reset()
        state = zero_state(params_np, 1)
        ep_ret = 0.0
        steps = 0
        while steps < num_steps:
            h0 = state[0].copy()
            cols = {k: [] for k in ("obs", "actions", "logp", "values",
                                    "rewards", "dones")}
            t = 0
            done = False
            while t < T and steps < num_steps:
                logits, value, state = np_recurrent_step(
                    params_np, obs[None], state)
                probs = _softmax_rows(logits)
                action = int(_sample_actions(self.rng, probs)[0])
                cols["obs"].append(obs.copy())
                cols["actions"].append(action)
                cols["logp"].append(
                    float(np.log(probs[0, action] + 1e-8)))
                cols["values"].append(float(value[0]))
                obs, r, done, _ = env.step(action)
                ep_ret += r
                cols["rewards"].append(float(r))
                cols["dones"].append(float(done))
                t += 1
                steps += 1
                if done:
                    episode_returns.append(ep_ret)
                    ep_ret = 0.0
                    obs = env.reset()
                    state = zero_state(params_np, 1)
                    break
            if done:
                last_v = 0.0
            else:
                _, v, _ = np_recurrent_step(params_np, obs[None], state)
                last_v = float(v[0])
            adv, ret = _gae(np.asarray(cols["rewards"]),
                            np.asarray(cols["values"]),
                            np.asarray(cols["dones"]), last_v,
                            gamma, lam)
            pad = T - t
            frag = {
                "obs": np.pad(np.asarray(cols["obs"], np.float32),
                              ((0, pad), (0, 0))),
                "actions": np.pad(
                    np.asarray(cols["actions"], np.int32), (0, pad)),
                "logp": np.pad(
                    np.asarray(cols["logp"], np.float32), (0, pad)),
                "advantages": np.pad(adv.astype(np.float32), (0, pad)),
                "returns": np.pad(ret.astype(np.float32), (0, pad)),
                "dones": np.pad(
                    np.asarray(cols["dones"], np.float32), (0, pad)),
                "mask": np.pad(np.ones(t, np.float32), (0, pad)),
                "h0": h0,
            }
            frags.append(frag)
        batch = {k: np.stack([f[k] for f in frags]) for k in frags[0]}
        batch["episode_returns"] = episode_returns
        return batch


@dataclass
class RecurrentPPOConfig:
    env: str = "CartPole-v1"
    cell: str = "gru"                  # "gru" | "lstm"
    max_seq_len: int = 16
    num_rollout_workers: int = 1
    rollout_fragment_length: int = 256
    lr: float = 3e-3
    gamma: float = 0.99
    lam: float = 0.95
    clip_eps: float = 0.2
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    num_sgd_iter: int = 4
    hidden: int = 32
    seed: int = 0

    def environment(self, env) -> "RecurrentPPOConfig":
        return replace(self, env=env)

    def rollouts(self, **kw) -> "RecurrentPPOConfig":
        return replace(self, **kw)

    def training(self, **kw) -> "RecurrentPPOConfig":
        return replace(self, **kw)

    def build(self) -> "RecurrentPPO":
        return RecurrentPPO(self)


class RecurrentPPO:
    """PPO over padded [B, T] fragments with per-fragment initial state
    (reference: the LSTM auto-wrapped PPO, recurrent_net.py:25). The
    whole update — scan forward, masked clipped surrogate, Adam — is
    one jit."""

    def __init__(self, config: RecurrentPPOConfig):
        import jax
        import optax

        self.config = config
        env = make_env(config.env, seed=config.seed)
        self.tx = optax.adam(config.lr)
        self.params = init_recurrent_module(
            jax.random.key(config.seed), env.obs_dim, env.n_actions,
            config.hidden, config.cell)
        self.opt_state = self.tx.init(
            {k: v for k, v in self.params.items() if k != "cell_type"})
        self._update = jax.jit(partial(
            _rppo_update, tx=self.tx, cell=config.cell,
            clip_eps=config.clip_eps,
            entropy_coeff=config.entropy_coeff,
            vf_coeff=config.vf_coeff))
        worker_cls = ray_tpu.remote(_RecurrentRolloutWorker)
        self.workers = [
            worker_cls.remote(config.env, config.seed + 1000 * (i + 1),
                              config.max_seq_len)
            for i in range(config.num_rollout_workers)
        ]
        self.iteration = 0

    def _params_np(self):
        import jax

        out = {k: (v if k == "cell_type" else jax.tree.map(np.asarray, v))
               for k, v in self.params.items()}
        return out

    def train(self) -> dict:
        cfg = self.config
        params_np = self._params_np()
        batches = ray_tpu.get([
            w.sample.remote(params_np, cfg.rollout_fragment_length,
                            cfg.gamma, cfg.lam)
            for w in self.workers
        ])
        episode_returns = [r for b in batches
                           for r in b["episode_returns"]]
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in ("obs", "actions", "logp", "advantages",
                           "returns", "dones", "mask", "h0")}
        # masked advantage normalization
        m = batch["mask"]
        adv = batch["advantages"]
        mean = (adv * m).sum() / m.sum()
        std = np.sqrt(((adv - mean) ** 2 * m).sum() / m.sum()) + 1e-8
        batch["advantages"] = (adv - mean) / std * m
        stats = None
        weights = {k: v for k, v in self.params.items()
                   if k != "cell_type"}
        for _ in range(cfg.num_sgd_iter):
            weights, self.opt_state, stats = self._update(
                weights, self.opt_state, batch)
        self.params = {**weights, "cell_type": cfg.cell}
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(episode_returns))
                                    if episode_returns else 0.0),
            "num_episodes": len(episode_returns),
            "policy_loss": float(stats["policy_loss"]),
            "entropy": float(stats["entropy"]),
            "num_env_steps_sampled": int(m.sum()),
        }

    def compute_action(self, obs, state=None):
        params_np = self._params_np()
        if state is None:
            state = zero_state(params_np, 1)
        logits, _, state = np_recurrent_step(
            params_np, np.asarray(obs, np.float32)[None], state)
        return int(np.argmax(logits[0])), state

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass


def _rppo_update(params, opt_state, batch, *, tx, cell, clip_eps,
                 entropy_coeff, vf_coeff):
    import jax
    import jax.numpy as jnp

    full = {**params, "cell_type": cell}

    def loss_fn(p):
        pf = {**p, "cell_type": cell}
        logits, values, _ = forward_recurrent_seq(
            pf, batch["obs"], batch["h0"], batch["dones"])
        m = batch["mask"]
        n = m.sum()
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1).squeeze(-1)
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        unclipped = ratio * adv
        clipped = jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv
        policy_loss = -(jnp.minimum(unclipped, clipped) * m).sum() / n
        vf_loss = (((values - batch["returns"]) ** 2) * m).sum() / n
        ent = -(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
                * m).sum() / n
        total = policy_loss + vf_coeff * vf_loss - entropy_coeff * ent
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": ent}

    del full
    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, stats
