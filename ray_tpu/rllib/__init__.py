"""ray_tpu.rllib: RL training subset (reference: RLlib, SURVEY P18)."""

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("rllib")


from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.bandit import (
    BanditLinTS,
    BanditLinTSConfig,
    BanditLinUCB,
    BanditLinUCBConfig,
)
from ray_tpu.rllib.connectors import (
    ClipActions,
    Connector,
    ConnectorEnv,
    ConnectorPipeline,
    FlattenObs,
    FrameStack,
    NormalizeObs,
    UnsquashActions,
)
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.dreamer import DreamerV3, DreamerV3Config
from ray_tpu.rllib.es import ARS, ARSConfig, ES, ESConfig
from ray_tpu.rllib.env import (
    BanditEnv,
    CartPole,
    ContinuousBandit,
    Pendulum,
    make_env,
)
from ray_tpu.rllib.gym_env import GymEnvAdapter
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.learner_group import LearnerGroup
from ray_tpu.rllib.recurrent import (
    MemoryCueEnv,
    RecurrentPPO,
    RecurrentPPOConfig,
    StatelessCartPole,
)
from ray_tpu.rllib.multi_agent import (
    MultiAgentCartPole,
    MultiAgentEnv,
    MultiAgentPPO,
    MultiAgentPPOConfig,
    MultiAgentReplay,
    PolicyMap,
)
from ray_tpu.rllib.estimators import (
    ImportanceSampling,
    WeightedImportanceSampling,
    episodes_from_dataset,
)
from ray_tpu.rllib.offline import (
    BC,
    BCConfig,
    CQL,
    CQLConfig,
    DatasetWriter,
    OfflineDataset,
    collect_dataset,
)
from ray_tpu.rllib.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.pg import A2C, A2CConfig, PG, PGConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.sac import SAC, SACConfig
from ray_tpu.rllib.td3 import DDPG, DDPGConfig, TD3, TD3Config

__all__ = ["A2C", "A2CConfig", "APPO", "APPOConfig", "ARS", "ARSConfig",
           "BC", "BCConfig", "BanditEnv", "BanditLinTS",
           "BanditLinTSConfig", "BanditLinUCB", "BanditLinUCBConfig",
           "CQL", "CQLConfig", "CartPole", "ContinuousBandit", "DQN",
           "DQNConfig", "DatasetWriter", "DreamerV3", "DreamerV3Config", "ES", "ESConfig",
           "GymEnvAdapter", "IMPALA", "IMPALAConfig", "LearnerGroup",
           "MARWIL",
           "MARWILConfig", "OfflineDataset", "PG", "PGConfig", "PPO",
           "PPOConfig", "Pendulum", "SAC", "SACConfig", "DDPG",
           "DDPGConfig", "TD3", "TD3Config", "collect_dataset",
           "make_env"]
