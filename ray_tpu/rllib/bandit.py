"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Reference analog: ``rllib/algorithms/bandit/`` (``BanditLinUCB``,
``BanditLinTS`` over ``DiscreteLinearModel``). Per-arm Bayesian linear
regression on the context: LinUCB picks the arm maximizing the upper
confidence bound ``theta_a @ x + alpha * sqrt(x' A_a^-1 x)``; LinTS
samples ``theta ~ N(mean, A^-1)`` per arm and exploits greedily.

These are exact closed-form updates (rank-1 Sherman–Morrison), no
gradient step — host numpy is the right tool, and the driver interacts
with the env directly (bandits are one-step, there is nothing to fan
out).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import numpy as np

from ray_tpu.rllib.env import make_env


@dataclass
class BanditLinUCBConfig:
    env: str = "Bandit-v0"
    alpha: float = 1.0              # exploration width
    lambda_reg: float = 1.0         # ridge prior precision
    steps_per_iteration: int = 100
    seed: int = 0

    def environment(self, env):
        return replace(self, env=env)

    def training(self, **kw):
        return replace(self, **kw)

    def build(self):
        return BanditLinUCB(self)


@dataclass
class BanditLinTSConfig(BanditLinUCBConfig):
    def build(self):
        return BanditLinTS(self)


class _LinearArmModel:
    """Ridge regression per arm with incrementally maintained inverse."""

    def __init__(self, dim: int, lambda_reg: float):
        self.a_inv = np.eye(dim) / lambda_reg
        self.b = np.zeros(dim)
        self.theta = np.zeros(dim)
        self.pulls = 0

    def update(self, x: np.ndarray, reward: float):
        # Sherman–Morrison rank-1 update of A^-1
        av = self.a_inv @ x
        self.a_inv -= np.outer(av, av) / (1.0 + x @ av)
        self.b += reward * x
        self.theta = self.a_inv @ self.b
        self.pulls += 1


class BanditLinUCB:
    def __init__(self, config):
        self.config = config
        self.env = make_env(config.env, seed=config.seed)
        self.rng = np.random.default_rng(config.seed)
        self.arms = [_LinearArmModel(self.env.obs_dim, config.lambda_reg)
                     for _ in range(self.env.n_actions)]
        self.iteration = 0
        self.total_steps = 0

    def _score(self, arm: _LinearArmModel, x: np.ndarray) -> float:
        ucb = np.sqrt(max(float(x @ arm.a_inv @ x), 0.0))
        return float(arm.theta @ x) + self.config.alpha * ucb

    def compute_action(self, obs) -> int:
        x = np.asarray(obs, dtype=np.float64)
        return int(np.argmax([self._score(a, x) for a in self.arms]))

    def train(self) -> dict:
        rewards = []
        obs = self.env.reset()
        for _ in range(self.config.steps_per_iteration):
            x = np.asarray(obs, dtype=np.float64)
            action = self.compute_action(x)
            obs, reward, done, _ = self.env.step(action)
            self.arms[action].update(x, float(reward))
            rewards.append(reward)
            if done:
                obs = self.env.reset()
        self.iteration += 1
        self.total_steps += len(rewards)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": float(np.mean(rewards)),
            "num_env_steps_sampled": self.total_steps,
            "arm_pulls": [a.pulls for a in self.arms],
        }

    def save(self, path: str):
        np.savez(path,
                 **{f"ainv{i}": a.a_inv for i, a in enumerate(self.arms)},
                 **{f"b{i}": a.b for i, a in enumerate(self.arms)})

    def restore(self, path: str):
        if not path.endswith(".npz"):
            path += ".npz"
        with np.load(path) as z:
            for i, a in enumerate(self.arms):
                a.a_inv = z[f"ainv{i}"]
                a.b = z[f"b{i}"]
                a.theta = a.a_inv @ a.b

    def stop(self):
        pass


class BanditLinTS(BanditLinUCB):
    """Thompson sampling: draw theta from the posterior, act greedily."""

    def _score(self, arm: _LinearArmModel, x: np.ndarray) -> float:
        cov = self.config.alpha ** 2 * arm.a_inv
        cov = 0.5 * (cov + cov.T)  # keep SM-updated inverse symmetric
        theta = self.rng.multivariate_normal(arm.theta, cov)
        return float(theta @ x)
