"""DreamerV3 (compact) on JAX: model-based RL via a recurrent
state-space world model (RSSM) + actor-critic trained on imagined
latent rollouts.

Reference analog: ``rllib/algorithms/dreamerv3/`` (world model with
categorical latents, KL balancing + free bits, symlog heads, imagination
horizon, REINFORCE actor with return-range normalization). TPU-first
shape: the WHOLE update — world-model loss over a [B, T] sequence batch,
posterior rollforward, H-step imagination, critic lambda-returns, actor
REINFORCE — is ONE jitted function built from three lax.scans; rollout
workers keep a numpy mirror of the filtering policy (encoder + GRU +
posterior + actor) so env stepping never touches jax.

Kept compact relative to the reference implementation (vector
observations, discrete actions, symlog-MSE reward/value heads instead of
twohot): the structural pieces — categorical latents with
straight-through gradients, KL balancing with free bits, continue head,
EMA target critic, percentile return normalization — are all here.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


# ---------------------------------------------------------------------------
# small pure-functional nets (shared MLP helpers come from sac.py, the
# convention td3.py already follows)
# ---------------------------------------------------------------------------

from ray_tpu.rllib.sac import _init_mlp, _mlp  # noqa: E402


def _init_gru(key, x_dim, h_dim):
    import jax

    k = jax.random.split(key, 2)
    return {
        "wx": jax.random.normal(k[0], (x_dim, 3 * h_dim)) * (x_dim ** -0.5),
        "wh": jax.random.normal(k[1], (h_dim, 3 * h_dim)) * (h_dim ** -0.5),
        "b": np.zeros((3 * h_dim,), np.float32),
    }


def _gru(p, x, h):
    import jax
    import jax.numpy as jnp

    gx = x @ p["wx"] + p["b"]
    gh = h @ p["wh"]
    zx, rx, cx = jnp.split(gx, 3, axis=-1)
    zh, rh, ch = jnp.split(gh, 3, axis=-1)
    z = jax.nn.sigmoid(zx + zh)
    r = jax.nn.sigmoid(rx + rh)
    cand = jnp.tanh(cx + r * ch)
    return (1.0 - z) * h + z * cand


def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


# numpy mirrors used by the rollout policy and greedy evaluation
def _np_mlp(p, x):
    for i, layer in enumerate(p):
        x = x @ layer["w"] + layer["b"]
        if i < len(p) - 1:
            x = np.tanh(x)
    return x


def _np_gru(p, x, h):
    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    gx = x @ p["wx"] + p["b"]
    gh = h @ p["wh"]
    zx, rx, cx = np.split(gx, 3)
    zh, rh, ch = np.split(gh, 3)
    z = sig(zx + zh)
    r = sig(rx + rh)
    return (1.0 - z) * h + z * np.tanh(cx + r * ch)


def _np_softmax(lg):
    e = np.exp(lg - lg.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def _np_symlog(x):
    return np.sign(x) * np.log1p(np.abs(x))


# ---------------------------------------------------------------------------
# RSSM core
# ---------------------------------------------------------------------------

def init_dreamer(key, obs_dim: int, n_actions: int, *, embed: int,
                 h_dim: int, n_cats: int, n_classes: int, hidden: int):
    import jax

    z_dim = n_cats * n_classes
    f_dim = h_dim + z_dim
    ks = jax.random.split(key, 9)
    return {
        "wm": {
            "encoder": _init_mlp(ks[0], (obs_dim, hidden, embed)),
            "gru": _init_gru(ks[1], z_dim + n_actions, h_dim),
            "prior": _init_mlp(ks[2], (h_dim, hidden, z_dim)),
            "post": _init_mlp(ks[3], (h_dim + embed, hidden, z_dim)),
            "decoder": _init_mlp(ks[4], (f_dim, hidden, obs_dim)),
            "reward": _init_mlp(ks[5], (f_dim, hidden, 1)),
            "cont": _init_mlp(ks[6], (f_dim, hidden, 1)),
        },
        "actor": _init_mlp(ks[7], (f_dim, hidden, n_actions)),
        "critic": _init_mlp(ks[8], (f_dim, hidden, 1)),
    }


def _sample_onehot(logits, key, n_cats, n_classes, *, unimix=0.01):
    """Sample a categorical latent (one one-hot per category) with the
    1% uniform mixture and straight-through gradients (DreamerV3)."""
    import jax
    import jax.numpy as jnp

    lg = logits.reshape(*logits.shape[:-1], n_cats, n_classes)
    probs = jax.nn.softmax(lg, axis=-1)
    probs = (1.0 - unimix) * probs + unimix / n_classes
    idx = jax.random.categorical(key, jnp.log(probs), axis=-1)
    onehot = jax.nn.one_hot(idx, n_classes)
    st = onehot + probs - jax.lax.stop_gradient(probs)   # straight-through
    return st.reshape(*logits.shape[:-1], n_cats * n_classes)


def _kl_cats(lhs_logits, rhs_logits, n_cats, n_classes):
    """KL(lhs || rhs) between factorized categoricals, summed over cats."""
    import jax
    import jax.numpy as jnp

    a = lhs_logits.reshape(*lhs_logits.shape[:-1], n_cats, n_classes)
    b = rhs_logits.reshape(*rhs_logits.shape[:-1], n_cats, n_classes)
    pa = jax.nn.softmax(a, axis=-1)
    return jnp.sum(pa * (jax.nn.log_softmax(a, axis=-1)
                         - jax.nn.log_softmax(b, axis=-1)), axis=(-2, -1))


# ---------------------------------------------------------------------------
# the one jitted update
# ---------------------------------------------------------------------------

def _dreamer_update(params, target_critic, opt_wm, opt_actor, opt_critic,
                    ret_scale, batch, key, *, cfg_s, tx_wm, tx_actor,
                    tx_critic):
    """World model + imagination actor-critic in one program.

    batch rows are ARRIVAL-ALIGNED (the reference DreamerV3 layout):
    actions[t] is the action taken at t-1 that produced obs[t] (zero on
    episode starts), rewards[t] arrived WITH obs[t], cont[t] is 0 iff
    obs[t] is terminal. feat_t's GRU therefore encodes actions[t], which
    is what makes the reward/continue heads' targets learnable for
    action-dependent rewards. cfg_s is the static size/coef tuple."""
    import jax
    import jax.numpy as jnp
    import optax

    (n_actions, n_cats, n_classes, h_dim, horizon, gamma, lam,
     entropy_coef, free_nats, kl_dyn, kl_rep, tau) = cfg_s
    z_dim = n_cats * n_classes
    obs = symlog(batch["obs"])
    acts = jax.nn.one_hot(batch["actions"], n_actions)
    b, t = acts.shape[:2]
    k_wm, k_img = jax.random.split(key)

    # -- world model loss over the sequence (posterior filtering scan) --
    def wm_loss(wm):
        embed = _mlp(wm["encoder"], obs)                       # [B,T,E]

        def step(carry, xs):
            h, z, k = carry
            e_t, a_prev, first = xs
            k, ks = jax.random.split(k)
            # is_first: reset recurrent state AND the previous action
            keep = (1.0 - first)[:, None]
            h = h * keep
            z = z * keep
            a_prev = a_prev * keep
            h = _gru(wm["gru"], jnp.concatenate([z, a_prev], -1), h)
            prior_lg = _mlp(wm["prior"], h)
            post_lg = _mlp(wm["post"], jnp.concatenate([h, e_t], -1))
            z = _sample_onehot(post_lg, ks, n_cats, n_classes)
            return (h, z, k), (h, z, prior_lg, post_lg)

        h0 = jnp.zeros((b, h_dim))
        z0 = jnp.zeros((b, z_dim))
        # actions[t] already IS the action arriving at t (see docstring)
        (_, _, _), (hs, zs, prior_lg, post_lg) = jax.lax.scan(
            step, (h0, z0, k_wm),
            (embed.transpose(1, 0, 2), acts.transpose(1, 0, 2),
             batch["is_first"].T))
        hs = hs.transpose(1, 0, 2)                              # [B,T,H]
        zs = zs.transpose(1, 0, 2)
        prior_lg = prior_lg.transpose(1, 0, 2)
        post_lg = post_lg.transpose(1, 0, 2)
        feat = jnp.concatenate([hs, zs], -1)                    # [B,T,F]

        recon = _mlp(wm["decoder"], feat)
        rew_pred = _mlp(wm["reward"], feat)[..., 0]
        cont_pred = _mlp(wm["cont"], feat)[..., 0]              # logits
        recon_loss = jnp.mean(jnp.sum((recon - obs) ** 2, -1))
        # fresh-reset rows have no arriving transition: mask their
        # reward/continue targets (their stored values are placeholders)
        m = 1.0 - batch["is_first"]
        denom = jnp.maximum(jnp.sum(m), 1.0)
        rew_loss = jnp.sum(
            m * (rew_pred - symlog(batch["rewards"])) ** 2) / denom
        cont_loss = jnp.sum(m * optax.sigmoid_binary_cross_entropy(
            cont_pred, batch["cont"])) / denom
        # KL balancing with free bits (reference: dyn 0.5 / rep 0.1)
        kl_d = _kl_cats(jax.lax.stop_gradient(post_lg), prior_lg,
                        n_cats, n_classes)
        kl_r = _kl_cats(post_lg, jax.lax.stop_gradient(prior_lg),
                        n_cats, n_classes)
        kl_loss = (kl_dyn * jnp.mean(jnp.maximum(kl_d, free_nats))
                   + kl_rep * jnp.mean(jnp.maximum(kl_r, free_nats)))
        total = recon_loss + rew_loss + cont_loss + kl_loss
        aux = {"recon_loss": recon_loss, "reward_loss": rew_loss,
               "cont_loss": cont_loss, "kl_loss": kl_loss,
               "feat": feat, "hs": hs, "zs": zs}
        return total, aux

    (wm_total, wm_aux), wm_grads = jax.value_and_grad(
        wm_loss, has_aux=True)(params["wm"])
    upd, opt_wm = tx_wm.update(wm_grads, opt_wm, params["wm"])
    wm_new = optax.apply_updates(params["wm"], upd)

    # -- imagination from every posterior state (updated world model) --
    wm_sg = jax.lax.stop_gradient(wm_new)
    n = b * t
    h = jax.lax.stop_gradient(wm_aux["hs"]).reshape(n, h_dim)
    z = jax.lax.stop_gradient(wm_aux["zs"]).reshape(n, z_dim)

    def imagine(actor):
        def step(carry, k):
            h, z = carry
            f = jnp.concatenate([h, z], -1)
            lg = _mlp(actor, f)
            ka, kz = jax.random.split(k)
            a = jax.random.categorical(ka, lg, axis=-1)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(lg), a[:, None], 1)[:, 0]
            ent = -jnp.sum(jax.nn.softmax(lg)
                           * jax.nn.log_softmax(lg), -1)
            a1 = jax.nn.one_hot(a, n_actions)
            h = _gru(wm_sg["gru"], jnp.concatenate([z, a1], -1), h)
            z = _sample_onehot(_mlp(wm_sg["prior"], h), kz,
                               n_cats, n_classes)
            return (h, z), (f, logp, ent)

        keys = jax.random.split(k_img, horizon)
        (hl, zl), (feats, logps, ents) = jax.lax.scan(step, (h, z), keys)
        f_last = jnp.concatenate([hl, zl], -1)
        return feats, logps, ents, f_last                     # [H,N,...]

    def actor_loss(actor):
        feats, logps, ents, f_last = imagine(actor)
        feats_sg = jax.lax.stop_gradient(feats)
        f_last_sg = jax.lax.stop_gradient(f_last)
        # arrival-aligned heads: the reward/continue of taking a_k at
        # f_k are predicted from the POST-transition features f_{k+1}
        # (whose GRU encodes a_k) — matching the world-model targets
        feats_next = jnp.concatenate([feats_sg[1:], f_last_sg[None]], 0)
        rewards = symexp(_mlp(wm_sg["reward"], feats_next)[..., 0])
        conts = jax.nn.sigmoid(_mlp(wm_sg["cont"], feats_next)[..., 0])
        disc = gamma * conts                                   # [H,N]

        # lambda-returns bootstrapped with the EMA target critic
        vs = symexp(_mlp(target_critic, feats_sg)[..., 0])     # [H,N]
        v_last = symexp(_mlp(target_critic, f_last_sg)[..., 0])
        v_next = jnp.concatenate([vs[1:], v_last[None]], 0)

        def ret_step(nxt, xs):
            r, d, v = xs
            ret = r + d * ((1.0 - lam) * v + lam * nxt)
            return ret, ret

        _, returns = jax.lax.scan(
            ret_step, v_last,
            (rewards[::-1], disc[::-1], v_next[::-1]))
        returns = returns[::-1]                                 # [H,N]

        # percentile return normalization (EMA of the 5-95 range)
        rng95 = jnp.percentile(returns, 95) - jnp.percentile(returns, 5)
        scale_new = 0.99 * ret_scale + 0.01 * jnp.maximum(rng95, 1.0)
        adv = jax.lax.stop_gradient((returns - vs) / scale_new)
        loss = -jnp.mean(adv * logps) - entropy_coef * jnp.mean(ents)
        # ONE imagination pass serves everything: gradients flow only
        # through logps/ents; returns/features come out as aux for the
        # critic update
        return loss, (feats_sg, returns, scale_new, jnp.mean(ents))

    (a_loss, (feats_sg, returns, ret_scale, ent_mean)), a_grads = \
        jax.value_and_grad(actor_loss, has_aux=True)(params["actor"])
    upd, opt_actor = tx_actor.update(a_grads, opt_actor, params["actor"])
    actor_new = optax.apply_updates(params["actor"], upd)

    def critic_loss(critic):
        v_pred = _mlp(critic, feats_sg)[..., 0]
        return jnp.mean((v_pred
                         - jax.lax.stop_gradient(symlog(returns))) ** 2)

    c_loss, c_grads = jax.value_and_grad(critic_loss)(params["critic"])
    upd, opt_critic = tx_critic.update(c_grads, opt_critic,
                                       params["critic"])
    critic_new = optax.apply_updates(params["critic"], upd)
    target_critic = jax.tree.map(lambda tgt, o: (1 - tau) * tgt + tau * o,
                                 target_critic, critic_new)

    params = {"wm": wm_new, "actor": actor_new, "critic": critic_new}
    metrics = {
        "wm_loss": wm_total,
        "recon_loss": wm_aux["recon_loss"],
        "reward_loss": wm_aux["reward_loss"],
        "cont_loss": wm_aux["cont_loss"],
        "kl_loss": wm_aux["kl_loss"],
        "actor_loss": a_loss,
        "critic_loss": c_loss,
        "imag_return_mean": jnp.mean(returns),
        "policy_entropy": ent_mean,
    }
    return (params, target_critic, opt_wm, opt_actor, opt_critic,
            ret_scale, metrics)


# ---------------------------------------------------------------------------
# sequence replay: one flat ring of steps, windows sampled anywhere —
# is_first flags let the posterior scan reset across episode joints
# ---------------------------------------------------------------------------

class SequenceReplay:
    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity,), np.int32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.is_first = np.zeros((capacity,), np.float32)
        self.cont = np.ones((capacity,), np.float32)
        self.pos = 0
        self.size = 0
        self._last_writer: int | None = None

    def add_batch(self, frag: dict, writer: int = 0):
        n = len(frag["actions"])
        idx = (self.pos + np.arange(n)) % self.capacity
        self.obs[idx] = frag["obs"]
        self.actions[idx] = frag["actions"]
        self.rewards[idx] = frag["rewards"]
        self.is_first[idx] = frag["is_first"]
        self.cont[idx] = frag["cont"]
        # fragments from DIFFERENT workers interleave in the ring: a
        # sampled window crossing such a joint would stitch unrelated
        # trajectories, so the joint is forced to a sequence start (a
        # same-worker fragment continues its predecessor and keeps
        # cross-fragment state)
        if writer != self._last_writer:
            self.is_first[idx[0]] = 1.0
            self._last_writer = writer
        self.pos = int((self.pos + n) % self.capacity)
        self.size = min(self.size + n, self.capacity)
        # the ring write head truncates whatever sequence it lands in:
        # mark the NEXT slot a sequence start so a sampled window never
        # stitches new steps onto stale ones
        if self.size == self.capacity:
            self.is_first[self.pos] = 1.0

    def sample(self, batch_size: int, seq_len: int, rng) -> dict:
        if self.size == self.capacity:
            # full ring: sample starts over the WHOLE ring modulo
            # capacity — windows spanning the capacity-1 -> 0 boundary
            # are temporally contiguous (the write head marks is_first
            # where continuity actually breaks), and excluding them
            # permanently under-samples the steps just after index 0
            starts = rng.integers(0, self.size, size=batch_size)
            idx = (starts[:, None] + np.arange(seq_len)[None, :]) \
                % self.capacity
        else:
            starts = rng.integers(0, self.size - seq_len + 1,
                                  size=batch_size)
            idx = starts[:, None] + np.arange(seq_len)[None, :]
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "is_first": self.is_first[idx],
            "cont": self.cont[idx],
        }


# ---------------------------------------------------------------------------
# rollout worker: numpy mirror of the filtering policy
# ---------------------------------------------------------------------------

class _DreamerRolloutWorker:
    def __init__(self, env_name: str, seed: int, sizes: tuple):
        self.env = make_env(env_name, seed=seed)
        self.rng = np.random.default_rng(seed)
        (self.n_actions, self.n_cats, self.n_classes,
         self.h_dim) = sizes
        self.obs = self.env.reset()
        self.first = True
        self.h = np.zeros((self.h_dim,), np.float32)
        self.z = np.zeros((self.n_cats * self.n_classes,), np.float32)
        self.prev_action = 0
        self.prev_reward = 0.0
        self.ep_ret = 0.0

    def sample(self, wm_np, actor_np, num_steps: int) -> dict:
        """Collect ``num_steps`` env steps as ARRIVAL-ALIGNED rows:
        row t = (obs_t, the action that produced obs_t, the reward that
        arrived with obs_t, is_first, cont). Episode ends additionally
        emit the terminal observation's row (cont=0 on termination, 1 on
        time-limit truncation), so terminal rewards are trainable."""
        obs_l, act_l, rew_l, first_l, cont_l = [], [], [], [], []
        episode_returns = []
        for _ in range(num_steps):
            if self.first:
                self.h[:] = 0.0
                self.z[:] = 0.0
                self.prev_action = 0
                self.prev_reward = 0.0
            # the row for the CURRENT (non-terminal) observation
            obs_l.append(self.obs)
            act_l.append(self.prev_action)
            rew_l.append(self.prev_reward)
            first_l.append(float(self.first))
            cont_l.append(1.0)
            # filtering policy: posterior over (h advanced by the
            # arriving action, current obs embedding). Episode-first
            # steps feed a ZERO action vector — matching the training
            # scan's `a_prev * keep` reset (a one-hot for action 0
            # would alias action 0 with episode starts)
            a_prev = np.zeros((self.n_actions,), np.float32)
            if not self.first:
                a_prev[self.prev_action] = 1.0
            obs_sym = _np_symlog(self.obs)
            e = _np_mlp(wm_np["encoder"], obs_sym.astype(np.float32))
            self.h = _np_gru(wm_np["gru"],
                             np.concatenate([self.z, a_prev]), self.h)
            post = _np_mlp(wm_np["post"], np.concatenate([self.h, e]))
            probs = _np_softmax(
                post.reshape(self.n_cats, self.n_classes))
            probs = 0.99 * probs + 0.01 / self.n_classes
            z = np.zeros_like(probs)
            for c in range(self.n_cats):
                z[c, self.rng.choice(self.n_classes, p=probs[c])] = 1.0
            self.z = z.reshape(-1).astype(np.float32)
            lg = _np_mlp(actor_np, np.concatenate([self.h, self.z]))
            a = int(self.rng.choice(self.n_actions, p=_np_softmax(lg)))
            next_obs, reward, done, _ = self.env.step(a)
            self.ep_ret += reward
            self.first = False
            if done:
                # terminal observation's row carries the final reward.
                # ``truncated`` is part of the env protocol (env.py sets
                # it on every builtin env): a time-limit end must train
                # the continue head as cont=1 (bootstrappable), not as a
                # true termination. Envs lacking the attribute get one
                # warning — silently treating their truncations as
                # terminations biases value bootstrapping.
                if not hasattr(self.env, "truncated") and \
                        not getattr(self, "_warned_truncated", False):
                    self._warned_truncated = True
                    import warnings

                    warnings.warn(
                        f"{type(self.env).__name__} does not expose "
                        f"'truncated'; episode ends will all be treated "
                        f"as true terminations (cont=0), which biases "
                        f"DreamerV3's continue head on time-limit envs",
                        stacklevel=2)
                terminal = not bool(getattr(self.env, "truncated",
                                            False))
                obs_l.append(next_obs)
                act_l.append(a)
                rew_l.append(reward)
                first_l.append(0.0)
                cont_l.append(0.0 if terminal else 1.0)
                episode_returns.append(self.ep_ret)
                self.ep_ret = 0.0
                self.obs = self.env.reset()
                self.first = True
            else:
                self.prev_action = a
                self.prev_reward = reward
                self.obs = next_obs
        return {"obs": np.asarray(obs_l, np.float32),
                "actions": np.asarray(act_l, np.int32),
                "rewards": np.asarray(rew_l, np.float32),
                "is_first": np.asarray(first_l, np.float32),
                "cont": np.asarray(cont_l, np.float32),
                "episode_returns": episode_returns}


# ---------------------------------------------------------------------------
# config + algorithm
# ---------------------------------------------------------------------------

@dataclass
class DreamerV3Config:
    env: str = "CartPole-v1"
    num_rollout_workers: int = 1
    rollout_fragment_length: int = 128
    seq_len: int = 16
    batch_size: int = 8
    horizon: int = 8
    lr_wm: float = 1e-3
    lr_actor: float = 3e-4
    lr_critic: float = 3e-4
    gamma: float = 0.997
    lam: float = 0.95
    entropy_coef: float = 3e-3
    free_nats: float = 1.0
    kl_dyn: float = 0.5
    kl_rep: float = 0.1
    tau: float = 0.02               # EMA target-critic rate
    embed: int = 64
    h_dim: int = 128
    n_cats: int = 8
    n_classes: int = 8
    hidden: int = 128
    buffer_capacity: int = 50_000
    learning_starts: int = 256
    num_updates_per_iter: int = 4
    seed: int = 0

    def environment(self, env) -> "DreamerV3Config":
        return replace(self, env=env)

    def rollouts(self, **kw) -> "DreamerV3Config":
        return replace(self, **kw)

    def training(self, **kw) -> "DreamerV3Config":
        return replace(self, **kw)

    def build(self) -> "DreamerV3":
        return DreamerV3(self)


class DreamerV3:
    def __init__(self, config: DreamerV3Config):
        import jax
        import optax

        self.config = config
        env = make_env(config.env, seed=config.seed)
        if getattr(env, "continuous", False):
            raise ValueError("this DreamerV3 build is discrete-action "
                             f"only; got continuous env {config.env!r}")
        self.obs_dim = env.obs_dim
        self.n_actions = env.n_actions
        c = config
        self.params = init_dreamer(
            jax.random.key(c.seed), self.obs_dim, self.n_actions,
            embed=c.embed, h_dim=c.h_dim, n_cats=c.n_cats,
            n_classes=c.n_classes, hidden=c.hidden)
        self.target_critic = jax.tree.map(lambda x: x,
                                          self.params["critic"])
        self.tx_wm = optax.chain(optax.clip_by_global_norm(100.0),
                                 optax.adam(c.lr_wm))
        self.tx_actor = optax.adam(c.lr_actor)
        self.tx_critic = optax.adam(c.lr_critic)
        self.opt_wm = self.tx_wm.init(self.params["wm"])
        self.opt_actor = self.tx_actor.init(self.params["actor"])
        self.opt_critic = self.tx_critic.init(self.params["critic"])
        self.ret_scale = np.float32(1.0)
        self.buffer = SequenceReplay(c.buffer_capacity, self.obs_dim)
        self.rng = np.random.default_rng(c.seed)
        self.key = jax.random.key(c.seed + 1)
        self.iteration = 0
        cfg_s = (self.n_actions, c.n_cats, c.n_classes, c.h_dim,
                 c.horizon, c.gamma, c.lam, c.entropy_coef, c.free_nats,
                 c.kl_dyn, c.kl_rep, c.tau)
        self._update = jax.jit(partial(
            _dreamer_update, cfg_s=cfg_s, tx_wm=self.tx_wm,
            tx_actor=self.tx_actor, tx_critic=self.tx_critic))
        sizes = (self.n_actions, c.n_cats, c.n_classes, c.h_dim)
        worker_cls = ray_tpu.remote(_DreamerRolloutWorker)
        self.workers = [
            worker_cls.remote(c.env, c.seed + 1000 * (i + 1), sizes)
            for i in range(c.num_rollout_workers)
        ]

    def _policy_np(self):
        import jax

        wm = self.params["wm"]
        wm_np = {
            "encoder": jax.tree.map(np.asarray, wm["encoder"]),
            "gru": jax.tree.map(np.asarray, wm["gru"]),
            "post": jax.tree.map(np.asarray, wm["post"]),
        }
        return wm_np, jax.tree.map(np.asarray, self.params["actor"])

    def train(self) -> dict:
        import jax

        cfg = self.config
        wm_np, actor_np = self._policy_np()
        frags = ray_tpu.get([
            w.sample.remote(wm_np, actor_np, cfg.rollout_fragment_length)
            for w in self.workers
        ])
        episode_returns = []
        for i, f in enumerate(frags):
            episode_returns.extend(f.pop("episode_returns"))
            self.buffer.add_batch(f, writer=i)

        metrics = {}
        if self.buffer.size >= max(cfg.learning_starts,
                                   cfg.seq_len + 1):
            for _ in range(cfg.num_updates_per_iter):
                batch = self.buffer.sample(cfg.batch_size, cfg.seq_len,
                                           self.rng)
                self.key, sub = jax.random.split(self.key)
                (self.params, self.target_critic, self.opt_wm,
                 self.opt_actor, self.opt_critic, self.ret_scale,
                 metrics) = self._update(
                    self.params, self.target_critic, self.opt_wm,
                    self.opt_actor, self.opt_critic, self.ret_scale,
                    batch, sub)
            metrics = {k: float(v) for k, v in metrics.items()}
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(episode_returns))
                                    if episode_returns else float("nan")),
            "episodes_this_iter": len(episode_returns),
            "buffer_size": self.buffer.size,
            **metrics,
        }

    def compute_single_action(self, obs, state=None):
        """Greedy filtered action; pass/carry ``state`` (h, z, a_prev)
        across steps of one episode (None = episode start)."""
        wm_np, actor_np = self._policy_np()
        c = self.config
        if state is None:
            h = np.zeros((c.h_dim,), np.float32)
            z = np.zeros((c.n_cats * c.n_classes,), np.float32)
            a_prev = np.zeros((self.n_actions,), np.float32)
        else:
            h, z, a_prev = state
        e = _np_mlp(wm_np["encoder"],
                    np.asarray(_np_symlog(np.asarray(obs)), np.float32))
        h = _np_gru(wm_np["gru"], np.concatenate([z, a_prev]), h)
        post = _np_mlp(wm_np["post"], np.concatenate([h, e]))
        probs = _np_softmax(post.reshape(c.n_cats, c.n_classes))
        z = np.zeros_like(probs)
        z[np.arange(c.n_cats), probs.argmax(-1)] = 1.0
        z = z.reshape(-1).astype(np.float32)
        lg = _np_mlp(actor_np, np.concatenate([h, z]))
        a = int(np.argmax(lg))
        a1 = np.zeros((self.n_actions,), np.float32)
        a1[a] = 1.0
        return a, (h, z, a1)

    def stop(self):
        for w in self.workers:
            ray_tpu.kill(w)
