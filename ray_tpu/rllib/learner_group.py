"""LearnerGroup: multi-learner (data-parallel) policy optimization.

Reference analog: ``rllib/core/learner/learner_group.py:61,145`` — the
reference scales learning with DDP-style learner actors (one per GPU,
torch DDP gradient averaging). The TPU-native redesign offers the same
capability with two planes:

- ``mode="mesh"`` (the TPU-first default): learners are data-parallel
  shards of ONE jitted update over a ``jax.sharding.Mesh`` — the batch
  shards over a ``dp`` axis, params stay replicated, and XLA inserts the
  gradient ``psum`` over ICI. One process drives any number of chips;
  this is what replaces the reference's one-actor-per-GPU DDP wiring.
- ``mode="actors"``: learner ACTORS (separate worker processes), each
  holding a params+optimizer replica, averaging gradients over the
  host collective plane (``util/collective.py`` — the Gloo analog).
  This exercises the cross-process path the reference uses, and scales
  learning beyond one host without a shared device mesh.

Algorithms plug in three pure functions: ``init_fn(key) -> params``,
``grad_fn(params, batch) -> (grads, stats)`` and an optax ``tx``; the
group owns params/opt_state and exposes ``update(batch)`` +
``get_params()`` (numpy, for rollout-worker broadcast).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np


def _pad_to_multiple(batch: dict, k: int) -> dict:
    """Pad the leading axis to a multiple of k by wrapping (mesh-sharded
    updates need equal per-shard sizes; wrapped rows re-weight a few
    samples — the standard drop-or-pad trade, biased toward pad)."""
    n = len(next(iter(batch.values())))
    rem = n % k
    if rem == 0:
        return batch
    extra = k - rem
    idx = np.arange(extra) % n
    return {key: np.concatenate([v, v[idx]]) for key, v in batch.items()}


class _MeshLearner:
    """SPMD data-parallel learners: one jit over a dp mesh axis."""

    def __init__(self, *, init_fn, grad_fn, tx, num_learners: int,
                 seed: int, devices=None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.parallel.mesh import create_mesh

        self.num_learners = num_learners
        if devices is None:
            avail = jax.devices()
            if len(avail) < num_learners:
                raise ValueError(
                    f"num_learners={num_learners} requires that many "
                    f"devices; found {len(avail)} "
                    f"({avail[0].platform})")
            devices = avail[:num_learners]
        self.mesh = create_mesh({"dp": num_learners}, devices=devices)
        self._rep = NamedSharding(self.mesh, P())
        self._batch_sh = NamedSharding(self.mesh, P("dp"))
        self.tx = tx
        params = init_fn(jax.random.key(seed))
        self.params = jax.device_put(params, self._rep)
        self.opt_state = jax.device_put(tx.init(params), self._rep)

        def step(params, opt_state, batch):
            grads, stats = grad_fn(params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, stats

        self._step = jax.jit(
            step,
            in_shardings=(self._rep, self._rep, self._batch_sh),
            out_shardings=(self._rep, self._rep, self._rep),
        )

    def update(self, batch: dict) -> dict:
        import jax

        batch = _pad_to_multiple(batch, self.num_learners)
        batch = jax.device_put(batch, self._batch_sh)
        self.params, self.opt_state, stats = self._step(
            self.params, self.opt_state, batch)
        return stats

    def get_params(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_params(self, params):
        """Replace the replicated params (checkpoint restore); optimizer
        state restarts fresh."""
        import jax

        self.params = jax.device_put(params, self._rep)
        self.opt_state = jax.device_put(self.tx.init(params), self._rep)


class _LearnerActorImpl:
    """One learner replica in its own worker process (reference: the
    per-GPU Learner actor). Gradient averaging over the host collective
    plane; identical seeds keep replicas in lockstep."""

    def __init__(self, ctor_blob: bytes, group_name: str, world_size: int,
                 rank: int, seed: int):
        import cloudpickle
        import jax

        init_fn, grad_fn, tx = cloudpickle.loads(ctor_blob)
        self.rank = rank
        self.world = world_size
        self.params = init_fn(jax.random.key(seed))
        self.tx = tx
        self.opt_state = tx.init(self.params)
        self._grad = jax.jit(grad_fn)
        if world_size > 1:
            from ray_tpu.util.collective import CollectiveGroup

            self.group = CollectiveGroup(group_name, world_size, rank)
        else:
            self.group = None
    def _allreduce_mean(self, grads):
        import jax

        leaves, treedef = jax.tree.flatten(grads)
        flat = np.concatenate([np.asarray(g).ravel() for g in leaves])
        flat = self.group.allreduce(flat) / self.world
        out, off = [], 0
        for leaf in leaves:
            size = leaf.size
            out.append(flat[off:off + size].reshape(leaf.shape))
            off += size
        return jax.tree.unflatten(treedef, out)

    def update(self, shard: dict) -> dict:
        import jax

        grads, stats = self._grad(self.params, shard)
        if self.group is not None:
            grads = self._allreduce_mean(grads)
        updates, self.opt_state = self.tx.update(
            grads, self.opt_state, self.params)
        self.params = jax.tree.map(lambda p, u: p + u, self.params,
                                   updates)
        return {k: float(v) for k, v in stats.items()}

    def get_params(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_params(self, params):
        self.params = params
        self.opt_state = self.tx.init(params)
        return True

    def ping(self):
        return self.rank


class _ActorLearners:
    """N learner actors + scatter/gather driver."""

    def __init__(self, *, init_fn, grad_fn, tx, num_learners: int,
                 seed: int):
        import cloudpickle

        import ray_tpu

        self.num_learners = num_learners
        blob = cloudpickle.dumps((init_fn, grad_fn, tx), protocol=5)
        group_name = f"learners-{seed}-{id(self)}"
        cls = ray_tpu.remote(_LearnerActorImpl)
        self.actors = [
            cls.remote(blob, group_name, num_learners, rank, seed)
            for rank in range(num_learners)
        ]
        ray_tpu.get([a.ping.remote() for a in self.actors])

    def update(self, batch: dict) -> dict:
        import ray_tpu

        batch = _pad_to_multiple(batch, self.num_learners)
        n = len(next(iter(batch.values())))
        per = n // self.num_learners
        shards = [
            {k: v[i * per:(i + 1) * per] for k, v in batch.items()}
            for i in range(self.num_learners)
        ]
        stats = ray_tpu.get([
            a.update.remote(s) for a, s in zip(self.actors, shards)
        ], timeout=120)
        return {k: float(np.mean([s[k] for s in stats]))
                for k in stats[0]}

    def get_params(self):
        import ray_tpu

        return ray_tpu.get(self.actors[0].get_params.remote(), timeout=60)

    def set_params(self, params):
        import ray_tpu

        ray_tpu.get([a.set_params.remote(params) for a in self.actors],
                    timeout=60)

    def stop(self):
        import ray_tpu

        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass


class LearnerGroup:
    """Facade (reference: ``LearnerGroup`` learner_group.py:61): owns the
    learner plane, dispatches batches, exposes replicated params."""

    def __init__(self, *, init_fn: Callable, grad_fn: Callable, tx: Any,
                 num_learners: int = 1, mode: str = "mesh", seed: int = 0,
                 devices=None):
        if mode not in ("mesh", "actors"):
            raise ValueError(f"unknown learner mode {mode!r}")
        self.mode = mode
        if mode == "mesh":
            self._impl = _MeshLearner(
                init_fn=init_fn, grad_fn=grad_fn, tx=tx,
                num_learners=max(1, num_learners), seed=seed,
                devices=devices)
        else:
            self._impl = _ActorLearners(
                init_fn=init_fn, grad_fn=grad_fn, tx=tx,
                num_learners=max(1, num_learners), seed=seed)

    def update(self, batch: dict) -> dict:
        return self._impl.update(batch)

    def get_params(self):
        return self._impl.get_params()

    def set_params(self, params):
        """Replace every replica's params (checkpoint restore); optimizer
        state restarts fresh on all learners."""
        self._impl.set_params(params)

    def stop(self):
        stop = getattr(self._impl, "stop", None)
        if stop is not None:
            stop()
