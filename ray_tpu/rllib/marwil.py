"""MARWIL — Monotonic Advantage Re-Weighted Imitation Learning.

Reference analog: ``rllib/algorithms/marwil/marwil.py`` — hybrid
imitation/RL from an offline dataset: fit a value function on
Monte-Carlo returns, then weight the behavior-cloning log-likelihood by
``exp(beta * advantage)`` so better-than-average transitions are imitated
harder. ``beta = 0`` degenerates to plain BC (the same relationship the
reference documents between its MARWIL and BC classes — here BC lives in
``ray_tpu.rllib.offline`` and MARWIL reuses its dataset format).

The update is one jitted program over the PPO-style MLP module.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.estimators import episodes_from_dataset
from ray_tpu.rllib.offline import OfflineDataset
from ray_tpu.rllib.ppo import _np_forward, forward_module, init_module


@dataclass
class MARWILConfig:
    env: str = "CartPole-v1"
    input_path: str = ""
    lr: float = 1e-3
    beta: float = 1.0               # advantage-weighting temperature
    vf_coeff: float = 1.0
    gamma: float = 0.99
    batch_size: int = 256
    hidden: int = 64
    # moving average of squared advantage used to normalize the
    # exponent (the reference's ``moving_average_sqd_adv_norm``)
    adv_norm_decay: float = 0.99
    seed: int = 0

    def environment(self, env):
        return replace(self, env=env)

    def offline_data(self, input_path: str):
        return replace(self, input_path=input_path)

    def training(self, **kw):
        return replace(self, **kw)

    def build(self):
        return MARWIL(self)


class MARWIL:
    def __init__(self, config: MARWILConfig):
        import jax
        import optax

        self.config = config
        env = make_env(config.env, seed=config.seed)
        self.env = env
        self.params = init_module(jax.random.key(config.seed),
                                  env.obs_dim, env.n_actions,
                                  config.hidden)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.iteration = 0
        self.rng = np.random.default_rng(config.seed)
        self._sqd_adv_norm = 1.0

        ds = OfflineDataset(config.input_path)
        # Monte-Carlo returns per episode (the regression target for the
        # value head and the advantage source for the policy weight)
        obs, actions, returns = [], [], []
        for ep in episodes_from_dataset(ds):
            g = 0.0
            rets = np.zeros(len(ep["rewards"]))
            for t in range(len(ep["rewards"]) - 1, -1, -1):
                g = ep["rewards"][t] + config.gamma * g
                rets[t] = g
            obs.append(ep["obs"])
            actions.append(ep["actions"])
            returns.append(rets)
        self.data = {
            "obs": np.concatenate(obs).astype(np.float32),
            "actions": np.concatenate(actions).astype(np.int32),
            "returns": np.concatenate(returns).astype(np.float32),
        }
        self._update = jax.jit(partial(
            _marwil_update, tx=self.tx, beta=config.beta,
            vf_coeff=config.vf_coeff))

    def train(self) -> dict:
        n = len(self.data["obs"])
        sel = self.rng.permutation(n)[:self.config.batch_size]
        batch = {k: v[sel] for k, v in self.data.items()}
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch,
            sqd_adv_norm=self._sqd_adv_norm)
        d = self.config.adv_norm_decay
        self._sqd_adv_norm = (d * self._sqd_adv_norm +
                              (1 - d) * float(stats["sqd_adv"]))
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "policy_loss": float(stats["policy_loss"]),
            "vf_loss": float(stats["vf_loss"]),
            "mean_adv_weight": float(stats["mean_weight"]),
            "num_samples_trained": len(batch["obs"]),
        }

    def compute_action(self, obs) -> int:
        import jax

        params_np = jax.tree.map(np.asarray, self.params)
        logits, _ = _np_forward(params_np, np.asarray(obs)[None])
        return int(np.argmax(logits[0]))

    def evaluate(self, num_episodes: int = 10) -> dict:
        rets = []
        for _ in range(num_episodes):
            obs, total, done = self.env.reset(), 0.0, False
            steps = 0
            while not done and steps < 500:
                obs, r, done, _ = self.env.step(self.compute_action(obs))
                total += r
                steps += 1
            rets.append(total)
        return {"episode_return_mean": float(np.mean(rets))}

    def save(self, path: str):
        import pickle

        import jax

        with open(path, "wb") as f:
            pickle.dump(jax.tree.map(np.asarray, self.params), f)

    def restore(self, path: str):
        import pickle

        with open(path, "rb") as f:
            self.params = pickle.load(f)

    def stop(self):
        pass


def _marwil_update(params, opt_state, batch, *, sqd_adv_norm, tx, beta,
                   vf_coeff):
    import jax
    import jax.numpy as jnp

    def loss_fn(p):
        logits, values = forward_module(p, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1).squeeze(-1)
        adv = batch["returns"] - values
        # normalize the exponent by the running RMS of advantages so the
        # weights stay bounded as the value fit improves
        weight = jnp.exp(beta * adv /
                         jnp.sqrt(sqd_adv_norm + 1e-8))
        weight = jnp.minimum(weight, 20.0)  # explosion guard
        policy_loss = -jnp.mean(jax.lax.stop_gradient(weight) * logp)
        vf_loss = jnp.mean(adv ** 2)
        total = policy_loss + vf_coeff * vf_loss
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "mean_weight": jnp.mean(weight),
                       "sqd_adv": jnp.mean(adv ** 2)}

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, stats
