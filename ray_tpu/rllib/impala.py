"""IMPALA on JAX: decoupled actors + V-trace off-policy correction.

Reference analog: ``rllib/algorithms/impala/`` — rollout actors collect
trajectories under a BEHAVIOR policy that lags the learner; the learner
corrects the off-policyness with V-trace (Espeholt et al. 2018)
truncated importance sampling. TPU-first shape: the V-trace recursion is
a ``lax.scan`` over time inside one jitted update (static shapes, no
host loop), and the policy/value MLP reuses the PPO module.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import (_np_forward, _softmax, forward_module,
                               init_module)


class _TrajectoryWorker:
    """Collects fixed-length trajectories with behavior logits recorded
    for V-trace. VECTORIZED over ``num_envs`` environments: each step
    runs one batched policy forward for all envs (reference: vectorized
    EnvRunner — the round-3 one-env-per-forward weakness)."""

    def __init__(self, env_name, seed: int, num_envs: int = 1,
                 cell: str | None = None):
        self.envs = [make_env(env_name, seed=seed + i)
                     for i in range(num_envs)]
        self.rng = np.random.default_rng(seed)
        self.obs = np.stack([e.reset() for e in self.envs])   # [E, obs]
        self.ep_ret = np.zeros(num_envs)
        self.num_envs = num_envs
        # recurrent core (reference: recurrent_net.py:25): the worker
        # CARRIES its state across unrolls and records the state at
        # each unroll's first step so the learner's scan replays it
        self.cell = cell
        self.state = None

    def sample(self, params_np: dict, unroll_length: int):
        from ray_tpu.rllib.ppo import _sample_actions, _softmax_rows

        T, ne = unroll_length, self.num_envs
        recurrent = self.cell is not None
        if recurrent:
            from ray_tpu.rllib.recurrent import (np_recurrent_step,
                                                 zero_state)

            if self.state is None:
                self.state = zero_state(params_np, ne)
            h0 = self.state.copy()
        obs_l, act_l, logits_l, rew_l, done_l = [], [], [], [], []
        episode_returns = []
        for _ in range(T):
            if recurrent:
                logits, _, self.state = np_recurrent_step(
                    params_np, self.obs, self.state)
            else:
                logits, _ = _np_forward(params_np, self.obs)  # [E, A]
            probs = _softmax_rows(logits)
            actions = _sample_actions(self.rng, probs)
            obs_l.append(self.obs.copy())
            act_l.append(actions)
            logits_l.append(logits)
            step_rew = np.zeros(ne, np.float32)
            step_done = np.zeros(ne, np.float32)
            for i, env in enumerate(self.envs):
                o, r, d, _ = env.step(int(actions[i]))
                step_rew[i] = r
                step_done[i] = float(d)
                self.ep_ret[i] += r
                if d:
                    episode_returns.append(float(self.ep_ret[i]))
                    self.ep_ret[i] = 0.0
                    o = env.reset()
                    if recurrent:
                        self.state[i] = 0.0   # fresh episode, fresh memory
                self.obs[i] = o
            rew_l.append(step_rew)
            done_l.append(step_done)
        # [T, E, ...] -> [E, T, ...] (the learner stacks over the batch
        # axis; each env is one trajectory)
        out = {
            "obs": np.stack(obs_l).swapaxes(0, 1).astype(np.float32),
            "actions": np.stack(act_l).swapaxes(0, 1).astype(np.int32),
            "behavior_logits": np.stack(logits_l).swapaxes(0, 1).astype(
                np.float32),
            "rewards": np.stack(rew_l).swapaxes(0, 1),
            "dones": np.stack(done_l).swapaxes(0, 1),
            "bootstrap_obs": self.obs.copy().astype(np.float32),
            "episode_returns": episode_returns,
        }
        if recurrent:
            out["h0"] = h0
        return out


@dataclass
class IMPALAConfig:
    env: str = "CartPole-v1"
    num_rollout_workers: int = 2
    # envs stepped in lockstep per worker (one batched forward per step)
    num_envs_per_worker: int = 1
    unroll_length: int = 64
    lr: float = 5e-4
    gamma: float = 0.99
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    rho_clip: float = 1.0     # V-trace rho-bar
    c_clip: float = 1.0       # V-trace c-bar
    # None = plain V-trace policy gradient; a float enables the APPO
    # clipped surrogate (see rllib/appo.py)
    clip_param: float | None = None
    hidden: int = 64
    # recurrent policy core (reference: recurrent_net.py:25 — LSTM/GRU
    # wrapping for POMDP envs): None = feedforward MLP
    cell: str | None = None
    seed: int = 0
    # multi-learner plane (reference: LearnerGroup learner_group.py:61)
    num_learners: int = 0
    learner_mode: str = "mesh"

    def environment(self, env) -> "IMPALAConfig":
        return replace(self, env=env)

    def rollouts(self, **kw) -> "IMPALAConfig":
        return replace(self, **kw)

    def training(self, **kw) -> "IMPALAConfig":
        return replace(self, **kw)

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALA:
    """Synchronous driver over the async algorithm's math: workers
    sample with the PREVIOUS iteration's params (one-step policy lag,
    like the reference's in-flight sample batches), and V-trace corrects
    the drift."""

    def __init__(self, config: IMPALAConfig):
        import jax
        import optax

        self.config = config
        env = make_env(config.env, seed=config.seed)
        self.obs_dim = env.obs_dim
        self.n_actions = env.n_actions
        self.tx = optax.adam(config.lr)
        self.iteration = 0
        worker_cls = ray_tpu.remote(_TrajectoryWorker)
        self.workers = [
            worker_cls.remote(config.env, config.seed + 1000 * (i + 1),
                              config.num_envs_per_worker, config.cell)
            for i in range(config.num_rollout_workers)
        ]
        grad_fn = partial(
            _impala_grads, gamma=config.gamma, rho_clip=config.rho_clip,
            c_clip=config.c_clip, entropy_coeff=config.entropy_coeff,
            vf_coeff=config.vf_coeff, clip_param=config.clip_param,
            cell=config.cell)
        if config.num_learners > 0:
            from ray_tpu.rllib.learner_group import LearnerGroup

            # bind plain ints — a lambda over `self` would cloudpickle
            # the whole algorithm into every learner actor's ctor blob
            obs_dim, n_actions, hidden = (self.obs_dim, self.n_actions,
                                          config.hidden)
            cell = config.cell
            if cell is not None:
                from ray_tpu.rllib.recurrent import init_recurrent_module

                def _init(key):
                    full = init_recurrent_module(key, obs_dim, n_actions,
                                                 hidden, cell)
                    # the string tag stays out of the optimizer pytree
                    return {k: v for k, v in full.items()
                            if k != "cell_type"}
            else:
                def _init(key):
                    return init_module(key, obs_dim, n_actions, hidden)
            self.learners = LearnerGroup(
                init_fn=_init,
                grad_fn=grad_fn, tx=self.tx,
                num_learners=config.num_learners,
                mode=config.learner_mode, seed=config.seed)
            self.params = None
            self.opt_state = None
        else:
            self.learners = None
            if config.cell is not None:
                from ray_tpu.rllib.recurrent import init_recurrent_module

                full = init_recurrent_module(
                    jax.random.key(config.seed), self.obs_dim,
                    self.n_actions, config.hidden, config.cell)
                # the string tag stays out of the optimizer pytree; the
                # worker-facing params re-add it in _params_np
                self.params = {k: v for k, v in full.items()
                               if k != "cell_type"}
            else:
                self.params = init_module(jax.random.key(config.seed),
                                          self.obs_dim, self.n_actions,
                                          config.hidden)
            self.opt_state = self.tx.init(self.params)
            self._update = jax.jit(partial(
                _impala_update, tx=self.tx, gamma=config.gamma,
                rho_clip=config.rho_clip, c_clip=config.c_clip,
                entropy_coeff=config.entropy_coeff,
                vf_coeff=config.vf_coeff,
                clip_param=config.clip_param, cell=config.cell))
        self._inflight = None  # refs sampled with lagged params

    def _params_np(self):
        import jax

        if self.learners is not None:
            params = self.learners.get_params()
        else:
            params = jax.tree.map(np.asarray, self.params)
        if self.config.cell is not None:
            params = {**params, "cell_type": self.config.cell}
        return params

    def train(self) -> dict:
        cfg = self.config
        params_np = self._params_np()
        if self._inflight is None:  # first iteration: no lag yet
            self._inflight = [
                w.sample.remote(params_np, cfg.unroll_length)
                for w in self.workers]
        batches = ray_tpu.get(self._inflight)
        # launch the NEXT round immediately with current params — by the
        # time the learner finishes, these are one update stale (the
        # off-policy lag V-trace exists to correct)
        self._inflight = [
            w.sample.remote(params_np, cfg.unroll_length)
            for w in self.workers]

        episode_returns = [r for b in batches
                           for r in b["episode_returns"]]
        # concatenate env trajectories to [B, T, ...] (each worker
        # contributes num_envs_per_worker trajectories)
        keys = ["obs", "actions", "behavior_logits", "rewards",
                "dones", "bootstrap_obs"]
        if cfg.cell is not None:
            keys.append("h0")
        batch = {
            k: np.concatenate([b[k] for b in batches]) for k in keys
        }
        if self.learners is not None:
            stats = self.learners.update(batch)
        else:
            self.params, self.opt_state, stats = self._update(
                self.params, self.opt_state, batch)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(episode_returns))
                                    if episode_returns else 0.0),
            "num_episodes": len(episode_returns),
            "policy_loss": float(stats["policy_loss"]),
            "vf_loss": float(stats["vf_loss"]),
            "entropy": float(stats["entropy"]),
            "mean_rho": float(stats["mean_rho"]),
        }

    def compute_action(self, obs, state=None):
        if self.config.cell is not None:
            from ray_tpu.rllib.recurrent import (np_recurrent_step,
                                                 zero_state)

            params = self._params_np()
            if state is None:
                state = zero_state(params, 1)
            logits, _, state = np_recurrent_step(
                params, np.asarray(obs, np.float32)[None], state)
            return int(np.argmax(logits[0])), state
        logits, _ = _np_forward(self._params_np(), np.asarray(obs)[None])
        return int(np.argmax(logits[0]))

    def save(self, path: str):
        import pickle

        with open(path, "wb") as f:
            pickle.dump(self._params_np(), f)

    def restore(self, path: str):
        import pickle

        with open(path, "rb") as f:
            params = pickle.load(f)
        if self.learners is not None:
            self.learners.set_params(params)
        else:
            self.params = params

    def stop(self):
        if self.learners is not None:
            self.learners.stop()
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass


def vtrace(behavior_logp, target_logp, rewards, values, bootstrap_value,
           dones, *, gamma, rho_clip, c_clip):
    """V-trace targets (Espeholt et al. 2018, eq. 1) as a reverse
    lax.scan over time. Inputs are time-major [T, B]."""
    import jax
    import jax.numpy as jnp

    rho = jnp.exp(target_logp - behavior_logp)
    rho_bar = jnp.minimum(rho, rho_clip)
    c_bar = jnp.minimum(rho, c_clip)
    discounts = gamma * (1.0 - dones)

    values_next = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = rho_bar * (rewards + discounts * values_next - values)

    def backward(acc, inputs):
        delta_t, disc_t, c_t = inputs
        acc = delta_t + disc_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, c_bar), reverse=True)
    vs = vs_minus_v + values
    # advantage for the policy gradient uses vs_{t+1}
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rho_bar * (rewards + discounts * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv), rho


def _impala_grads(params, batch, *, gamma, rho_clip, c_clip,
                  entropy_coeff, vf_coeff, clip_param=None, cell=None):
    """Pure gradient fn (Learner.compute_gradients analog); under a
    dp-sharded batch axis the mean-loss grads are globally averaged.
    ``cell``: recurrent core — the forward becomes a lax.scan over the
    unroll with the worker-recorded initial state (batch["h0"]), episode
    boundaries resetting the carried state in-scan."""
    import jax
    import jax.numpy as jnp

    # batch is [B, T, ...]; V-trace wants time-major
    obs = jnp.swapaxes(batch["obs"], 0, 1)               # [T, B, obs]
    actions = jnp.swapaxes(batch["actions"], 0, 1)       # [T, B]
    behavior_logits = jnp.swapaxes(batch["behavior_logits"], 0, 1)
    rewards = jnp.swapaxes(batch["rewards"], 0, 1)
    dones = jnp.swapaxes(batch["dones"], 0, 1)

    def loss_fn(p):
        T, B = actions.shape
        if cell is not None:
            from ray_tpu.rllib.recurrent import (_cell_step,
                                                 forward_recurrent_seq)

            pf = {**p, "cell_type": cell}
            logits_bt, values_bt, h_final = forward_recurrent_seq(
                pf, batch["obs"], batch["h0"], batch["dones"])
            logits = jnp.swapaxes(logits_bt, 0, 1)
            values = jnp.swapaxes(values_bt, 0, 1)
            # bootstrap value: one more cell step from the carried
            # state (zeroed where the last unroll step ended an episode
            # — the bootstrap obs is then a fresh reset)
            h_boot = h_final * (1.0 - batch["dones"][:, -1])[:, None]
            x = jnp.tanh(batch["bootstrap_obs"] @ pf["enc"]["w"]
                         + pf["enc"]["b"])
            h, _ = _cell_step(pf, x, h_boot, jnp)
            bootstrap_value = (h @ pf["vf"]["w"]
                               + pf["vf"]["b"]).squeeze(-1)
        else:
            logits, values = forward_module(p, obs.reshape(T * B, -1))
            logits = logits.reshape(T, B, -1)
            values = values.reshape(T, B)
            _, bootstrap_value = forward_module(p, batch["bootstrap_obs"])

        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, actions[..., None], axis=-1).squeeze(-1)
        blogp_all = jax.nn.log_softmax(behavior_logits)
        behavior_logp = jnp.take_along_axis(
            blogp_all, actions[..., None], axis=-1).squeeze(-1)

        vs, pg_adv, rho = vtrace(
            behavior_logp, target_logp, rewards, values,
            jax.lax.stop_gradient(bootstrap_value), dones,
            gamma=gamma, rho_clip=rho_clip, c_clip=c_clip)

        if clip_param is not None:
            # APPO: PPO clipped surrogate on the V-trace advantage
            # (reference: rllib/algorithms/appo/ — IMPALA architecture
            # with the clip objective stabilizing the off-policy update)
            ratio = jnp.exp(target_logp - behavior_logp)
            clipped = jnp.clip(ratio, 1.0 - clip_param, 1.0 + clip_param)
            policy_loss = -jnp.mean(
                jnp.minimum(ratio * pg_adv, clipped * pg_adv))
        else:
            policy_loss = -jnp.mean(target_logp * pg_adv)
        vf_loss = jnp.mean((values - vs) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = policy_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": entropy, "mean_rho": jnp.mean(rho)}

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    return grads, stats


def _impala_update(params, opt_state, batch, *, tx, gamma, rho_clip,
                   c_clip, entropy_coeff, vf_coeff, clip_param=None,
                   cell=None):
    import jax

    grads, stats = _impala_grads(
        params, batch, gamma=gamma, rho_clip=rho_clip, c_clip=c_clip,
        entropy_coeff=entropy_coeff, vf_coeff=vf_coeff,
        clip_param=clip_param, cell=cell)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, stats
