"""Multi-agent RL: env API, policy mapping, and multi-agent PPO.

Reference analogs:
- ``MultiAgentEnv`` (``rllib/env/multi_agent_env.py:30``): dict-keyed
  reset/step — ``reset() -> {agent_id: obs}``, ``step({agent_id: action})
  -> (obs_dict, reward_dict, done_dict, info_dict)`` with the special
  ``"__all__"`` done key ending the episode for everyone.
- ``PolicyMap`` (``rllib/policy/policy_map.py:20``): policy_id -> policy
  state with an LRU capacity bound (least-recently-used states detach to
  host/disk so league-style setups with 100s of policies fit in memory).
- policy mapping in rollouts (``rllib/evaluation/rollout_worker.py``,
  ``policy_mapping_fn``): every agent's observation routes to the policy
  its id maps to; sample batches are collected PER POLICY.
- multi-agent PPO training (``rllib/algorithms/ppo``) with shared or
  independent policies.

TPU-first shape: policies are pure JAX param pytrees in a dict; each
policy's update is one jitted fused step (the same update as
single-agent ``ppo._ppo_update``), so N policies = N small jit calls,
not a Python object graph. Rollouts are host-side numpy like the
single-agent workers (the envs are host-bound anyway).
"""

from __future__ import annotations

import pickle
from collections import OrderedDict, defaultdict
from dataclasses import dataclass, field, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.ppo import (
    _gae,
    _np_forward,
    _ppo_update,
    _sample_actions,
    _softmax_rows,
    init_module,
)

AGENT_DONE_ALL = "__all__"


# ---------------------------------------------------------------------------
# MultiAgentEnv API + builtin envs
# ---------------------------------------------------------------------------

class MultiAgentEnv:
    """Base class for environments hosting multiple independent agents
    (reference: ``rllib/env/multi_agent_env.py:30``).

    Contract:
    - ``agent_ids``: iterable of string agent ids.
    - ``reset() -> {agent_id: obs}`` for every agent acting first step.
    - ``step(action_dict) -> (obs, rewards, dones, infos)``, all dicts
      keyed by agent id; ``dones["__all__"]`` ends the episode for every
      agent. Agents absent from ``obs`` don't act next step.
    - ``obs_dims`` / ``n_actions_map``: per-agent obs sizes and action
      counts (dict or scalar applied to all agents).
    """

    agent_ids: tuple = ()

    def reset(self) -> dict:
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    # -- space helpers (scalar = uniform across agents) --
    def obs_dim_of(self, agent_id) -> int:
        dims = getattr(self, "obs_dims", None)
        if isinstance(dims, dict):
            return dims[agent_id]
        return int(dims)

    def n_actions_of(self, agent_id) -> int:
        acts = getattr(self, "n_actions_map", None)
        if isinstance(acts, dict):
            return acts[agent_id]
        return int(acts)


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPoles, one per agent (the reference's standard
    multi-agent debug env, ``rllib/examples/env/multi_agent.py``).
    Each agent's episode ends on its own pole falling; ``__all__`` when
    every agent is done."""

    def __init__(self, num_agents: int = 2, seed: int | None = None):
        from ray_tpu.rllib.env import CartPole

        self.agent_ids = tuple(f"agent_{i}" for i in range(num_agents))
        self.envs = {a: CartPole(seed=None if seed is None else seed + i)
                     for i, a in enumerate(self.agent_ids)}
        self.obs_dims = 4
        self.n_actions_map = 2
        self._done: set = set()

    def reset(self) -> dict:
        self._done = set()
        return {a: e.reset() for a, e in self.envs.items()}

    def step(self, action_dict: dict):
        obs, rews, dones, infos = {}, {}, {}, {}
        for a, act in action_dict.items():
            if a in self._done:
                continue
            o, r, d, i = self.envs[a].step(int(act))
            rews[a] = r
            dones[a] = d
            infos[a] = i
            if d:
                self._done.add(a)
            else:
                obs[a] = o
        dones[AGENT_DONE_ALL] = len(self._done) == len(self.agent_ids)
        return obs, rews, dones, infos


class CoopMatchEnv(MultiAgentEnv):
    """Two-agent cooperative coordination game with a deterministic
    learning signal (the multi-agent analog of ``BanditEnv``): each
    agent sees ITS OWN context in {-1,+1}^2 (different per agent);
    the team earns 1.0 split evenly only when BOTH agents match the
    sign of their own context. Solvable only if per-agent observations
    reach the right policies — a policy-routing bug flatlines it."""

    def __init__(self, seed: int | None = None):
        self.agent_ids = ("a0", "a1")
        self.rng = np.random.default_rng(seed)
        self.obs_dims = 2
        self.n_actions_map = 2
        self._obs: dict = {}

    def reset(self) -> dict:
        self._obs = {
            a: self.rng.choice([-1.0, 1.0], size=2).astype(np.float32)
            for a in self.agent_ids
        }
        return dict(self._obs)

    def step(self, action_dict: dict):
        ok = all((self._obs[a][0] > 0) == (int(action_dict[a]) == 1)
                 for a in self.agent_ids)
        rew = {a: (0.5 if ok else 0.0) for a in self.agent_ids}
        obs = self.reset()
        dones = {a: True for a in self.agent_ids}
        dones[AGENT_DONE_ALL] = True
        return obs, rew, dones, {}


MULTI_ENV_REGISTRY = {
    "MultiAgentCartPole": MultiAgentCartPole,
    "CoopMatch-v0": CoopMatchEnv,
}


def make_multi_env(name_or_cls, seed=None, **kw):
    if isinstance(name_or_cls, str):
        cls = MULTI_ENV_REGISTRY[name_or_cls]
        return cls(seed=seed, **kw)
    return name_or_cls(seed=seed, **kw)


# ---------------------------------------------------------------------------
# PolicyMap
# ---------------------------------------------------------------------------

class PolicyMap:
    """policy_id -> policy state (param pytrees here), LRU-bounded
    (reference: ``rllib/policy/policy_map.py:20`` — keeps ``capacity``
    policies in memory, detaches the least recently used to disk so
    league-based setups with 100s of policies fit)."""

    def __init__(self, capacity: int = 100, spill_dir: str | None = None):
        self.capacity = capacity
        self._mem: OrderedDict = OrderedDict()
        self._spill_dir = spill_dir
        self._spilled: dict[str, str] = {}

    def __setitem__(self, policy_id: str, state):
        self._mem[policy_id] = state
        self._mem.move_to_end(policy_id)
        self._maybe_spill()

    def __getitem__(self, policy_id: str):
        if policy_id in self._mem:
            self._mem.move_to_end(policy_id)
            return self._mem[policy_id]
        path = self._spilled.get(policy_id)
        if path is None:
            raise KeyError(policy_id)
        with open(path, "rb") as f:
            state = pickle.load(f)
        self._spilled.pop(policy_id)
        self[policy_id] = state   # back in memory (may spill another)
        return state

    def __contains__(self, policy_id: str) -> bool:
        return policy_id in self._mem or policy_id in self._spilled

    def __iter__(self):
        yield from self._mem
        yield from self._spilled

    def __len__(self):
        return len(self._mem) + len(self._spilled)

    def keys(self):
        return list(self)

    def _maybe_spill(self):
        import os
        import tempfile

        while len(self._mem) > self.capacity:
            pid, state = self._mem.popitem(last=False)   # LRU
            if self._spill_dir is None:
                self._spill_dir = tempfile.mkdtemp(prefix="policy_map_")
            os.makedirs(self._spill_dir, exist_ok=True)
            path = f"{self._spill_dir}/{pid}.pkl"
            with open(path, "wb") as f:
                pickle.dump(state, f)
            self._spilled[pid] = path


# ---------------------------------------------------------------------------
# Multi-policy replay (off-policy algorithms)
# ---------------------------------------------------------------------------

class MultiAgentReplay:
    """Replay keyed by policy id (reference: ``MultiAgentReplayBuffer``,
    rllib/utils/replay_buffers/multi_agent_replay_buffer.py): each
    policy's transitions live in an independent ring; sampling draws a
    per-policy batch so off-policy updates never mix experience across
    policies."""

    def __init__(self, capacity_per_policy: int = 100_000, seed: int = 0):
        self.capacity = capacity_per_policy
        self.rng = np.random.default_rng(seed)
        self._buffers: dict[str, dict] = {}
        self._sizes: dict[str, int] = defaultdict(int)
        self._heads: dict[str, int] = defaultdict(int)

    def add(self, policy_id: str, transitions: dict):
        """``transitions``: dict of equal-length arrays (column store)."""
        n = len(next(iter(transitions.values())))
        buf = self._buffers.get(policy_id)
        if buf is None:
            buf = {k: np.zeros((self.capacity,) + np.asarray(v).shape[1:],
                               np.asarray(v).dtype)
                   for k, v in transitions.items()}
            self._buffers[policy_id] = buf
        head = self._heads[policy_id]
        idx = (head + np.arange(n)) % self.capacity
        for k, v in transitions.items():
            buf[k][idx] = v
        self._heads[policy_id] = (head + n) % self.capacity
        self._sizes[policy_id] = min(self.capacity,
                                     self._sizes[policy_id] + n)

    def sample(self, policy_id: str, batch_size: int) -> dict:
        size = self._sizes[policy_id]
        if size == 0:
            raise ValueError(f"no experience for policy {policy_id!r}")
        idx = self.rng.integers(0, size, batch_size)
        return {k: v[idx] for k, v in self._buffers[policy_id].items()}

    def size(self, policy_id: str) -> int:
        return self._sizes[policy_id]

    def policy_ids(self):
        return list(self._buffers)


# ---------------------------------------------------------------------------
# Multi-agent rollout worker
# ---------------------------------------------------------------------------

class _MultiAgentRolloutWorker:
    """Steps a MultiAgentEnv, routing each agent's observation through
    the policy its id maps to (reference: per-policy batch collection in
    ``rollout_worker.py``). Returns ``{policy_id: flat batch}`` with
    per-agent GAE computed over each agent's OWN trajectory."""

    def __init__(self, env_spec, mapping_src, seed: int, env_kw=None):
        self.env = make_multi_env(env_spec, seed=seed, **(env_kw or {}))
        self.mapping = (pickle.loads(mapping_src)
                        if isinstance(mapping_src, bytes) else mapping_src)
        self.rng = np.random.default_rng(seed)

    def sample(self, policies_np: dict, num_steps: int, gamma: float,
               lam: float) -> dict:
        env = self.env
        # per-(agent) open trajectory columns
        traj = defaultdict(lambda: defaultdict(list))
        done_frags: list = []    # (agent, policy_id, cols, last_value)
        episode_returns: list = []
        ep_ret = 0.0
        obs = env.reset()
        for _ in range(num_steps):
            # group agents by policy: ONE batched forward per policy per
            # step (the multi-agent analog of the vectorized runner)
            by_policy = defaultdict(list)
            for a, o in obs.items():
                by_policy[self.mapping(a)].append((a, o))
            actions = {}
            for pid, items in by_policy.items():
                batch = np.stack([o for _, o in items])
                logits, values = _np_forward(policies_np[pid], batch)
                probs = _softmax_rows(logits)
                acts = _sample_actions(self.rng, probs)
                for j, (a, o) in enumerate(items):
                    actions[a] = int(acts[j])
                    t = traj[a]
                    t["obs"].append(o)
                    t["actions"].append(int(acts[j]))
                    t["logp"].append(
                        float(np.log(probs[j, acts[j]] + 1e-8)))
                    t["values"].append(float(values[j]))
            next_obs, rews, dones, _ = env.step(actions)
            for a in actions:
                t = traj[a]
                r = float(rews.get(a, 0.0))
                t["rewards"].append(r)
                t["dones"].append(float(bool(dones.get(a, False))))
                ep_ret += r
            # close finished agent trajectories (terminal value 0)
            for a in list(traj):
                if dones.get(a, False) or dones.get(AGENT_DONE_ALL, False):
                    done_frags.append((a, self.mapping(a),
                                       traj.pop(a), 0.0))
            if dones.get(AGENT_DONE_ALL, False):
                episode_returns.append(ep_ret)
                ep_ret = 0.0
                next_obs = env.reset()
            obs = next_obs
        # bootstrap still-open trajectories with the policy value
        for a, t in traj.items():
            pid = self.mapping(a)
            o = obs.get(a)
            last_v = 0.0
            if o is not None:
                _, v = _np_forward(policies_np[pid], o[None])
                last_v = float(v[0])
            done_frags.append((a, pid, t, last_v))
        # per-policy flat batches with per-fragment GAE
        out: dict = {}
        for _, pid, t, last_v in done_frags:
            if not t["rewards"]:
                continue
            n = len(t["rewards"])
            adv, ret = _gae(np.asarray(t["rewards"]),
                            np.asarray(t["values"][:n]),
                            np.asarray(t["dones"]), last_v, gamma, lam)
            cols = out.setdefault(pid, defaultdict(list))
            cols["obs"].append(np.asarray(t["obs"][:n], np.float32))
            cols["actions"].append(np.asarray(t["actions"][:n], np.int32))
            cols["logp"].append(np.asarray(t["logp"][:n], np.float32))
            cols["advantages"].append(adv.astype(np.float32))
            cols["returns"].append(ret.astype(np.float32))
        return {
            "batches": {
                pid: {k: np.concatenate(v) for k, v in cols.items()}
                for pid, cols in out.items()
            },
            "episode_returns": episode_returns,
        }


# ---------------------------------------------------------------------------
# Multi-agent PPO
# ---------------------------------------------------------------------------

@dataclass
class MultiAgentPPOConfig:
    """Builder-style config (reference: ``AlgorithmConfig.multi_agent``,
    algorithm_config.py): ``policies`` declares the policy ids (None =
    one shared policy "default" for every agent); ``policy_mapping_fn``
    routes agent ids to policy ids."""

    env: object = "CoopMatch-v0"
    env_kw: dict = field(default_factory=dict)
    policies: tuple = ("default",)
    policy_mapping_fn: object = None       # (agent_id) -> policy_id
    num_rollout_workers: int = 1
    rollout_fragment_length: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip_eps: float = 0.2
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    num_sgd_iter: int = 4
    minibatch_size: int = 128
    hidden: int = 64
    seed: int = 0

    def environment(self, env, **env_kw) -> "MultiAgentPPOConfig":
        return replace(self, env=env, env_kw=env_kw)

    def multi_agent(self, *, policies=None, policy_mapping_fn=None
                    ) -> "MultiAgentPPOConfig":
        cfg = self
        if policies is not None:
            cfg = replace(cfg, policies=tuple(policies))
        if policy_mapping_fn is not None:
            cfg = replace(cfg, policy_mapping_fn=policy_mapping_fn)
        return cfg

    def rollouts(self, *, num_rollout_workers=None,
                 rollout_fragment_length=None) -> "MultiAgentPPOConfig":
        cfg = self
        if num_rollout_workers is not None:
            cfg = replace(cfg, num_rollout_workers=num_rollout_workers)
        if rollout_fragment_length is not None:
            cfg = replace(cfg,
                          rollout_fragment_length=rollout_fragment_length)
        return cfg

    def training(self, **kw) -> "MultiAgentPPOConfig":
        return replace(self, **kw)

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """PPO over a policy map: shared (every agent -> one policy) or
    independent (agent -> own policy) training. Each policy holds its
    own params + Adam state and updates with the SAME jitted fused step
    as single-agent PPO — per-policy minibatches never mix."""

    def __init__(self, config: MultiAgentPPOConfig):
        import jax
        import optax

        self.config = config
        probe = make_multi_env(config.env, seed=config.seed,
                               **config.env_kw)
        mapping = config.policy_mapping_fn or (lambda aid: "default")
        # validate the mapping covers every agent with a known policy
        for a in probe.agent_ids:
            pid = mapping(a)
            if pid not in config.policies:
                raise ValueError(
                    f"policy_mapping_fn({a!r}) = {pid!r} not in "
                    f"policies {config.policies}")
        self.mapping = mapping
        self.tx = optax.adam(config.lr)
        self.policies = PolicyMap()
        self.opt_states: dict = {}
        key = jax.random.key(config.seed)
        for pid in config.policies:
            # spaces come from any agent mapped to this policy
            agents = [a for a in probe.agent_ids if mapping(a) == pid]
            if not agents:
                raise ValueError(f"policy {pid!r} has no mapped agents")
            key, sub = jax.random.split(key)
            params = init_module(sub, probe.obs_dim_of(agents[0]),
                                 probe.n_actions_of(agents[0]),
                                 config.hidden)
            self.policies[pid] = params
            self.opt_states[pid] = self.tx.init(params)
        self._update = jax.jit(partial(
            _ppo_update, tx=self.tx, clip_eps=config.clip_eps,
            entropy_coeff=config.entropy_coeff, vf_coeff=config.vf_coeff))
        worker_cls = ray_tpu.remote(_MultiAgentRolloutWorker)
        import cloudpickle

        mapping_src = cloudpickle.dumps(mapping)
        self.workers = [
            worker_cls.remote(config.env, mapping_src,
                              config.seed + 1000 * (i + 1), config.env_kw)
            for i in range(config.num_rollout_workers)
        ]
        self.iteration = 0

    def _policies_np(self) -> dict:
        import jax

        return {pid: jax.tree.map(np.asarray, self.policies[pid])
                for pid in self.policies.keys()}

    def train(self) -> dict:
        cfg = self.config
        policies_np = self._policies_np()
        results = ray_tpu.get([
            w.sample.remote(policies_np, cfg.rollout_fragment_length,
                            cfg.gamma, cfg.lam)
            for w in self.workers
        ])
        episode_returns = [r for res in results
                           for r in res["episode_returns"]]
        # merge per-policy batches across workers
        merged: dict = {}
        for res in results:
            for pid, b in res["batches"].items():
                cols = merged.setdefault(pid, defaultdict(list))
                for k, v in b.items():
                    cols[k].append(v)
        stats_acc: list = []
        rng = np.random.default_rng(cfg.seed + self.iteration)
        total_steps = 0
        for pid, cols in merged.items():
            batch = {k: np.concatenate(v) for k, v in cols.items()}
            adv = batch["advantages"]
            batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
            n = len(batch["obs"])
            total_steps += n
            params = self.policies[pid]
            opt_state = self.opt_states[pid]
            for _ in range(cfg.num_sgd_iter):
                perm = rng.permutation(n)
                for start in range(0, n, cfg.minibatch_size):
                    idx = perm[start:start + cfg.minibatch_size]
                    mb = {k: v[idx] for k, v in batch.items()}
                    params, opt_state, stats = self._update(
                        params, opt_state, mb)
                    stats_acc.append(stats)
            self.policies[pid] = params
            self.opt_states[pid] = opt_state
        self.iteration += 1
        mean = lambda key: float(np.mean(  # noqa: E731
            [float(s[key]) for s in stats_acc])) if stats_acc else 0.0
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(episode_returns))
                                    if episode_returns else 0.0),
            "num_episodes": len(episode_returns),
            "policy_loss": mean("policy_loss"),
            "entropy": mean("entropy"),
            "num_env_steps_sampled": total_steps,
            "policy_ids": sorted(merged),
        }

    def compute_actions(self, obs_dict: dict) -> dict:
        policies_np = self._policies_np()
        out = {}
        for a, o in obs_dict.items():
            logits, _ = _np_forward(policies_np[self.mapping(a)],
                                    np.asarray(o)[None])
            out[a] = int(np.argmax(logits[0]))
        return out

    def save(self, path: str):
        state = {pid: self._policies_np()[pid]
                 for pid in self.policies.keys()}
        with open(path, "wb") as f:
            pickle.dump(state, f)

    def restore(self, path: str):
        with open(path, "rb") as f:
            state = pickle.load(f)
        for pid, params in state.items():
            self.policies[pid] = params

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
