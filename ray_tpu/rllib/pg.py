"""Vanilla Policy Gradient (REINFORCE) and A2C.

Reference analogs: ``rllib/algorithms/pg/pg.py`` (the minimal
on-policy baseline: plain REINFORCE on Monte-Carlo returns, no critic)
and ``rllib/algorithms/a2c/a2c.py`` (synchronous advantage actor-critic:
the PPO sampling architecture with a single unclipped update per batch).

Both share PPO's rollout-worker actors and functional MLP module
(``ray_tpu.rllib.ppo``) the same way the reference's A2C inherits from
its PPO/PG lineage — the only difference is the loss. The updates are
single jitted programs; the MXU sees the same fused MLP matmuls.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import (
    _np_forward,
    _RolloutWorker,
    forward_module,
    init_module,
)


@dataclass
class A2CConfig:
    env: str = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 256
    lr: float = 1e-3
    gamma: float = 0.99
    lam: float = 1.0                # MC advantages by default
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    hidden: int = 64
    seed: int = 0

    def environment(self, env):
        return replace(self, env=env)

    def rollouts(self, *, num_rollout_workers=None,
                 rollout_fragment_length=None):
        cfg = self
        if num_rollout_workers is not None:
            cfg = replace(cfg, num_rollout_workers=num_rollout_workers)
        if rollout_fragment_length is not None:
            cfg = replace(cfg,
                          rollout_fragment_length=rollout_fragment_length)
        return cfg

    def training(self, **kw):
        return replace(self, **kw)

    def build(self):
        return A2C(self)


@dataclass
class PGConfig(A2CConfig):
    vf_coeff: float = 0.0           # no critic in the loss

    def build(self):
        return PG(self)


class A2C:
    """Synchronous advantage actor-critic driver."""

    _use_critic = True

    def __init__(self, config):
        import jax
        import optax

        self.config = config
        env = make_env(config.env, seed=config.seed)
        self.obs_dim = env.obs_dim
        self.n_actions = env.n_actions
        self.params = init_module(jax.random.key(config.seed),
                                  self.obs_dim, self.n_actions,
                                  config.hidden)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.iteration = 0
        worker_cls = ray_tpu.remote(_RolloutWorker)
        self.workers = [
            worker_cls.remote(config.env, config.seed + 1000 * (i + 1))
            for i in range(config.num_rollout_workers)
        ]
        self._update = jax.jit(partial(
            _a2c_update, tx=self.tx,
            entropy_coeff=config.entropy_coeff,
            vf_coeff=config.vf_coeff,
            use_critic=self._use_critic))

    def train(self) -> dict:
        import jax

        cfg = self.config
        params_np = jax.tree.map(np.asarray, self.params)
        batches = ray_tpu.get([
            w.sample.remote(params_np, cfg.rollout_fragment_length,
                            cfg.gamma, cfg.lam)
            for w in self.workers
        ])
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in ("obs", "actions", "advantages", "returns")}
        episode_returns = [r for b in batches for r in b["episode_returns"]]
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(episode_returns))
                                    if episode_returns else 0.0),
            "num_episodes": len(episode_returns),
            "policy_loss": float(stats["policy_loss"]),
            "vf_loss": float(stats["vf_loss"]),
            "entropy": float(stats["entropy"]),
            "num_env_steps_sampled": len(batch["obs"]),
        }

    def compute_action(self, obs) -> int:
        import jax

        params_np = jax.tree.map(np.asarray, self.params)
        logits, _ = _np_forward(params_np, np.asarray(obs)[None])
        return int(np.argmax(logits[0]))

    def save(self, path: str):
        import pickle

        import jax

        with open(path, "wb") as f:
            pickle.dump(jax.tree.map(np.asarray, self.params), f)

    def restore(self, path: str):
        import pickle

        with open(path, "rb") as f:
            self.params = pickle.load(f)

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass


class PG(A2C):
    """REINFORCE: the A2C machinery with the critic removed from the
    loss (the value head still exists in the module but gets no
    gradient signal when ``vf_coeff == 0`` and advantages fall back to
    returns-to-go via ``lam=1`` GAE)."""

    _use_critic = False


def _a2c_update(params, opt_state, batch, *, tx, entropy_coeff, vf_coeff,
                use_critic):
    import jax
    import jax.numpy as jnp

    def loss_fn(p):
        logits, values = forward_module(p, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1).squeeze(-1)
        adv = batch["advantages"] if use_critic else batch["returns"]
        if not use_critic:
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        policy_loss = -jnp.mean(logp * adv)
        vf_loss = jnp.mean((values - batch["returns"]) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = policy_loss - entropy_coeff * entropy
        if use_critic:
            total = total + vf_coeff * vf_loss
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, stats
