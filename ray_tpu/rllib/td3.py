"""TD3 (+ DDPG) for continuous control.

Reference analog: ``rllib/algorithms/td3`` / ``rllib/algorithms/ddpg``
(legacy stack; moved to rllib_contrib). TD3 = deterministic-policy
actor-critic with the three fixes over DDPG: twin critics (min-Q
targets), target-policy smoothing noise, and delayed policy updates.
DDPG is the degenerate config (single critic, no smoothing, delay 1) —
exposed as :class:`DDPG` the same way APPO layers over IMPALA.

Shares the MLP/critic builders, replay buffer, and rollout-actor shape
with SAC (``rllib/sac.py``); the learner is one jitted update.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.dqn import ReplayBuffer
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.sac import (_ContinuousRolloutWorker, _init_mlp,
                               _mlp, _q)


def init_td3(key, obs_dim: int, action_dim: int, hidden: int = 64,
             twin_q: bool = True):
    import jax

    ka, k1, k2 = jax.random.split(key, 3)
    params = {
        "actor": _init_mlp(ka, (obs_dim, hidden, hidden, action_dim)),
        "q1": _init_mlp(k1, (obs_dim + action_dim, hidden, hidden, 1)),
    }
    if twin_q:
        params["q2"] = _init_mlp(k2, (obs_dim + action_dim, hidden,
                                      hidden, 1))
    return params


def _pi(actor_params, obs):
    import jax.numpy as jnp

    return jnp.tanh(_mlp(actor_params, obs))


def _td3_update(params, targets, opt_state, batch, key, do_policy, *,
                tx, gamma, tau, target_noise, noise_clip, twin_q):
    """One TD3 step: critics every call; the actor (and polyak targets)
    only when ``do_policy`` (delayed policy updates)."""
    import jax
    import jax.numpy as jnp
    import optax

    obs, act = batch["obs"], batch["actions"]
    rew, nxt, done = batch["rewards"], batch["next_obs"], batch["dones"]

    # target action with clipped smoothing noise (TD3 fix #2)
    na = _pi(targets["actor"], nxt)
    noise = jnp.clip(
        target_noise * jax.random.normal(key, na.shape),
        -noise_clip, noise_clip)
    na = jnp.clip(na + noise, -1.0, 1.0)
    tq = _q(targets["q1"], nxt, na)
    if twin_q:
        tq = jnp.minimum(tq, _q(targets["q2"], nxt, na))  # fix #1
    target = jax.lax.stop_gradient(
        rew + gamma * (1.0 - done) * tq)

    def critic_loss_fn(p):
        loss = jnp.mean((_q(p["q1"], obs, act) - target) ** 2)
        if twin_q:
            loss = loss + jnp.mean((_q(p["q2"], obs, act) - target) ** 2)
        return loss

    def actor_loss_fn(p):
        a = _pi(p["actor"], obs)
        return -jnp.mean(_q(jax.lax.stop_gradient(p["q1"]), obs, a))

    c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(params)
    a_loss, a_grads = jax.value_and_grad(actor_loss_fn)(params)
    grads = jax.tree.map(lambda c, a: c + a, c_grads, a_grads)
    updates, opt_state = tx.update(grads, opt_state, params)
    # delayed policy updates (fix #3): gate the APPLIED actor update —
    # zeroing only the gradient would still move the actor off-cycle
    # through the shared Adam's nonzero first moment
    updates = {**updates,
               "actor": jax.tree.map(
                   lambda u: jnp.where(do_policy, u, 0.0),
                   updates["actor"])}
    params = optax.apply_updates(params, updates)
    # polyak targets move only on policy steps (matches the paper)
    targets = jax.tree.map(
        lambda t, o: jnp.where(do_policy, (1 - tau) * t + tau * o, t),
        targets,
        {k: params[k] for k in targets})
    return params, targets, opt_state, {
        "critic_loss": c_loss, "actor_loss": a_loss}


class _TD3RolloutWorker(_ContinuousRolloutWorker):
    """Deterministic policy + Gaussian exploration noise (reference:
    DDPG/TD3 exploration); rollout loop shared with SAC."""

    def __init__(self, env_name, seed: int, expl_noise: float):
        super().__init__(env_name, seed)
        self.expl_noise = expl_noise

    def _act(self, actor_np, obs):
        a = np.tanh(self._mlp_np(actor_np, obs))
        a = a + self.expl_noise * self.rng.standard_normal(a.shape)
        return np.clip(a, -1.0, 1.0)


@dataclass
class TD3Config:
    env: str = "Pendulum-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 128
    lr: float = 1e-3
    gamma: float = 0.99
    tau: float = 0.005
    buffer_capacity: int = 100_000
    learning_starts: int = 500
    train_batch_size: int = 128
    num_updates_per_iter: int = 32
    policy_delay: int = 2
    target_noise: float = 0.2
    noise_clip: float = 0.5
    expl_noise: float = 0.1
    twin_q: bool = True
    hidden: int = 64
    seed: int = 0

    def environment(self, env) -> "TD3Config":
        return replace(self, env=env)

    def rollouts(self, **kw) -> "TD3Config":
        return replace(self, **kw)

    def training(self, **kw) -> "TD3Config":
        return replace(self, **kw)

    def build(self) -> "TD3":
        return TD3(self)


@dataclass
class DDPGConfig(TD3Config):
    """DDPG = TD3 minus its three fixes (reference: ddpg is the base
    TD3 generalizes)."""

    policy_delay: int = 1
    target_noise: float = 0.0
    noise_clip: float = 0.0
    twin_q: bool = False

    def build(self) -> "TD3":
        return DDPG(self)


class TD3:
    def __init__(self, config: TD3Config):
        import jax
        import optax

        self.config = config
        env = make_env(config.env, seed=config.seed)
        if not getattr(env, "continuous", False):
            raise ValueError(f"TD3 requires a continuous-action env, "
                             f"got {config.env!r}")
        self.obs_dim = env.obs_dim
        self.action_dim = env.action_dim
        self.action_low = float(getattr(env, "action_low", -1.0))
        self.action_high = float(getattr(env, "action_high", 1.0))
        self.params = init_td3(jax.random.key(config.seed), self.obs_dim,
                               self.action_dim, config.hidden,
                               twin_q=config.twin_q)
        self.targets = jax.tree.map(lambda x: x, self.params)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.buffer = ReplayBuffer(config.buffer_capacity, self.obs_dim,
                                   action_shape=(self.action_dim,),
                                   action_dtype=np.float32,
                                   gamma=config.gamma)
        self.iteration = 0
        self.update_count = 0
        self.rng = np.random.default_rng(config.seed)
        self.key = jax.random.key(config.seed + 1)
        worker_cls = ray_tpu.remote(_TD3RolloutWorker)
        self.workers = [
            worker_cls.remote(config.env, config.seed + 1000 * (i + 1),
                              config.expl_noise)
            for i in range(config.num_rollout_workers)
        ]
        self._update = jax.jit(partial(
            _td3_update, tx=self.tx, gamma=config.gamma, tau=config.tau,
            target_noise=config.target_noise,
            noise_clip=config.noise_clip, twin_q=config.twin_q))

    def train(self) -> dict:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        actor_np = jax.tree.map(np.asarray, self.params["actor"])
        warmup = self.buffer.size < cfg.learning_starts
        batches = ray_tpu.get([
            w.sample.remote(actor_np, cfg.rollout_fragment_length, warmup)
            for w in self.workers
        ])
        episode_returns = []
        for b in batches:
            episode_returns.extend(b.pop("episode_returns"))
            self.buffer.add_batch(b)

        metrics = {}
        if self.buffer.size >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size, self.rng)
                self.key, sub = jax.random.split(self.key)
                self.update_count += 1
                do_policy = jnp.asarray(
                    self.update_count % cfg.policy_delay == 0)
                (self.params, self.targets, self.opt_state,
                 metrics) = self._update(
                    self.params, self.targets, self.opt_state, mb, sub,
                    do_policy)
            metrics = {k: float(v) for k, v in metrics.items()}
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(episode_returns))
                                    if episode_returns else float("nan")),
            "episodes_this_iter": len(episode_returns),
            "buffer_size": self.buffer.size,
            **metrics,
        }

    def compute_single_action(self, obs) -> np.ndarray:
        import jax.numpy as jnp

        a = np.asarray(_pi(self.params["actor"],
                           jnp.asarray(obs, jnp.float32)[None]))[0]
        return self.action_low + (a + 1.0) * 0.5 * (
            self.action_high - self.action_low)

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass


class DDPG(TD3):
    """DDPG via its TD3 generalization (see DDPGConfig)."""
