"""Off-policy evaluation (OPE) estimators for offline datasets.

Reference analog: ``rllib/offline/estimators/`` — ``ImportanceSampling``
and ``WeightedImportanceSampling`` score a TARGET policy on episodes
collected by a BEHAVIOR policy, without running the target in the env.
Both take per-step action log-probabilities under each policy; episodes
come from the columnar offline dataset (split on ``dones``).
"""

from __future__ import annotations

import numpy as np


def episodes_from_dataset(dataset) -> list[dict]:
    """Split a columnar OfflineDataset (obs/actions/rewards/dones in
    collection order) into per-episode dicts. A trailing partial episode
    (no terminal ``done``) is kept — estimators discount it the same."""
    data = dataset.data if hasattr(dataset, "data") else dataset
    dones = np.asarray(data["dones"]).astype(bool)
    episodes = []
    start = 0
    for i, d in enumerate(dones):
        if d:
            episodes.append({k: np.asarray(v[start:i + 1])
                             for k, v in data.items()})
            start = i + 1
    if start < len(dones):
        episodes.append({k: np.asarray(v[start:])
                         for k, v in data.items()})
    return episodes


def _episode_stats(episodes, target_logp_fn, behavior_logp_fn, gamma):
    returns = []
    log_ratios = []
    for ep in episodes:
        obs = ep["obs"]
        actions = ep["actions"]
        rewards = np.asarray(ep["rewards"], np.float64)
        discounts = gamma ** np.arange(len(rewards))
        returns.append(float(np.sum(discounts * rewards)))
        t = np.asarray(target_logp_fn(obs, actions), np.float64)
        b = np.asarray(behavior_logp_fn(obs, actions), np.float64)
        log_ratios.append(float(np.sum(t - b)))
    return np.asarray(returns), np.asarray(log_ratios)


class ImportanceSampling:
    """Ordinary (unweighted) per-episode importance sampling
    (reference: ``estimators/importance_sampling.py``):
    ``V_target = mean_i( w_i * G_i )`` with
    ``w_i = prod_t pi(a|s) / beta(a|s)``."""

    def __init__(self, gamma: float = 0.99, clip_ratio: float = 1e4):
        self.gamma = gamma
        self.clip_ratio = clip_ratio

    def estimate(self, episodes, target_logp_fn, behavior_logp_fn) -> dict:
        returns, log_ratios = _episode_stats(
            episodes, target_logp_fn, behavior_logp_fn, self.gamma)
        weights = np.clip(np.exp(log_ratios), 0.0, self.clip_ratio)
        return {
            "v_behavior": float(returns.mean()),
            "v_target": float((weights * returns).mean()),
            "mean_weight": float(weights.mean()),
            "num_episodes": len(returns),
        }


class WeightedImportanceSampling(ImportanceSampling):
    """Self-normalized IS (reference:
    ``estimators/weighted_importance_sampling.py``): weights divide by
    their sum — biased but far lower variance on long horizons."""

    def estimate(self, episodes, target_logp_fn, behavior_logp_fn) -> dict:
        returns, log_ratios = _episode_stats(
            episodes, target_logp_fn, behavior_logp_fn, self.gamma)
        weights = np.clip(np.exp(log_ratios), 0.0, self.clip_ratio)
        denom = weights.sum()
        v_target = (float((weights * returns).sum() / denom)
                    if denom > 0 else 0.0)
        return {
            "v_behavior": float(returns.mean()),
            "v_target": v_target,
            "effective_sample_size": (
                float(denom ** 2 / np.maximum((weights ** 2).sum(), 1e-12))),
            "num_episodes": len(returns),
        }
