"""DQN on JAX: epsilon-greedy rollout actors + replay + jitted TD update.

Reference analog: ``rllib/algorithms/dqn/`` (DQN with replay buffer
``rllib/utils/replay_buffers/``, target network updates, double-Q).
TPU-first shape: the Q-network update is one jitted function (batched
MLP matmuls on the MXU); replay stays host-side numpy (it's bandwidth-
light bookkeeping, exactly like the reference keeps it on CPU).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


# The Q-network reuses the shared policy/value MLP from ppo.py (same
# torso; the "pi" head serves as Q values and the value head is unused)
# so MLP fixes live in one place.
from ray_tpu.rllib.ppo import _np_forward, forward_module, init_module


def init_qnet(key, obs_dim: int, n_actions: int, hidden: int = 64,
              num_atoms: int = 1):
    """num_atoms > 1 -> C51 head: the "pi" head emits n_actions *
    num_atoms logits reshaped to per-action distributions."""
    return init_module(key, obs_dim, n_actions * num_atoms, hidden)


def q_forward(params, obs, *, dueling: bool = False):
    logits, value = forward_module(params, obs)
    if dueling:
        # Q = V + A - mean_a A (Wang et al.) — reuses the module's value
        # head as V, the action head as advantages; greedy argmax is
        # unchanged, so rollout workers need no dueling flag
        return value[:, None] + logits - logits.mean(-1, keepdims=True)
    return logits


def dist_forward(params, obs, n_actions: int, num_atoms: int):
    """C51: per-action categorical distributions [B, A, atoms]."""
    import jax

    logits, _ = forward_module(params, obs)
    return jax.nn.softmax(
        logits.reshape(-1, n_actions, num_atoms), axis=-1)


def _np_q(params, obs, num_atoms: int = 1, support=None):
    logits, _ = _np_forward(params, obs)
    if num_atoms > 1:
        z = logits.reshape(len(obs), -1, num_atoms)
        z = np.exp(z - z.max(-1, keepdims=True))
        probs = z / z.sum(-1, keepdims=True)
        return probs @ support          # expected values [B, A]
    return logits


class ReplayBuffer:
    """Uniform ring buffer (reference:
    ``rllib/utils/replay_buffers/replay_buffer.py``). ``action_shape``/
    ``action_dtype`` cover discrete (scalar int) and continuous (vector
    float) action spaces with one implementation."""

    def __init__(self, capacity: int, obs_dim: int,
                 action_shape: tuple = (), action_dtype=np.int32,
                 gamma: float = 0.99):
        self.capacity = capacity
        self.gamma = gamma
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity, *action_shape), action_dtype)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        # per-transition bootstrap factor; always allocated so ring
        # slots can't silently hold stale values when some batches
        # carry "discounts" and others don't
        self.discounts = np.zeros((capacity,), np.float32)
        self.size = 0
        self.pos = 0

    def add_batch(self, batch: dict):
        """Vectorized ring insert: at most two slice assignments per
        field (wraparound)."""
        n = len(batch["obs"])
        if n == 0:
            return
        if n >= self.capacity:  # keep only the newest capacity items
            batch = {k: v[-self.capacity:] for k, v in batch.items()}
            n = self.capacity
        if "discounts" not in batch:
            # derive the 1-step bootstrap factor so every slot is valid
            batch = dict(batch)
            batch["discounts"] = (self.gamma
                                  * (1.0 - batch["dones"])).astype(np.float32)
        fields = [("obs", self.obs), ("next_obs", self.next_obs),
                  ("actions", self.actions), ("rewards", self.rewards),
                  ("dones", self.dones), ("discounts", self.discounts)]
        first = min(n, self.capacity - self.pos)
        for name, dst in fields:
            src = batch[name]
            dst[self.pos:self.pos + first] = src[:first]
            if n > first:
                dst[:n - first] = src[first:]
        self.pos = (self.pos + n) % self.capacity
        self.size = min(self.size + n, self.capacity)

    def sample(self, batch_size: int, rng) -> dict:
        idx = rng.integers(0, self.size, size=batch_size)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx], "rewards": self.rewards[idx],
                "dones": self.dones[idx],
                "discounts": self.discounts[idx]}


class _DQNRolloutWorker:
    def __init__(self, env_name, seed: int, num_atoms: int = 1,
                 support=None):
        self.env = make_env(env_name, seed=seed)
        self.rng = np.random.default_rng(seed)
        self.obs = self.env.reset()
        self.ep_ret = 0.0
        self.num_atoms = num_atoms
        self.support = None if support is None else np.asarray(support)

    def sample(self, params_np: dict, num_steps: int, epsilon: float):
        obs_l, next_l, act_l, rew_l, done_l = [], [], [], [], []
        episode_returns = []
        for _ in range(num_steps):
            if self.rng.random() < epsilon:
                action = int(self.rng.integers(self.env.n_actions))
            else:
                action = int(np.argmax(_np_q(
                    params_np, self.obs[None], self.num_atoms,
                    self.support)[0]))
            next_obs, reward, done, _ = self.env.step(action)
            obs_l.append(self.obs)
            next_l.append(next_obs)
            act_l.append(action)
            rew_l.append(reward)
            done_l.append(float(done))
            self.ep_ret += reward
            if done:
                episode_returns.append(self.ep_ret)
                self.ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {"obs": np.asarray(obs_l, np.float32),
                "next_obs": np.asarray(next_l, np.float32),
                "actions": np.asarray(act_l, np.int32),
                "rewards": np.asarray(rew_l, np.float32),
                "dones": np.asarray(done_l, np.float32),
                "episode_returns": episode_returns}


@dataclass
class DQNConfig:
    env: str = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 128
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_capacity: int = 50_000
    learning_starts: int = 500
    train_batch_size: int = 64
    num_updates_per_iter: int = 32
    target_update_freq: int = 4      # iterations between hard target syncs
    double_q: bool = True
    dueling: bool = False            # Q = V + A - mean(A)
    # C51 distributional Q (Bellemare et al.): num_atoms > 1 switches
    # the head to per-action categorical distributions over
    # [v_min, v_max] with a projected-Bellman cross-entropy loss
    num_atoms: int = 1
    v_min: float = -10.0
    v_max: float = 10.0
    n_step: int = 1                  # n-step return folding before insert
    prioritized_replay: bool = False
    pr_alpha: float = 0.6            # priority exponent
    pr_beta0: float = 0.4            # IS-weight exponent, annealed -> 1
    pr_beta_iters: int = 100
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_iters: int = 30
    hidden: int = 64
    seed: int = 0

    def environment(self, env) -> "DQNConfig":
        return replace(self, env=env)

    def rollouts(self, **kw) -> "DQNConfig":
        return replace(self, **kw)

    def training(self, **kw) -> "DQNConfig":
        return replace(self, **kw)

    def build(self) -> "DQN":
        return DQN(self)


class DQN:
    def __init__(self, config: DQNConfig):
        import jax
        import optax

        self.config = config
        if config.dueling and config.num_atoms > 1:
            raise ValueError("dueling + distributional (C51) is not "
                             "supported together; pick one")
        if config.num_atoms > 1 and config.v_max <= config.v_min:
            raise ValueError(
                f"C51 needs v_max > v_min, got [{config.v_min}, "
                f"{config.v_max}] (a degenerate support trains nothing)")
        env = make_env(config.env, seed=config.seed)
        self.obs_dim = env.obs_dim
        self.n_actions = env.n_actions
        self.support = (np.linspace(config.v_min, config.v_max,
                                    config.num_atoms, dtype=np.float32)
                        if config.num_atoms > 1 else None)
        self.params = init_qnet(jax.random.key(config.seed), self.obs_dim,
                                self.n_actions, config.hidden,
                                config.num_atoms)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        if config.prioritized_replay:
            from ray_tpu.rllib.replay import PrioritizedReplayBuffer

            self.buffer = PrioritizedReplayBuffer(
                config.buffer_capacity, self.obs_dim,
                alpha=config.pr_alpha, gamma=config.gamma)
        else:
            self.buffer = ReplayBuffer(config.buffer_capacity,
                                       self.obs_dim, gamma=config.gamma)
        self.iteration = 0
        self.rng = np.random.default_rng(config.seed)
        worker_cls = ray_tpu.remote(_DQNRolloutWorker)
        self.workers = [
            worker_cls.remote(config.env, config.seed + 1000 * (i + 1),
                              config.num_atoms, self.support)
            for i in range(config.num_rollout_workers)
        ]
        if config.num_atoms > 1:
            self._update = jax.jit(partial(
                _c51_update, tx=self.tx, double_q=config.double_q,
                n_actions=self.n_actions, num_atoms=config.num_atoms,
                v_min=config.v_min, v_max=config.v_max))
        else:
            self._update = jax.jit(partial(
                _dqn_update, tx=self.tx, double_q=config.double_q,
                dueling=config.dueling))

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_start + frac * (cfg.epsilon_end
                                           - cfg.epsilon_start)

    def train(self) -> dict:
        import jax

        cfg = self.config
        params_np = jax.tree.map(np.asarray, self.params)
        eps = self._epsilon()
        batches = ray_tpu.get([
            w.sample.remote(params_np, cfg.rollout_fragment_length, eps)
            for w in self.workers
        ])
        from ray_tpu.rllib.replay import nstep_batch

        episode_returns = []
        for b in batches:
            episode_returns.extend(b.pop("episode_returns"))
            # per-worker batches are time-ordered, which n-step folding
            # needs; discounts carry the bootstrap factor either way
            self.buffer.add_batch(nstep_batch(b, cfg.n_step, cfg.gamma))

        beta = min(1.0, cfg.pr_beta0 + (1.0 - cfg.pr_beta0)
                   * self.iteration / max(1, cfg.pr_beta_iters))
        losses = []
        if self.buffer.size >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                if cfg.prioritized_replay:
                    mb = self.buffer.sample(cfg.train_batch_size,
                                            self.rng, beta=beta)
                    idx = mb.pop("idx")
                else:
                    mb = self.buffer.sample(cfg.train_batch_size,
                                            self.rng)
                    mb["weights"] = np.ones(
                        len(mb["obs"]), np.float32)
                    idx = None
                self.params, self.opt_state, loss, td = self._update(
                    self.params, self.opt_state, self.target_params, mb)
                if idx is not None:
                    self.buffer.update_priorities(idx, np.asarray(td))
                losses.append(float(loss))
        self.iteration += 1
        if self.iteration % cfg.target_update_freq == 0:
            self.target_params = jax.tree.map(lambda x: x, self.params)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(episode_returns))
                                    if episode_returns else 0.0),
            "num_episodes": len(episode_returns),
            "td_loss": float(np.mean(losses)) if losses else None,
            "epsilon": eps,
            "buffer_size": self.buffer.size,
        }

    def compute_action(self, obs) -> int:
        import jax

        params_np = jax.tree.map(np.asarray, self.params)
        return int(np.argmax(_np_q(params_np, np.asarray(obs)[None],
                                   self.config.num_atoms,
                                   self.support)[0]))

    def save(self, path: str):
        import pickle

        import jax

        with open(path, "wb") as f:
            pickle.dump(jax.tree.map(np.asarray, self.params), f)

    def restore(self, path: str):
        import pickle

        import jax

        with open(path, "rb") as f:
            self.params = pickle.load(f)
        self.target_params = jax.tree.map(lambda x: x, self.params)

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass


def _dqn_update(params, opt_state, target_params, batch, *, tx,
                double_q, dueling=False):
    """Weighted TD update. ``batch["discounts"]`` is the bootstrap
    factor (gamma for 1-step, gamma^h with terminal zeroing for n-step);
    ``batch["weights"]`` are IS weights (ones for uniform replay).
    Returns per-sample |TD| for priority refresh."""
    import jax
    import jax.numpy as jnp

    def loss_fn(p):
        q = q_forward(p, batch["obs"], dueling=dueling)
        q_taken = jnp.take_along_axis(
            q, batch["actions"][:, None], axis=1).squeeze(-1)
        q_next_target = q_forward(target_params, batch["next_obs"],
                                  dueling=dueling)
        if double_q:
            # online net selects, target net evaluates
            sel = jnp.argmax(
                q_forward(p, batch["next_obs"], dueling=dueling), axis=-1)
            next_q = jnp.take_along_axis(
                q_next_target, sel[:, None], axis=1).squeeze(-1)
        else:
            next_q = jnp.max(q_next_target, axis=-1)
        target = batch["rewards"] + batch["discounts"] * \
            jax.lax.stop_gradient(next_q)
        td = q_taken - target
        return jnp.mean(batch["weights"] * td ** 2), jnp.abs(td)

    (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, loss, td


def _c51_update(params, opt_state, target_params, batch, *, tx, double_q,
                n_actions, num_atoms, v_min, v_max):
    """C51 projected-Bellman update (Bellemare et al. 2017): the target
    distribution Tz = clip(r + discount * z) is projected onto the fixed
    support and the loss is categorical cross entropy against the online
    distribution of the taken action. ``discounts`` already carries
    terminal zeroing and n-step gamma^h, so termination collapses Tz to
    a point mass at the (clipped) reward for free. Returns per-sample
    cross entropy as the priority signal."""
    import jax
    import jax.numpy as jnp

    support = jnp.linspace(v_min, v_max, num_atoms)
    delta = (v_max - v_min) / (num_atoms - 1)

    def loss_fn(p):
        # next-state distribution of the greedy action
        next_target = dist_forward(target_params, batch["next_obs"],
                                   n_actions, num_atoms)      # [B, A, Z]
        ev_target = next_target @ support                     # [B, A]
        if double_q:
            next_online = dist_forward(p, batch["next_obs"],
                                       n_actions, num_atoms)
            sel = jnp.argmax(next_online @ support, axis=-1)
        else:
            sel = jnp.argmax(ev_target, axis=-1)
        p_next = jnp.take_along_axis(
            next_target, sel[:, None, None], axis=1).squeeze(1)  # [B, Z]

        # project Tz onto the support
        tz = jnp.clip(batch["rewards"][:, None]
                      + batch["discounts"][:, None] * support[None, :],
                      v_min, v_max)                           # [B, Z]
        b = (tz - v_min) / delta
        lo = jnp.floor(b).astype(jnp.int32)
        hi = jnp.ceil(b).astype(jnp.int32)
        # when b is integral lo == hi: give it full mass via the lo term
        w_hi = b - lo
        w_lo = 1.0 - w_hi
        atoms = jnp.arange(num_atoms)
        # m[k] = sum_j p_next[j] * (w_lo[j]·[lo_j==k] + w_hi[j]·[hi_j==k])
        m = (jnp.where(lo[:, :, None] == atoms[None, None, :],
                       (p_next * w_lo)[:, :, None], 0.0).sum(1)
             + jnp.where(hi[:, :, None] == atoms[None, None, :],
                         (p_next * w_hi)[:, :, None], 0.0).sum(1))
        m = jax.lax.stop_gradient(m)

        online = dist_forward(p, batch["obs"], n_actions, num_atoms)
        p_taken = jnp.take_along_axis(
            online, batch["actions"][:, None, None], axis=1).squeeze(1)
        xent = -(m * jnp.log(p_taken + 1e-8)).sum(-1)         # [B]
        return jnp.mean(batch["weights"] * xent), xent

    (loss, xent), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, loss, xent
