"""APPO on JAX: IMPALA's async architecture + PPO's clipped surrogate.

Reference analog: ``rllib/algorithms/appo/`` — asynchronous PPO keeps
IMPALA's decoupled rollout workers and V-trace off-policy correction but
replaces the plain policy-gradient term with the PPO clip objective,
which bounds how far one update can move the policy from the behavior
policy that collected the data.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig


@dataclass
class APPOConfig(IMPALAConfig):
    clip_param: float | None = 0.3

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    def __init__(self, config):
        if config.clip_param is None:   # field lives on IMPALAConfig
            config = replace(config, clip_param=0.3)
        super().__init__(config)
