"""PPO on JAX: rollout-worker actors + jitted learner.

Reference analog: the new-stack triad — ``RLModule``
(rllib/core/rl_module/rl_module.py:229) → here a functional MLP
policy+value; ``EnvRunner``/``RolloutWorker`` (rollout_worker.py:159,
sample:660) → ``_RolloutWorker`` actors collecting episodes with broadcast
params; ``Learner`` (rllib/core/learner/learner.py:229, update:1230) →
one jitted GAE + clipped-surrogate update (shardable over a mesh: batch
axis is data-parallel; the MXU sees fused MLP matmuls).

Config follows the ``AlgorithmConfig`` builder style
(``PPOConfig().environment(...).training(...)``).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


# ---------------------------------------------------------------------------
# RLModule: functional MLP policy + value heads
# ---------------------------------------------------------------------------

def init_module(key, obs_dim: int, n_actions: int, hidden: int = 64):
    import jax

    k1, k2, k3, k4 = jax.random.split(key, 4)

    def dense(k, fan_in, fan_out):
        scale = (2.0 / fan_in) ** 0.5
        return {"w": jax.random.normal(k, (fan_in, fan_out)) * scale,
                "b": jax.numpy.zeros((fan_out,))}

    return {
        "torso1": dense(k1, obs_dim, hidden),
        "torso2": dense(k2, hidden, hidden),
        "pi": dense(k3, hidden, n_actions),
        "vf": dense(k4, hidden, 1),
    }


def forward_module(params, obs):
    import jax.numpy as jnp

    h = jnp.tanh(obs @ params["torso1"]["w"] + params["torso1"]["b"])
    h = jnp.tanh(h @ params["torso2"]["w"] + params["torso2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"]).squeeze(-1)
    return logits, value


# ---------------------------------------------------------------------------
# Rollout workers (actors)
# ---------------------------------------------------------------------------

class _RolloutWorker:
    """VECTORIZED env runner (reference: ``EnvRunner`` over vectorized
    envs, rllib/env/env_runner.py:9): ``num_envs`` environments step in
    lockstep with ONE batched policy forward per step — the per-step
    numpy matmul amortizes over the env batch instead of running once
    per environment (the round-3 one-env-per-forward weakness)."""

    def __init__(self, env_name, seed: int, num_envs: int = 1):
        self.envs = [make_env(env_name, seed=seed + i)
                     for i in range(num_envs)]
        self.rng = np.random.default_rng(seed)
        self.num_envs = num_envs

    def sample(self, params_np: dict, num_steps: int, gamma: float,
               lam: float):
        """Collect num_steps transitions PER ENV; returns a flat numpy
        batch (n_envs * num_steps rows) with GAE advantages computed
        env-side (cheap, host-bound anyway)."""
        ne = self.num_envs
        obs = np.stack([e.reset() for e in self.envs])      # [E, obs]
        obs_l, act_l, logp_l, rew_l, val_l, done_l = ([] for _ in range(6))
        episode_returns = []
        ep_ret = np.zeros(ne)
        for _ in range(num_steps):
            logits, values = _np_forward(params_np, obs)    # [E, A], [E]
            probs = _softmax_rows(logits)
            actions = _sample_actions(self.rng, probs)
            obs_l.append(obs.copy())
            act_l.append(actions)
            logp_l.append(np.log(
                probs[np.arange(ne), actions] + 1e-8))
            val_l.append(values)
            step_rew = np.zeros(ne)
            step_done = np.zeros(ne, bool)
            next_obs = obs.copy()
            for i, env in enumerate(self.envs):
                o, r, d, _ = env.step(int(actions[i]))
                step_rew[i] = r
                step_done[i] = d
                ep_ret[i] += r
                if d:
                    episode_returns.append(float(ep_ret[i]))
                    ep_ret[i] = 0.0
                    o = env.reset()
                next_obs[i] = o
            rew_l.append(step_rew)
            done_l.append(step_done.astype(np.float32))
            obs = next_obs
        _, last_vals = _np_forward(params_np, obs)          # [E]
        # per-env GAE over the time axis
        rews = np.stack(rew_l)                              # [T, E]
        vals = np.stack(val_l)
        dones = np.stack(done_l)
        adv = np.zeros_like(rews)
        ret = np.zeros_like(rews)
        for i in range(ne):
            a, r = _gae(rews[:, i], vals[:, i], dones[:, i],
                        float(last_vals[i]), gamma, lam)
            adv[:, i] = a
            ret[:, i] = r
        return {
            "obs": np.stack(obs_l).reshape(-1, obs.shape[-1]).astype(
                np.float32),
            "actions": np.stack(act_l).reshape(-1).astype(np.int32),
            "logp": np.stack(logp_l).reshape(-1).astype(np.float32),
            "advantages": adv.reshape(-1).astype(np.float32),
            "returns": ret.reshape(-1).astype(np.float32),
            "episode_returns": episode_returns,
        }


def _np_forward(params, obs):
    h = np.tanh(obs @ params["torso1"]["w"] + params["torso1"]["b"])
    h = np.tanh(h @ params["torso2"]["w"] + params["torso2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"]).squeeze(-1)
    return logits, value


def _softmax(x):
    e = np.exp(x - x.max())
    return e / e.sum()


def _softmax_rows(x):
    e = np.exp(x - x.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def _sample_actions(rng, probs) -> np.ndarray:
    """Vectorized categorical sampling (inverse CDF per row). Raises on
    non-finite probabilities like ``Generator.choice`` would — silent
    action-0 fallback would mask a diverged policy."""
    if not np.all(np.isfinite(probs)):
        raise ValueError("policy produced non-finite action probabilities "
                         "(diverged parameters?)")
    u = rng.random((probs.shape[0], 1))
    actions = (probs.cumsum(axis=1) < u).sum(axis=1)
    return np.minimum(actions, probs.shape[1] - 1)


def _gae(rewards, values, dones, last_value, gamma, lam):
    n = len(rewards)
    adv = np.zeros(n)
    last_gae = 0.0
    next_value = last_value
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - float(dones[t])
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    return adv, adv + values


# ---------------------------------------------------------------------------
# Config + Algorithm
# ---------------------------------------------------------------------------

@dataclass
class PPOConfig:
    env: str = "CartPole-v1"
    num_rollout_workers: int = 2
    # envs stepped in lockstep per worker (one batched forward per step)
    num_envs_per_worker: int = 1
    rollout_fragment_length: int = 256
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip_eps: float = 0.2
    entropy_coeff: float = 0.01
    vf_coeff: float = 0.5
    num_sgd_iter: int = 4
    minibatch_size: int = 128
    hidden: int = 64
    seed: int = 0
    # multi-learner plane (reference: LearnerGroup learner_group.py:61):
    # 0 = single in-process jit; >=1 = LearnerGroup with that many
    # data-parallel learners ("mesh": dp shards of one jit over a device
    # mesh; "actors": learner actors w/ collective grad averaging)
    num_learners: int = 0
    learner_mode: str = "mesh"

    def environment(self, env) -> "PPOConfig":
        return replace(self, env=env)

    def rollouts(self, *, num_rollout_workers=None,
                 rollout_fragment_length=None) -> "PPOConfig":
        cfg = self
        if num_rollout_workers is not None:
            cfg = replace(cfg, num_rollout_workers=num_rollout_workers)
        if rollout_fragment_length is not None:
            cfg = replace(cfg,
                          rollout_fragment_length=rollout_fragment_length)
        return cfg

    def training(self, **kw) -> "PPOConfig":
        return replace(self, **kw)

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """Algorithm driver (reference: ``Algorithm.step:815`` →
    ``training_step:1402`` = sample from rollout workers + learner
    update)."""

    def __init__(self, config: PPOConfig):
        import jax
        import optax

        self.config = config
        env = make_env(config.env, seed=config.seed)
        self.obs_dim = env.obs_dim
        self.n_actions = env.n_actions
        self.tx = optax.adam(config.lr)
        self.iteration = 0
        worker_cls = ray_tpu.remote(_RolloutWorker)
        self.workers = [
            worker_cls.remote(config.env, config.seed + 1000 * (i + 1),
                              config.num_envs_per_worker)
            for i in range(config.num_rollout_workers)
        ]
        grad_fn = partial(_ppo_grads, clip_eps=config.clip_eps,
                          entropy_coeff=config.entropy_coeff,
                          vf_coeff=config.vf_coeff)
        if config.num_learners > 0:
            from ray_tpu.rllib.learner_group import LearnerGroup

            # bind plain ints — a lambda over `self` would cloudpickle
            # the whole algorithm (rollout ActorHandles included) into
            # every learner actor's ctor blob
            obs_dim, n_actions, hidden = (self.obs_dim, self.n_actions,
                                          config.hidden)
            self.learners = LearnerGroup(
                init_fn=lambda key: init_module(
                    key, obs_dim, n_actions, hidden),
                grad_fn=grad_fn, tx=self.tx,
                num_learners=config.num_learners,
                mode=config.learner_mode, seed=config.seed)
            self.params = None
            self.opt_state = None
        else:
            self.learners = None
            self.params = init_module(jax.random.key(config.seed),
                                      self.obs_dim, self.n_actions,
                                      config.hidden)
            self.opt_state = self.tx.init(self.params)
            self._update = jax.jit(partial(
                _ppo_update, tx=self.tx, clip_eps=config.clip_eps,
                entropy_coeff=config.entropy_coeff,
                vf_coeff=config.vf_coeff))

    def _params_np(self):
        import jax

        if self.learners is not None:
            return self.learners.get_params()
        return jax.tree.map(np.asarray, self.params)

    def train(self) -> dict:
        import numpy as np

        cfg = self.config
        params_np = self._params_np()
        batches = ray_tpu.get([
            w.sample.remote(params_np, cfg.rollout_fragment_length,
                            cfg.gamma, cfg.lam)
            for w in self.workers
        ])
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in ("obs", "actions", "logp", "advantages",
                           "returns")}
        episode_returns = [r for b in batches for r in b["episode_returns"]]
        # advantage normalization (standard PPO practice)
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)

        n = len(batch["obs"])
        rng = np.random.default_rng(cfg.seed + self.iteration)
        losses = []
        for _ in range(cfg.num_sgd_iter):
            perm = rng.permutation(n)
            for start in range(0, n, cfg.minibatch_size):
                idx = perm[start:start + cfg.minibatch_size]
                mb = {k: v[idx] for k, v in batch.items()}
                if self.learners is not None:
                    losses.append(self.learners.update(mb))
                else:
                    self.params, self.opt_state, stats = self._update(
                        self.params, self.opt_state, mb)
                    losses.append(stats)
        self.iteration += 1
        mean = lambda key: float(np.mean([float(s[key]) for s in losses]))  # noqa: E731
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(episode_returns))
                                    if episode_returns else 0.0),
            "num_episodes": len(episode_returns),
            "policy_loss": mean("policy_loss"),
            "vf_loss": mean("vf_loss"),
            "entropy": mean("entropy"),
            "num_env_steps_sampled": n,
        }

    def save(self, path: str):
        import pickle

        with open(path, "wb") as f:
            pickle.dump(self._params_np(), f)

    def restore(self, path: str):
        import pickle

        with open(path, "rb") as f:
            params = pickle.load(f)
        if self.learners is not None:
            self.learners.set_params(params)
        else:
            self.params = params

    def compute_action(self, obs) -> int:
        import numpy as np

        logits, _ = _np_forward(self._params_np(), np.asarray(obs)[None])
        return int(np.argmax(logits[0]))

    def stop(self):
        if self.learners is not None:
            self.learners.stop()
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass


def _ppo_grads(params, batch, *, clip_eps, entropy_coeff, vf_coeff):
    """Pure gradient fn (the ``Learner.compute_gradients`` analog,
    learner.py:1230): under a dp-sharded batch the mean-loss grad is
    the global average — XLA inserts the psum."""
    import jax
    import jax.numpy as jnp

    def loss_fn(p):
        logits, values = forward_module(p, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1).squeeze(-1)
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        unclipped = ratio * adv
        clipped = jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv
        policy_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
        vf_loss = jnp.mean((values - batch["returns"]) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = (policy_loss + vf_coeff * vf_loss
                 - entropy_coeff * entropy)
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    return grads, stats


def _ppo_update(params, opt_state, batch, *, tx, clip_eps, entropy_coeff,
                vf_coeff):
    import jax

    grads, stats = _ppo_grads(params, batch, clip_eps=clip_eps,
                              entropy_coeff=entropy_coeff,
                              vf_coeff=vf_coeff)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = jax.tree.map(lambda p, u: p + u, params, updates)
    return params, opt_state, stats
