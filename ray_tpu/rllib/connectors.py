"""Connector pipelines: obs/action transformations between env and policy.

Reference analog: ``rllib/connectors/`` — env-to-module connectors
preprocess observations on the way INTO the policy (flatten, running
normalization, frame stacking) and module-to-env connectors postprocess
actions on the way OUT (clip, unsquash). Pipelines are stateful (running
stats, stacked frames), serializable (``state_dict``/``load_state``) so
learned preprocessing travels with checkpoints, and composable.

``ConnectorEnv`` wraps any registry/gymnasium env with a pipeline pair,
so every algorithm gains connectors through its existing ``env`` config
field: ``PPOConfig(env=lambda seed=None: ConnectorEnv("CartPole",
obs_connectors=[NormalizeObs()], seed=seed))``.
"""

from __future__ import annotations

import numpy as np


class Connector:
    """One transformation stage. Override ``__call__``; optionally
    ``state_dict``/``load_state`` for learned/stateful stages and
    ``reset`` for per-episode state."""

    def __call__(self, x):
        raise NotImplementedError

    def reset(self):
        pass

    def state_dict(self) -> dict:
        return {}

    def load_state(self, state: dict):
        pass


class ConnectorPipeline(Connector):
    def __init__(self, connectors: list[Connector] | None = None):
        self.connectors = list(connectors or [])

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def reset(self):
        for c in self.connectors:
            c.reset()

    def state_dict(self) -> dict:
        return {str(i): c.state_dict()
                for i, c in enumerate(self.connectors)}

    def load_state(self, state: dict):
        for i, c in enumerate(self.connectors):
            if str(i) in state:
                c.load_state(state[str(i)])


# ---------------------------------------------------------------------------
# env-to-module (observation) connectors
# ---------------------------------------------------------------------------

class FlattenObs(Connector):
    """Any-shaped observation -> 1-D float32 vector (reference:
    ``connectors/env_to_module/flatten_observations.py``)."""

    def __call__(self, obs):
        return np.asarray(obs, np.float32).reshape(-1)


class NormalizeObs(Connector):
    """Running mean/std normalization (reference:
    ``mean_std_filter.py`` — the classic MeanStdFilter). Welford
    accumulation; stats persist via state_dict."""

    def __init__(self, clip: float = 10.0, epsilon: float = 1e-8):
        self.clip = clip
        self.epsilon = epsilon
        self.count = 0
        self.mean: np.ndarray | None = None
        self.m2: np.ndarray | None = None
        self.frozen = False   # eval mode: apply stats, stop updating

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        if self.mean is None:
            self.mean = np.zeros_like(obs)
            self.m2 = np.zeros_like(obs)
        if not self.frozen:
            self.count += 1
            delta = obs - self.mean
            self.mean = self.mean + delta / self.count
            self.m2 = self.m2 + delta * (obs - self.mean)
        var = (self.m2 / max(self.count - 1, 1)
               if self.count > 1 else np.ones_like(obs))
        out = (obs - self.mean) / np.sqrt(var + self.epsilon)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def state_dict(self) -> dict:
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def load_state(self, state: dict):
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


class FrameStack(Connector):
    """Stack the last k observations along a new leading axis
    (reference: ``frame_stacking.py``). reset() clears the deque at
    episode boundaries; short episodes left-pad with the first frame."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames: list = []

    def __call__(self, obs):
        obs = np.asarray(obs, np.float32)
        if not self._frames:
            self._frames = [obs] * self.k
        else:
            self._frames = self._frames[1:] + [obs]
        return np.stack(self._frames)

    def reset(self):
        self._frames = []


# ---------------------------------------------------------------------------
# module-to-env (action) connectors
# ---------------------------------------------------------------------------

class ClipActions(Connector):
    """Clip continuous actions into [low, high] (reference:
    ``module_to_env/clip_actions`` option)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, action):
        return np.clip(np.asarray(action, np.float32),
                       self.low, self.high)


class UnsquashActions(Connector):
    """Map tanh-space actions in [-1, 1] to [low, high] (reference:
    ``normalize_actions``/unsquash option)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, action):
        a = np.asarray(action, np.float32)
        return self.low + (np.clip(a, -1.0, 1.0) + 1.0) * 0.5 \
            * (self.high - self.low)


# ---------------------------------------------------------------------------
# env wrapper
# ---------------------------------------------------------------------------

class ConnectorEnv:
    """Wrap an env with obs/action connector pipelines; algorithms use
    it through their ``env`` field (any callable accepting ``seed=``)."""

    def __init__(self, env_or_name, *, obs_connectors=None,
                 action_connectors=None, seed=None):
        from ray_tpu.rllib.env import make_env

        # a CLASS has a .step attribute too — only an INSTANCE is used
        # as-is; names/classes/factories go through make_env
        if (isinstance(env_or_name, (str, type))
                or not hasattr(env_or_name, "step")):
            self.env = make_env(env_or_name, seed=seed)
        else:
            self.env = env_or_name
        self.obs_pipeline = ConnectorPipeline(obs_connectors)
        self.action_pipeline = ConnectorPipeline(action_connectors)

    def __getattr__(self, name):
        return getattr(self.env, name)

    def reset(self):
        self.obs_pipeline.reset()
        self.action_pipeline.reset()
        return self.obs_pipeline(self.env.reset())

    def step(self, action):
        obs, reward, done, info = self.env.step(
            self.action_pipeline(action))
        return self.obs_pipeline(obs), reward, done, info

    def state_dict(self) -> dict:
        return {"obs": self.obs_pipeline.state_dict(),
                "action": self.action_pipeline.state_dict()}

    def load_state(self, state: dict):
        self.obs_pipeline.load_state(state.get("obs", {}))
        self.action_pipeline.load_state(state.get("action", {}))
