"""SAC on JAX: continuous control with squashed-Gaussian actor, twin Q
critics, soft target updates, and auto-tuned entropy temperature.

Reference analog: ``rllib/algorithms/sac/`` (SAC with twin Q networks,
target entropy = -|A|, replay buffer). TPU-first shape: the entire update
(actor + both critics + alpha) is ONE jitted function of stacked batches —
small MLP matmuls fuse on the MXU; replay stays host-side numpy like the
reference keeps it on CPU.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import numpy as np

import ray_tpu
from ray_tpu.rllib.dqn import ReplayBuffer
from ray_tpu.rllib.env import make_env

LOG_STD_MIN, LOG_STD_MAX = -10.0, 2.0


# ---------------------------------------------------------------------------
# networks (pure-functional MLPs, kept local — actor outputs (mu, log_std),
# critics take [obs, action] and output one scalar)
# ---------------------------------------------------------------------------

def _init_mlp(key, sizes):
    import jax

    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, (n_in, n_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.normal(k, (n_in, n_out)) * (n_in ** -0.5)
        params.append({"w": w, "b": np.zeros((n_out,), np.float32)})
    return params


def _mlp(params, x):
    import jax.numpy as jnp

    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


def init_sac(key, obs_dim: int, action_dim: int, hidden: int = 64):
    import jax

    ka, k1, k2 = jax.random.split(key, 3)
    return {
        "actor": _init_mlp(ka, (obs_dim, hidden, hidden, 2 * action_dim)),
        "q1": _init_mlp(k1, (obs_dim + action_dim, hidden, hidden, 1)),
        "q2": _init_mlp(k2, (obs_dim + action_dim, hidden, hidden, 1)),
        "log_alpha": np.zeros((), np.float32),
    }


def _actor_dist(actor_params, obs):
    import jax.numpy as jnp

    out = _mlp(actor_params, obs)
    mu, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    return mu, log_std


def _sample_action(actor_params, obs, key):
    """Squashed-Gaussian sample + its log-prob (tanh correction)."""
    import jax
    import jax.numpy as jnp

    mu, log_std = _actor_dist(actor_params, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mu.shape)
    pre = mu + std * eps
    a = jnp.tanh(pre)
    logp = jnp.sum(
        -0.5 * (eps**2 + 2 * log_std + jnp.log(2 * jnp.pi))
        - jnp.log(1 - a**2 + 1e-6),
        axis=-1,
    )
    return a, logp


def _q(params, obs, act):
    import jax.numpy as jnp

    x = jnp.concatenate([obs, act], axis=-1)
    return _mlp(params, x)[..., 0]


def _sac_update(params, target_q, opt_state, batch, key, *, tx, gamma, tau,
                target_entropy):
    """One SAC step: critics -> actor -> temperature, then polyak targets."""
    import jax
    import jax.numpy as jnp
    import optax

    obs, act = batch["obs"], batch["actions"]
    rew, nxt, done = batch["rewards"], batch["next_obs"], batch["dones"]
    k1, k2 = jax.random.split(key)
    alpha = jnp.exp(params["log_alpha"])

    # target: r + gamma * (min Q_target(s', a') - alpha * logp(a'))
    na, nlogp = _sample_action(params["actor"], nxt, k1)
    tq = jnp.minimum(_q(target_q["q1"], nxt, na),
                     _q(target_q["q2"], nxt, na))
    target = rew + gamma * (1.0 - done) * (tq - alpha * nlogp)
    target = jax.lax.stop_gradient(target)

    def loss_fn(p):
        q1 = _q(p["q1"], obs, act)
        q2 = _q(p["q2"], obs, act)
        critic_loss = jnp.mean((q1 - target) ** 2) \
            + jnp.mean((q2 - target) ** 2)
        a_new, logp = _sample_action(p["actor"], obs, k2)
        q_new = jnp.minimum(
            _q(jax.lax.stop_gradient(p["q1"]), obs, a_new),
            _q(jax.lax.stop_gradient(p["q2"]), obs, a_new))
        actor_loss = jnp.mean(
            jnp.exp(jax.lax.stop_gradient(p["log_alpha"])) * logp - q_new)
        alpha_loss = -p["log_alpha"] * jnp.mean(
            jax.lax.stop_gradient(logp) + target_entropy)
        total = critic_loss + actor_loss + alpha_loss
        return total, {"critic_loss": critic_loss,
                       "actor_loss": actor_loss,
                       "alpha": jnp.exp(p["log_alpha"]),
                       "entropy": -jnp.mean(logp)}

    grads, metrics = jax.grad(loss_fn, has_aux=True)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    target_q = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                            target_q,
                            {"q1": params["q1"], "q2": params["q2"]})
    return params, target_q, opt_state, metrics


class _ContinuousRolloutWorker:
    """Shared rollout actor for the continuous-control algorithms (SAC,
    TD3/DDPG): env stepping, warmup random actions, episode bookkeeping,
    action scaling. Subclasses supply ``_act`` (the numpy policy — the
    rollout actors stay jax-free)."""

    def __init__(self, env_name, seed: int):
        self.env = make_env(env_name, seed=seed)
        self.rng = np.random.default_rng(seed)
        self.obs = self.env.reset()
        self.ep_ret = 0.0
        self.low = float(getattr(self.env, "action_low", -1.0))
        self.high = float(getattr(self.env, "action_high", 1.0))

    def _act(self, actor_np, obs):
        raise NotImplementedError

    def _mlp_np(self, actor_np, obs):
        x = obs[None]
        for i, layer in enumerate(actor_np):
            x = x @ layer["w"] + layer["b"]
            if i < len(actor_np) - 1:
                x = np.tanh(x)
        return x[0]

    def _scale(self, a):
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)

    def sample(self, actor_np, num_steps: int, random_actions: bool):
        obs_l, next_l, act_l, rew_l, done_l = [], [], [], [], []
        episode_returns = []
        for _ in range(num_steps):
            if random_actions:
                a = self.rng.uniform(-1.0, 1.0,
                                     size=self.env.action_dim)
            else:
                a = self._act(actor_np, self.obs)
            next_obs, reward, done, _ = self.env.step(self._scale(a))
            obs_l.append(self.obs)
            next_l.append(next_obs)
            act_l.append(np.asarray(a, np.float32))
            rew_l.append(reward)
            done_l.append(float(done))
            self.ep_ret += reward
            if done:
                episode_returns.append(self.ep_ret)
                self.ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {"obs": np.asarray(obs_l, np.float32),
                "next_obs": np.asarray(next_l, np.float32),
                "actions": np.asarray(act_l, np.float32),
                "rewards": np.asarray(rew_l, np.float32),
                "dones": np.asarray(done_l, np.float32),
                "episode_returns": episode_returns}


class _SACRolloutWorker(_ContinuousRolloutWorker):
    def _act(self, actor_np, obs):
        # numpy mirror of _sample_action
        mu, log_std = np.split(self._mlp_np(actor_np, obs), 2)
        std = np.exp(np.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
        return np.tanh(mu + std * self.rng.standard_normal(mu.shape))


@dataclass
class SACConfig:
    env: str = "Pendulum-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005              # polyak target rate
    buffer_capacity: int = 100_000
    learning_starts: int = 500
    train_batch_size: int = 128
    num_updates_per_iter: int = 32
    target_entropy: float | None = None   # default -action_dim
    hidden: int = 64
    seed: int = 0

    def environment(self, env) -> "SACConfig":
        return replace(self, env=env)

    def rollouts(self, **kw) -> "SACConfig":
        return replace(self, **kw)

    def training(self, **kw) -> "SACConfig":
        return replace(self, **kw)

    def build(self) -> "SAC":
        return SAC(self)


class SAC:
    def __init__(self, config: SACConfig):
        import jax
        import optax

        self.config = config
        env = make_env(config.env, seed=config.seed)
        if not getattr(env, "continuous", False):
            raise ValueError(f"SAC requires a continuous-action env, "
                             f"got {config.env!r}")
        self.obs_dim = env.obs_dim
        self.action_dim = env.action_dim
        self.action_low = float(getattr(env, "action_low", -1.0))
        self.action_high = float(getattr(env, "action_high", 1.0))
        self.params = init_sac(jax.random.key(config.seed), self.obs_dim,
                               self.action_dim, config.hidden)
        self.target_q = jax.tree.map(
            lambda x: x, {"q1": self.params["q1"], "q2": self.params["q2"]})
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.buffer = ReplayBuffer(config.buffer_capacity, self.obs_dim,
                                   action_shape=(self.action_dim,),
                                   action_dtype=np.float32,
                                   gamma=config.gamma)
        self.iteration = 0
        self.rng = np.random.default_rng(config.seed)
        self.key = jax.random.key(config.seed + 1)
        te = (config.target_entropy if config.target_entropy is not None
              else -float(self.action_dim))
        worker_cls = ray_tpu.remote(_SACRolloutWorker)
        self.workers = [
            worker_cls.remote(config.env, config.seed + 1000 * (i + 1))
            for i in range(config.num_rollout_workers)
        ]
        self._update = jax.jit(partial(
            _sac_update, tx=self.tx, gamma=config.gamma, tau=config.tau,
            target_entropy=te))

    def train(self) -> dict:
        import jax

        cfg = self.config
        actor_np = jax.tree.map(np.asarray, self.params["actor"])
        warmup = self.buffer.size < cfg.learning_starts
        batches = ray_tpu.get([
            w.sample.remote(actor_np, cfg.rollout_fragment_length, warmup)
            for w in self.workers
        ])
        episode_returns = []
        for b in batches:
            episode_returns.extend(b.pop("episode_returns"))
            self.buffer.add_batch(b)

        metrics = {}
        if self.buffer.size >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size, self.rng)
                self.key, sub = jax.random.split(self.key)
                (self.params, self.target_q, self.opt_state,
                 metrics) = self._update(
                    self.params, self.target_q, self.opt_state, mb, sub)
            metrics = {k: float(v) for k, v in metrics.items()}
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(episode_returns))
                                    if episode_returns else float("nan")),
            "episodes_this_iter": len(episode_returns),
            "buffer_size": self.buffer.size,
            **metrics,
        }

    def compute_single_action(self, obs) -> np.ndarray:
        """Deterministic (mean) action for evaluation."""
        import jax
        import jax.numpy as jnp

        mu, _ = _actor_dist(self.params["actor"],
                            jnp.asarray(obs, jnp.float32)[None])
        a = np.tanh(np.asarray(mu)[0])
        return self.action_low + (a + 1.0) * 0.5 * (
            self.action_high - self.action_low)

    def stop(self):
        for w in self.workers:
            ray_tpu.kill(w)
