"""Environment API + builtin envs.

Reference analog: ``rllib/env/env_runner.py:9`` ``EnvRunner`` environments
(gym API). Numpy-only (no gym dependency): ``reset() -> obs``,
``step(action) -> (obs, reward, done, info)``.
"""

from __future__ import annotations

import numpy as np


class CartPole:
    """Classic control CartPole-v1 dynamics (numpy re-implementation of
    the standard equations; episode cap 500)."""

    obs_dim = 4
    n_actions = 2

    def __init__(self, seed: int | None = None):
        self.rng = np.random.default_rng(seed)
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.total_mass = self.masspole + self.masscart
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.max_steps = 500
        self.state = None
        self.steps = 0

    def reset(self):
        self.state = self.rng.uniform(-0.05, 0.05, size=4)
        self.steps = 0
        self.truncated = False
        return self.state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.polemass_length * theta_dot**2 * sintheta
                ) / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2
                           / self.total_mass))
        xacc = temp - self.polemass_length * thetaacc * costheta \
            / self.total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.steps += 1
        failed = bool(abs(x) > self.x_threshold
                      or abs(theta) > self.theta_threshold)
        done = failed or self.steps >= self.max_steps
        # time-limit ends are TRUNCATIONS, not terminations — consumers
        # that bootstrap values past episode ends (DreamerV3's continue
        # head) must distinguish the two
        self.truncated = bool(done and not failed)
        return self.state.astype(np.float32), 1.0, done, {}


class BanditEnv:
    """One-step contextual bandit (deterministic learning signal for
    tests): obs in {-1,+1}^dim; action matching sign of obs[0] pays 1."""

    obs_dim = 2
    n_actions = 2

    def __init__(self, seed: int | None = None):
        self.rng = np.random.default_rng(seed)
        self.obs = None

    def reset(self):
        self.obs = self.rng.choice([-1.0, 1.0], size=2).astype(np.float32)
        return self.obs

    def step(self, action: int):
        reward = 1.0 if (self.obs[0] > 0) == (action == 1) else 0.0
        obs = self.reset()
        return obs, reward, True, {}


class Pendulum:
    """Classic control Pendulum-v1 dynamics (continuous torque in
    [-2, 2]; reward is negative cost of angle/velocity/effort)."""

    obs_dim = 3
    action_dim = 1
    action_low = -2.0
    action_high = 2.0
    continuous = True

    def __init__(self, seed: int | None = None):
        self.rng = np.random.default_rng(seed)
        self.max_speed = 8.0
        self.dt = 0.05
        self.g = 10.0
        self.m = 1.0
        self.length = 1.0
        self.max_steps = 200
        self.state = None
        self.steps = 0

    def _obs(self):
        th, thdot = self.state
        return np.array([np.cos(th), np.sin(th), thdot], dtype=np.float32)

    def reset(self):
        self.state = self.rng.uniform([-np.pi, -1.0], [np.pi, 1.0])
        self.steps = 0
        return self._obs()

    def step(self, action):
        th, thdot = self.state
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -2.0, 2.0))
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot = thdot + (3 * self.g / (2 * self.length) * np.sin(th)
                         + 3.0 / (self.m * self.length**2) * u) * self.dt
        thdot = float(np.clip(thdot, -self.max_speed, self.max_speed))
        th = th + thdot * self.dt
        self.state = np.array([th, thdot])
        self.steps += 1
        done = self.steps >= self.max_steps
        return self._obs(), -float(cost), done, {}


class ContinuousBandit:
    """One-step continuous-action env with a deterministic optimum
    (reward = -(a - 0.5)^2): fast, non-flaky learning signal for
    continuous-control tests."""

    obs_dim = 1
    action_dim = 1
    action_low = -1.0
    action_high = 1.0
    continuous = True
    target = 0.5

    def __init__(self, seed: int | None = None):
        self.rng = np.random.default_rng(seed)

    def reset(self):
        return np.zeros(1, dtype=np.float32)

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1.0, 1.0))
        reward = -(a - self.target) ** 2
        return self.reset(), reward, True, {}


class PixelCartPole:
    """CartPole with Atari-shaped observations: the 4-dim state is
    rendered into an 84x84 uint8 frame (cart position / pole angle
    drawn as bright bars — the policy must read the picture). The
    large-obs env for rollout/learner THROUGHPUT measurement
    (reference: the Atari suites in release_tests.yaml) without
    shipping ROMs."""

    obs_dim = 84 * 84
    n_actions = 2

    def __init__(self, seed: int | None = None):
        self.env = CartPole(seed=seed)

    def _render(self, state) -> np.ndarray:
        x, x_dot, theta, theta_dot = state
        frame = np.zeros((84, 84), np.float32)
        cart_col = int(np.clip((x / 2.4 + 1.0) * 41.5, 0, 83))
        frame[70:74, max(cart_col - 4, 0):cart_col + 5] = 1.0
        tip_col = int(np.clip(cart_col + 30 * np.sin(theta), 0, 83))
        tip_row = int(np.clip(70 - 30 * np.cos(theta), 0, 83))
        rr = np.linspace(70, tip_row, 30).astype(int)
        cc = np.linspace(cart_col, tip_col, 30).astype(int)
        frame[rr, cc] = 1.0
        # velocity channels as intensity rows (keeps it an MDP)
        frame[0, :] = np.clip(x_dot / 3.0 + 0.5, 0, 1)
        frame[1, :] = np.clip(theta_dot / 3.0 + 0.5, 0, 1)
        return frame.reshape(-1)

    def reset(self):
        return self._render(self.env.reset())

    def step(self, action):
        obs, r, d, i = self.env.step(action)
        self.truncated = self.env.truncated
        return self._render(obs), r, d, i


ENV_REGISTRY = {"CartPole-v1": CartPole, "Bandit-v0": BanditEnv,
                "Pendulum-v1": Pendulum,
                "ContinuousBandit-v0": ContinuousBandit,
                "PixelCartPole-v0": PixelCartPole}


def make_env(name_or_cls, seed=None):
    if isinstance(name_or_cls, str):
        cls = ENV_REGISTRY.get(name_or_cls)
        if cls is not None:
            return cls(seed=seed)
        # unknown id: resolve through gymnasium (Atari/MuJoCo-class envs)
        from ray_tpu.rllib.gym_env import try_make_gym_env

        env = try_make_gym_env(name_or_cls, seed=seed)
        if env is None:
            raise KeyError(
                f"unknown env {name_or_cls!r}: not in ENV_REGISTRY and "
                f"not a gymnasium id")
        return env
    return name_or_cls(seed=seed)
