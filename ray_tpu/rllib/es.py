"""Evolution Strategies (ES) and Augmented Random Search (ARS).

Reference analogs: ``rllib/algorithms/es/es.py`` (OpenAI-ES: antithetic
gaussian perturbations scored by fitness, centered-rank gradient
estimate, shared noise table) and ``rllib/algorithms/ars/ars.py``
(top-k directions, reward-std step normalization, V2 observation
normalization). Both are rebuilt here on ray_tpu primitives rather than
translated: the shared noise table is a single large numpy array placed
in the shared-memory object store once (``ray_tpu.put``) and mapped
zero-copy by every rollout worker — the same trick the reference plays
with its ``SharedNoiseTable`` over plasma — and perturbation evaluation
fans out as plain actor calls.

Neither algorithm backpropagates, so the policy is a numpy MLP evaluated
on host; the update itself is a couple of dense reductions.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


# ---------------------------------------------------------------------------
# Shared noise table + flat linear/MLP policy
# ---------------------------------------------------------------------------

NOISE_TABLE_SIZE = 4_000_000  # floats; ~16 MB, plenty for small policies


def make_noise_table(seed: int = 1234, size: int = NOISE_TABLE_SIZE):
    return np.random.default_rng(seed).standard_normal(
        size, dtype=np.float32)


def _policy_shapes(obs_dim: int, n_out: int, hidden: int):
    if hidden <= 0:  # linear policy (ARS default)
        return [(obs_dim, n_out)]
    return [(obs_dim, hidden), (hidden, hidden), (hidden, n_out)]


def _flat_size(shapes):
    return sum(int(np.prod(s)) for s in shapes)


def _forward_flat(theta, shapes, obs):
    """Evaluate the flat-parameter MLP; tanh torso, linear head."""
    x = obs
    off = 0
    for i, shape in enumerate(shapes):
        n = int(np.prod(shape))
        w = theta[off:off + n].reshape(shape)
        off += n
        x = x @ w
        if i < len(shapes) - 1:
            x = np.tanh(x)
    return x


class _FitnessWorker:
    """Evaluates perturbed policies; one episode (or step budget) each.

    Holds the env and a zero-copy view of the shared noise table.
    """

    def __init__(self, env_name, seed, noise, shapes, discrete,
                 action_low=None, action_high=None):
        self.env = make_env(env_name, seed=seed)
        # the driver passes the table as an ObjectRef arg; the runtime
        # materializes it here zero-copy out of the shm store
        self.noise = np.asarray(noise)
        self.shapes = list(map(tuple, shapes))
        self.dim = _flat_size(self.shapes)
        self.discrete = discrete
        self.low, self.high = action_low, action_high
        self.rng = np.random.default_rng(seed)
        # ARS-style per-dimension observation statistics, pooled by the
        # driver across workers: (count, sum, sum-of-squares)
        obs_dim = self.env.obs_dim
        self.obs_count = 0
        self.obs_sum = np.zeros(obs_dim)
        self.obs_sumsq = np.zeros(obs_dim)

    def _act(self, theta, obs):
        out = _forward_flat(theta, self.shapes, obs)
        if self.discrete:
            return int(np.argmax(out))
        a = np.tanh(out)
        if self.low is not None:
            a = self.low + (a + 1.0) * 0.5 * (self.high - self.low)
        return a

    def _episode(self, theta, max_steps, ob_mean, ob_std):
        obs = self.env.reset()
        total, steps = 0.0, 0
        for _ in range(max_steps):
            o = np.asarray(obs, dtype=np.float64)
            self.obs_count += 1
            self.obs_sum += o
            self.obs_sumsq += o * o
            if ob_std is not None:
                o = (o - ob_mean) / ob_std
            obs, reward, done, _ = self.env.step(self._act(theta, o))
            total += reward
            steps += 1
            if done:
                break
        return total, steps

    def _fitness(self, theta, episodes, max_steps, ob_mean, ob_std):
        """Mean return over ``episodes`` episodes (averaging smooths
        noisy one-step envs; full-episode envs keep episodes=1)."""
        total, steps = 0.0, 0
        for _ in range(episodes):
            r, s = self._episode(theta, max_steps, ob_mean, ob_std)
            total += r
            steps += s
        return total / episodes, steps

    def do_rollouts(self, theta, num_pairs, sigma, max_steps,
                    ob_stats=None, episodes_per_direction=1):
        """Antithetic evaluation of ``num_pairs`` noise directions.

        Returns (noise_indices, returns+, returns-, steps, obs_stats).
        """
        theta = np.asarray(theta, dtype=np.float32)
        ob_mean = ob_std = None
        if ob_stats is not None:
            ob_mean, ob_std = ob_stats
        idxs, pos, neg, steps = [], [], [], 0
        for _ in range(num_pairs):
            i = int(self.rng.integers(0, len(self.noise) - self.dim))
            eps = self.noise[i:i + self.dim]
            r_pos, s1 = self._fitness(theta + sigma * eps,
                                      episodes_per_direction, max_steps,
                                      ob_mean, ob_std)
            r_neg, s2 = self._fitness(theta - sigma * eps,
                                      episodes_per_direction, max_steps,
                                      ob_mean, ob_std)
            idxs.append(i)
            pos.append(r_pos)
            neg.append(r_neg)
            steps += s1 + s2
        return (np.asarray(idxs), np.asarray(pos), np.asarray(neg),
                steps, (self.obs_count, self.obs_sum, self.obs_sumsq))

    def eval_policy(self, theta, episodes, max_steps, ob_stats=None):
        theta = np.asarray(theta, dtype=np.float32)
        ob_mean = ob_std = None
        if ob_stats is not None:
            ob_mean, ob_std = ob_stats
        return [self._episode(theta, max_steps, ob_mean, ob_std)[0]
                for _ in range(episodes)]


def _centered_ranks(x):
    """Map fitness values to centered ranks in [-0.5, 0.5] (ES trick that
    makes the estimator invariant to reward scaling)."""
    flat = x.ravel()
    ranks = np.empty(len(flat), dtype=np.float32)
    ranks[flat.argsort()] = np.arange(len(flat), dtype=np.float32)
    ranks = ranks.reshape(x.shape)
    if len(flat) > 1:
        ranks = ranks / (len(flat) - 1) - 0.5
    return ranks


# ---------------------------------------------------------------------------
# ES
# ---------------------------------------------------------------------------

@dataclass
class ESConfig:
    env: str = "CartPole-v1"
    num_rollout_workers: int = 2
    episodes_per_batch: int = 16     # antithetic pairs per iteration
    sigma: float = 0.1               # perturbation stddev
    lr: float = 0.02
    l2_coeff: float = 0.005
    hidden: int = 32                 # <=0 -> linear policy
    max_episode_steps: int = 500
    episodes_per_direction: int = 1  # fitness = mean over this many eps
    seed: int = 0

    def environment(self, env):
        return replace(self, env=env)

    def rollouts(self, *, num_rollout_workers=None):
        if num_rollout_workers is None:
            return self
        return replace(self, num_rollout_workers=num_rollout_workers)

    def training(self, **kw):
        return replace(self, **kw)

    def build(self):
        return ES(self)


class ES:
    """OpenAI-style Evolution Strategies driver."""

    _normalize_obs = False

    def __init__(self, config):
        self.config = config
        env = make_env(config.env, seed=config.seed)
        self.discrete = hasattr(env, "n_actions")
        n_out = env.n_actions if self.discrete else env.action_dim
        self.low = getattr(env, "action_low", -1.0)
        self.high = getattr(env, "action_high", 1.0)
        self.shapes = _policy_shapes(env.obs_dim, n_out, config.hidden)
        self.dim = _flat_size(self.shapes)
        rng = np.random.default_rng(config.seed)
        self.theta = (rng.standard_normal(self.dim) /
                      np.sqrt(env.obs_dim)).astype(np.float32) * 0.1
        self.noise = make_noise_table(seed=config.seed + 99)
        noise_ref = ray_tpu.put(self.noise)
        worker_cls = ray_tpu.remote(_FitnessWorker)
        self.workers = [
            worker_cls.remote(config.env, config.seed + 7 * (i + 1),
                              noise_ref, self.shapes, self.discrete,
                              None if self.discrete else self.low,
                              None if self.discrete else self.high)
            for i in range(config.num_rollout_workers)
        ]
        self.iteration = 0
        self.total_steps = 0
        # Adam state for the gradient step
        self._m = np.zeros(self.dim, dtype=np.float32)
        self._v = np.zeros(self.dim, dtype=np.float32)
        self._obs_stats = None

    def _gradient(self, idxs, pos, neg):
        ranks = _centered_ranks(np.stack([pos, neg], axis=1))
        weights = ranks[:, 0] - ranks[:, 1]
        grad = np.zeros(self.dim, dtype=np.float32)
        for w, i in zip(weights, idxs):
            grad += w * self.noise[i:i + self.dim]
        grad /= (len(idxs) * self.config.sigma)
        return grad - self.config.l2_coeff * self.theta

    def _adam_step(self, grad):
        cfg = self.config
        t = self.iteration + 1
        self._m = 0.9 * self._m + 0.1 * grad
        self._v = 0.999 * self._v + 0.001 * grad * grad
        mhat = self._m / (1 - 0.9 ** t)
        vhat = self._v / (1 - 0.999 ** t)
        self.theta = self.theta + cfg.lr * mhat / (np.sqrt(vhat) + 1e-8)

    def train(self) -> dict:
        cfg = self.config
        per = max(1, cfg.episodes_per_batch // len(self.workers))
        outs = ray_tpu.get([
            w.do_rollouts.remote(self.theta, per, cfg.sigma,
                                 cfg.max_episode_steps,
                                 self._obs_stats if self._normalize_obs
                                 else None,
                                 cfg.episodes_per_direction)
            for w in self.workers
        ])
        idxs = np.concatenate([o[0] for o in outs])
        pos = np.concatenate([o[1] for o in outs])
        neg = np.concatenate([o[2] for o in outs])
        self.total_steps += sum(o[3] for o in outs)
        if self._normalize_obs:
            count = sum(o[4][0] for o in outs)
            if count > 1:
                total = np.sum([o[4][1] for o in outs], axis=0)
                sumsq = np.sum([o[4][2] for o in outs], axis=0)
                mean = total / count
                var = np.maximum(sumsq / count - mean * mean, 1e-8)
                self._obs_stats = (mean, np.sqrt(var))
        self._update(idxs, pos, neg)
        self.iteration += 1
        rets = np.concatenate([pos, neg])
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": float(rets.mean()),
            "episode_return_max": float(rets.max()),
            "num_env_steps_sampled": self.total_steps,
            "theta_norm": float(np.linalg.norm(self.theta)),
        }

    def _update(self, idxs, pos, neg):
        self._adam_step(self._gradient(idxs, pos, neg))

    def evaluate(self, num_episodes: int = 8) -> dict:
        per = max(1, num_episodes // len(self.workers))
        rets = [r for w in self.workers
                for r in ray_tpu.get(
                    w.eval_policy.remote(self.theta, per,
                                         self.config.max_episode_steps,
                                         self._obs_stats))]
        return {"episode_return_mean": float(np.mean(rets))}

    def compute_action(self, obs):
        o = np.asarray(obs, dtype=np.float64)
        if self._obs_stats is not None:
            o = (o - self._obs_stats[0]) / self._obs_stats[1]
        out = _forward_flat(self.theta, self.shapes, o)
        if self.discrete:
            return int(np.argmax(out))
        # same squash+rescale the rollout workers act with
        a = np.tanh(out)
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)

    def save(self, path: str):
        np.savez(path, theta=self.theta)

    def restore(self, path: str):
        if not path.endswith(".npz"):
            path += ".npz"
        self.theta = np.load(path)["theta"]

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# ARS
# ---------------------------------------------------------------------------

@dataclass
class ARSConfig(ESConfig):
    hidden: int = 0            # ARS default: linear policy
    top_k: int = 8             # directions kept for the update
    lr: float = 0.05

    def build(self):
        return ARS(self)


class ARS(ES):
    """Augmented Random Search (V2: top-k directions + reward-std step
    normalization; observation normalization via pooled worker stats)."""

    _normalize_obs = True

    def _update(self, idxs, pos, neg):
        cfg = self.config
        k = min(cfg.top_k, len(idxs))
        best = np.argsort(-np.maximum(pos, neg))[:k]
        r_std = np.concatenate([pos[best], neg[best]]).std() + 1e-8
        step = np.zeros(self.dim, dtype=np.float32)
        for j in best:
            step += (pos[j] - neg[j]) * self.noise[idxs[j]:idxs[j] + self.dim]
        self.theta = self.theta + cfg.lr / (k * r_std) * step
