"""Durable workflow execution (reference: ``python/ray/workflow/``, P19).

``workflow.run(dag_node, workflow_id=...)`` executes a ``ray_tpu.dag``
graph with per-step checkpointing: each node's result is persisted under
the workflow's storage directory keyed by a deterministic step id
(topological index + function name). ``resume`` re-runs the DAG, skipping
every step whose checkpoint exists — the saga-style recovery of the
reference (``workflow_state_from_storage.py``) specialized to DAGs.
"""

from __future__ import annotations

import os
import pickle

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("workflow")

import ray_tpu
from ray_tpu.dag import DAGNode

_STORAGE = os.path.join(os.path.expanduser("~"), "ray_tpu_workflows")


def _step_id(index: int, node: DAGNode) -> str:
    return f"{index:04d}_{getattr(node._fn, '__name__', 'step')}"


def run(dag: DAGNode, *, workflow_id: str,
        storage: str | None = None):
    """Execute with checkpointing; returns the final result (sync)."""
    root = os.path.join(storage or _STORAGE, workflow_id)
    os.makedirs(root, exist_ok=True)
    order = dag.topo_order()
    results: dict[int, object] = {}
    for index, node in enumerate(order):
        path = os.path.join(root, _step_id(index, node) + ".pkl")
        if os.path.exists(path):
            with open(path, "rb") as f:
                results[id(node)] = pickle.load(f)
            continue
        args = [results[id(a)] if isinstance(a, DAGNode) else a
                for a in node._args]
        kwargs = {k: results[id(v)] if isinstance(v, DAGNode) else v
                  for k, v in node._kwargs.items()}
        value = ray_tpu.get(ray_tpu.remote(node._fn).remote(*args, **kwargs))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)  # atomic: a crash never leaves half a step
        results[id(node)] = value
    _mark(root, "SUCCESS")
    return results[id(dag)]


def resume(dag: DAGNode, *, workflow_id: str, storage: str | None = None):
    """Re-run, skipping checkpointed steps (crash recovery)."""
    return run(dag, workflow_id=workflow_id, storage=storage)


def status(workflow_id: str, *, storage: str | None = None) -> str:
    root = os.path.join(storage or _STORAGE, workflow_id)
    if not os.path.isdir(root):
        return "NOT_FOUND"
    if os.path.exists(os.path.join(root, "_STATUS_SUCCESS")):
        return "SUCCESS"
    return "RUNNING" if os.listdir(root) else "PENDING"


def list_all(*, storage: str | None = None) -> list[str]:
    base = storage or _STORAGE
    return sorted(os.listdir(base)) if os.path.isdir(base) else []


def delete(workflow_id: str, *, storage: str | None = None):
    import shutil

    shutil.rmtree(os.path.join(storage or _STORAGE, workflow_id),
                  ignore_errors=True)


def _mark(root: str, state: str):
    open(os.path.join(root, f"_STATUS_{state}"), "w").close()
