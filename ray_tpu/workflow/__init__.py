"""Durable workflow execution (reference: ``python/ray/workflow/``, P19).

``workflow.run(dag_node, workflow_id=...)`` executes a ``ray_tpu.dag``
graph with per-step checkpointing: each node's result is persisted under
the workflow's storage directory keyed by a CONTENT-ADDRESSED step id —
a digest over the step's function name and its input lineage (static
args + the ids of upstream steps). Editing the DAG therefore invalidates
exactly the steps whose inputs changed: inserting or removing an
unrelated step never silently remaps another step's checkpoint (the
round-1 topological-index scheme did), and a step whose upstream chain
changed re-runs instead of reusing a stale result. ``resume`` re-runs
the DAG, skipping every step whose checkpoint exists — the saga-style
recovery of the reference (``workflow_state_from_storage.py``)
specialized to DAGs.
"""

from __future__ import annotations

import os
import pickle

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("workflow")

import ray_tpu
from ray_tpu.dag import DAGNode

_STORAGE = os.path.join(os.path.expanduser("~"), "ray_tpu_workflows")


def _arg_digest(h, value):
    import pickle as _pickle
    import re as _re

    # sets pickle in iteration order, which string-hash randomization
    # reshuffles per process — canonicalize so the SAME set always
    # digests the same and resume finds its checkpoints
    if isinstance(value, (set, frozenset)):
        h.update(b"set:")
        for item in sorted(value, key=repr):
            _arg_digest(h, item)
        return
    try:
        h.update(_pickle.dumps(value, protocol=5))
    except Exception:  # noqa: BLE001 - unpicklable static arg
        # repr() embeds memory addresses ("<X at 0x7f..>") which would
        # make the id differ every process and break resume — strip them
        # (the residual collision risk only affects unpicklable args,
        # which cluster execution couldn't ship anyway)
        h.update(_re.sub(r"0x[0-9a-fA-F]+", "0x", repr(value)).encode())


def _step_ids(order: list[DAGNode]) -> dict[int, str]:
    """Content-addressed step ids: digest(fn qualname, static args,
    upstream step ids). Two identical sub-DAGs share an id — and
    therefore a checkpoint — which is sound for the deterministic steps
    workflows assume (and dedups repeated work on resume)."""
    import hashlib

    ids: dict[int, str] = {}
    for node in order:           # topo order: parents resolve first
        h = hashlib.sha256()
        fn = node._fn
        # module + qualname alone collide (same-scope lambdas share a
        # qualname; same-named fns exist across modules). cloudpickle
        # serializes the function BY VALUE — bytecode plus captured
        # closure cells, default args, and referenced globals — so
        # editing any of those changes the step's identity and the stale
        # checkpoint is correctly invalidated.
        h.update(getattr(fn, "__module__", "").encode())
        h.update(getattr(fn, "__qualname__", "step").encode())
        try:
            import cloudpickle as _cp

            h.update(_cp.dumps(fn, protocol=5))
        except Exception:  # noqa: BLE001 - fall back to bytecode identity
            code = getattr(fn, "__code__", None)
            if code is not None:
                h.update(code.co_code)
                _arg_digest(h, code.co_consts)
        for a in node._args:
            if isinstance(a, DAGNode):
                h.update(ids[id(a)].encode())
            else:
                _arg_digest(h, a)
        for k in sorted(node._kwargs):
            v = node._kwargs[k]
            h.update(k.encode())
            if isinstance(v, DAGNode):
                h.update(ids[id(v)].encode())
            else:
                _arg_digest(h, v)
        name = getattr(node._fn, "__name__", "step")
        ids[id(node)] = f"{name}-{h.hexdigest()[:16]}"
    return ids


def _run_step(node: DAGNode, args, kwargs):
    """One step with per-step workflow options (reference: step options
    ``max_retries``/``catch_exceptions`` in ``workflow/api.py``).

    - ``workflow_max_retries``: re-submit the step N extra times on error
    - ``workflow_catch_exceptions``: return (result, None) / (None, exc)
      instead of raising, so downstream steps can compensate (saga style)
    """
    import time as _time

    opts = dict(node._options or {})
    retries = int(opts.pop("workflow_max_retries", 0))
    catch = bool(opts.pop("workflow_catch_exceptions", False))
    task = ray_tpu.remote(node._fn)
    if opts:
        task = task.options(**opts)
    last = None
    for attempt in range(retries + 1):
        try:
            value = ray_tpu.get(task.remote(*args, **kwargs))
            return (value, None) if catch else value
        except Exception as e:  # noqa: BLE001
            # surface the USER's exception, not the runtime's TaskError
            # wrapper (reference: catch_exceptions hands back the cause)
            last = getattr(e, "cause", None) or e
            if attempt < retries:
                _time.sleep(0.05 * (attempt + 1))
    if catch:
        return (None, last)
    raise last


def run(dag: DAGNode, *, workflow_id: str,
        storage: str | None = None):
    """Execute with checkpointing; returns the final result (sync)."""
    root = os.path.join(storage or _STORAGE, workflow_id)
    os.makedirs(root, exist_ok=True)
    order = dag.topo_order()
    step_ids = _step_ids(order)
    results: dict[int, object] = {}
    final = None
    try:
        for node in order:
            path = os.path.join(root, step_ids[id(node)] + ".pkl")
            if os.path.exists(path):
                with open(path, "rb") as f:
                    results[id(node)] = pickle.load(f)
                continue
            args = [results[id(a)] if isinstance(a, DAGNode) else a
                    for a in node._args]
            kwargs = {k: results[id(v)] if isinstance(v, DAGNode) else v
                      for k, v in node._kwargs.items()}
            value = _run_step(node, args, kwargs)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(value, f)
            os.replace(tmp, path)  # atomic: a crash never leaves half a step
            results[id(node)] = value
        final = results[id(dag)]
    except Exception:
        _mark(root, "FAILED")
        raise
    # persist the workflow output for get_output() — atomically, like
    # step checkpoints (a crash mid-write must not fake a half-output)
    out_path = os.path.join(root, "_OUTPUT.pkl")
    tmp = out_path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(final, f)
    os.replace(tmp, out_path)
    _mark(root, "SUCCESS")
    # retire the events THIS workflow consumed so a later workflow
    # reusing the name blocks for a fresh signal (broadcast within one
    # run; no stale refire across runs)
    for node in order:
        ev = getattr(node._fn, "__wf_event_name__", None)
        if ev is not None:
            clear_event(ev)
    return final


def run_async(dag: DAGNode, *, workflow_id: str,
              storage: str | None = None):
    """Run the whole workflow inside a task; returns an ObjectRef
    (reference: ``workflow.run_async`` — ``workflow/api.py:174``).

    The storage path resolves on the DRIVER so the executing worker and
    the driver agree on it. On a multi-node cluster, pass a SHARED
    filesystem path (NFS/GCS fuse) — same requirement as the
    reference's workflow storage."""
    blob = (dag, workflow_id, storage or _STORAGE)

    @ray_tpu.remote
    def _drive(payload):
        d, wid, st = payload
        return run(d, workflow_id=wid, storage=st)

    return _drive.remote(blob)


def get_output(workflow_id: str, *, storage: str | None = None):
    """Result of a completed workflow (reference: workflow.get_output)."""
    root = os.path.join(storage or _STORAGE, workflow_id)
    path = os.path.join(root, "_OUTPUT.pkl")
    if not os.path.exists(path):
        raise ValueError(
            f"workflow {workflow_id!r} has no recorded output "
            f"(status={status(workflow_id, storage=storage)})")
    with open(path, "rb") as f:
        return pickle.load(f)


def resume(dag: DAGNode, *, workflow_id: str, storage: str | None = None):
    """Re-run, skipping checkpointed steps (crash recovery)."""
    return run(dag, workflow_id=workflow_id, storage=storage)


def status(workflow_id: str, *, storage: str | None = None) -> str:
    root = os.path.join(storage or _STORAGE, workflow_id)
    if not os.path.isdir(root):
        return "NOT_FOUND"
    if os.path.exists(os.path.join(root, "_STATUS_SUCCESS")):
        return "SUCCESS"
    if os.path.exists(os.path.join(root, "_STATUS_FAILED")):
        return "FAILED"
    return "RUNNING" if os.listdir(root) else "PENDING"


def metadata(workflow_id: str, *, storage: str | None = None) -> dict:
    """Steps completed + status (reference: workflow metadata API)."""
    root = os.path.join(storage or _STORAGE, workflow_id)
    steps = []
    if os.path.isdir(root):
        steps = sorted(f[:-4] for f in os.listdir(root)
                       if f.endswith(".pkl") and not f.startswith("_"))
    return {"workflow_id": workflow_id,
            "status": status(workflow_id, storage=storage),
            "steps_completed": steps}


# ---------------------------------------------------------------------------
# events (reference: workflow/http_event_provider.py — here events live in
# the internal KV so any process can signal them)
# ---------------------------------------------------------------------------

def signal_event(name: str, payload=b"1") -> None:
    """Fire an event; a workflow blocked in wait_for_event resumes."""
    from ray_tpu.experimental import internal_kv_put

    internal_kv_put(f"__wf_event_{name}", payload)


def clear_event(name: str) -> None:
    """Remove a fired event so its name can be reused without the new
    waiter seeing the stale payload."""
    from ray_tpu.experimental import internal_kv_del

    internal_kv_del(f"__wf_event_{name}")


def event(name: str, *, poll_interval_s: float = 0.05,
          timeout_s: float = 60.0) -> DAGNode:
    """A DAG node that completes when the named event fires; its value is
    the event payload. Compose like any step:
        done = process.bind(workflow.event("upstream-ready"))
    """
    from ray_tpu.dag import DAGNode as _Node

    def _wait(_name=name, _poll=poll_interval_s, _timeout=timeout_s):
        import time as _time

        from ray_tpu.experimental import internal_kv_get

        deadline = _time.monotonic() + _timeout
        while _time.monotonic() < deadline:
            val = internal_kv_get(f"__wf_event_{_name}")
            if val is not None:
                # BROADCAST semantics: the payload stays so every waiter
                # (concurrent workflows, parallel event nodes) resumes;
                # call clear_event() before reusing a name
                return val
            _time.sleep(_poll)
        raise TimeoutError(f"workflow event {_name!r} never fired")

    _wait.__name__ = f"event_{name}"
    _wait.__wf_event_name__ = name
    return _Node(_wait, (), {})


def list_all(*, storage: str | None = None) -> list[str]:
    base = storage or _STORAGE
    return sorted(os.listdir(base)) if os.path.isdir(base) else []


def delete(workflow_id: str, *, storage: str | None = None):
    import shutil

    shutil.rmtree(os.path.join(storage or _STORAGE, workflow_id),
                  ignore_errors=True)


def _mark(root: str, state: str):
    open(os.path.join(root, f"_STATUS_{state}"), "w").close()
