"""Dashboard head: HTTP observability endpoint for a running cluster.

Reference analog: ``dashboard/head.py:81`` + REST modules under
``dashboard/modules/`` (P7). The reference runs an aiohttp app with a
React frontend; here a dependency-free threaded http.server exposes the
same information surface:

- ``GET /``                       single-page web UI (tabs over the REST
                                  API below; ``_private/dashboard_app.html``
                                  — the reference's React app analog)
- ``GET /api/cluster_status``     cluster summary (nodes/actors/resources)
- ``GET /api/nodes|actors|tasks|jobs|placement_groups|objects``
- ``GET /api/timeline``           chrome://tracing JSON of task events
- ``GET /api/traces``             collected distributed traces (GCS
                                  TraceStore); ``/api/trace/<trace_id>``
                                  returns spans + waterfall rows
- ``GET /api/stuck_calls``        cluster-wide in-flight calls past a
                                  threshold; ``/api/flight_record``
                                  dumps a process's recent span window
- ``GET /api/memory``             ownership-attributed memory summary
                                  (pinned/spilled/in-proc per owner,
                                  call sites, pressure) + leak suspects
- ``GET /metrics``                Prometheus text (``ray.util.metrics``
                                  analog + runtime counters)
- ``GET /api/version``

Data comes from ``ray_tpu.util.state`` (GCS-backed in cluster mode,
runtime introspection locally) so the dashboard works in both modes.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

import ray_tpu
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import state as _state

_FALLBACK_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title></head>
<body><h2>ray_tpu dashboard</h2>
<p>web UI asset missing; REST API remains at /api/*</p>
<pre>{summary}</pre></body></html>
"""


def _index_html() -> bytes:
    import importlib.resources

    try:
        return (importlib.resources.files("ray_tpu._private")
                .joinpath("dashboard_app.html").read_bytes())
    except (FileNotFoundError, ModuleNotFoundError, OSError):
        summary = json.dumps(_state.cluster_summary(), indent=2,
                             default=str)
        return _FALLBACK_HTML.format(summary=summary).encode()


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # silence request logging
        pass

    def do_POST(self):  # noqa: N802 - http.server API
        """Job submission REST (reference: dashboard/modules/job/
        job_head.py): POST /api/jobs {"entrypoint": ..., "env": {...}}."""
        path = self.path.split("?", 1)[0].rstrip("/")
        try:
            if path == "/api/jobs":
                from ray_tpu.job_submission import JobSubmissionClient

                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, TypeError):
                    self._send_json({"error": "malformed request body"},
                                    400)
                    return
                if "entrypoint" not in body:
                    self._send_json({"error": "entrypoint required"}, 400)
                    return
                client = JobSubmissionClient()
                job_id = client.submit_job(
                    entrypoint=body["entrypoint"],
                    env=body.get("env"),
                    working_dir=body.get("working_dir"),
                    submission_id=body.get("submission_id"))
                self._send_json({"job_id": job_id}, 200)
            elif path.startswith("/api/jobs/") and path.endswith("/stop"):
                from ray_tpu.job_submission import JobSubmissionClient

                job_id = path[len("/api/jobs/"):-len("/stop")]
                JobSubmissionClient().stop_job(job_id)
                self._send_json({"ok": True})
            else:
                self._send_json({"error": f"unknown path {path}"}, 404)
        except ValueError as e:
            try:
                self._send_json({"error": str(e)}, 404)
            except OSError:
                pass
        except OSError:
            pass
        except Exception as e:  # noqa: BLE001
            try:
                self._send_json({"error": repr(e)}, 500)
            except OSError:
                pass

    def _send(self, body: bytes, content_type: str, status: int = 200):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, obj, status: int = 200):
        self._send(json.dumps(obj, indent=2, default=str).encode(),
                   "application/json", status)

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/":
                self._send(_index_html(), "text/html")
            elif path == "/api/cluster_status":
                self._send_json(_state.cluster_summary())
            elif path == "/api/nodes":
                self._send_json(_state.list_nodes())
            elif path == "/api/actors":
                self._send_json(_state.list_actors())
            elif path == "/api/tasks":
                self._send_json(_state.list_tasks())
            elif path == "/api/jobs":
                self._send_json(_state.list_jobs())
            elif path == "/api/placement_groups":
                self._send_json(_state.list_placement_groups())
            elif path == "/api/objects":
                self._send_json(_state.list_objects())
            elif path == "/api/timeline":
                self._send_json(ray_tpu.timeline())
            elif path == "/api/stacks":
                qs = parse_qs(self.path.partition("?")[2])
                self._send_json(_state.dump_worker_stacks(
                    node_id=qs.get("node", [None])[0],
                    worker_id=qs.get("worker", [None])[0]))
            elif path == "/api/profile":
                # ?worker=<id> profiles one worker; no worker fans ONE
                # sampling window across the whole cluster (optionally
                # filtered by ?procs=driver,gcs,raylet,worker) and
                # returns the merged collapsed stacks
                qs = parse_qs(self.path.partition("?")[2])
                worker = qs.get("worker", [None])[0]
                duration_s = float(qs.get("duration", ["2.0"])[0])
                hz = int(qs.get("hz", ["100"])[0])
                if worker:
                    self._send_json(_state.profile_worker(
                        worker, duration_s=duration_s, hz=hz))
                else:
                    procs = [p for p in
                             qs.get("procs", [""])[0].split(",") if p]
                    self._send_json(_state.profile_cluster(
                        procs=procs or None, duration_s=duration_s,
                        hz=hz))
            elif path == "/api/profile/stacks":
                # one-shot stack dump of any single process — no
                # sampling window (?proc=driver|gcs|<node_id>|<worker>)
                qs = parse_qs(self.path.partition("?")[2])
                self._send_json(_state.dump_proc_stacks(
                    proc=qs.get("proc", [None])[0]))
            elif path.startswith("/api/jobs/") and path.endswith("/logs"):
                from ray_tpu.job_submission import JobSubmissionClient

                job_id = path[len("/api/jobs/"):-len("/logs")]
                self._send(JobSubmissionClient().get_job_logs(
                    job_id).encode(), "text/plain")
            elif path.startswith("/api/jobs/"):
                from ray_tpu.job_submission import JobSubmissionClient

                job_id = path[len("/api/jobs/"):]
                self._send_json(
                    JobSubmissionClient().get_job_info(job_id))
            elif path == "/api/metrics/query":
                # cluster metrics plane range/instant query:
                # ?name=...&last_s=60&group_by=src,stage&per_window=1
                # (no name -> the metric-name listing)
                qs = parse_qs(self.path.partition("?")[2])
                name = qs.get("name", [None])[0]
                gb = [g for g in
                      qs.get("group_by", [""])[0].split(",") if g]
                last_s = qs.get("last_s", [None])[0]
                tags = {k[4:]: v[0] for k, v in qs.items()
                        if k.startswith("tag.")}
                self._send_json(_state.cluster_metrics(
                    name, tags=tags or None,
                    last_s=float(last_s) if last_s else None,
                    group_by=gb,
                    per_window=qs.get("per_window", ["0"])[0] == "1"))
            elif path == "/api/traces":
                qs = parse_qs(self.path.partition("?")[2])
                self._send_json(_state.list_traces(
                    limit=int(qs.get("limit", ["50"])[0])))
            elif path.startswith("/api/trace/"):
                from ray_tpu.util import tracing as _tracing

                trace_id = path[len("/api/trace/"):]
                trace = _state.get_trace(trace_id)
                if trace is None:
                    self._send_json(
                        {"error": f"unknown trace {trace_id!r}"}, 404)
                else:
                    self._send_json({
                        "trace": trace,
                        "waterfall": _tracing.build_waterfall(
                            trace.get("spans") or [])})
            elif path == "/api/logs":
                # log-plane overview: per-proc listing + error groups
                qs = parse_qs(self.path.partition("?")[2])
                last_s = qs.get("last_s", [None])[0]
                self._send_json({
                    "logs": _state.list_logs(),
                    "errors": _state.summarize_errors(
                        float(last_s) if last_s else None)})
            elif path == "/api/logs/tail":
                # ?proc=<name>&n=100 or ?task_id=<id> (exact segment)
                qs = parse_qs(self.path.partition("?")[2])
                proc = qs.get("proc", [None])[0]
                task_id = qs.get("task_id", [None])[0]
                if not proc and not task_id:
                    self._send_json(
                        {"error": "need ?proc= or ?task_id="}, 400)
                else:
                    self._send_json(_state.get_log(
                        proc=proc, task_id=task_id,
                        tail=int(qs.get("n", ["100"])[0])))
            elif path == "/api/stuck_calls":
                qs = parse_qs(self.path.partition("?")[2])
                t = qs.get("threshold_s", [None])[0]
                self._send_json(_state.stuck_calls(
                    threshold_s=float(t) if t else None))
            elif path == "/api/flight_record":
                qs = parse_qs(self.path.partition("?")[2])
                last_s = qs.get("last_s", [None])[0]
                self._send_json(_state.flight_record(
                    proc=qs.get("proc", [None])[0],
                    last_s=float(last_s) if last_s else None))
            elif path == "/api/memory":
                # cluster memory plane: ownership-attributed summary
                # (?top_n=20), plus suspected leaks
                qs = parse_qs(self.path.partition("?")[2])
                top_n = int(qs.get("top_n", ["20"])[0])
                self._send_json({
                    "summary": _state.memory_summary(top_n=top_n),
                    "leaks": _state.memory_leaks()})
            elif path == "/api/latencies":
                # per-stage latency digest (live dashboard view)
                qs = parse_qs(self.path.partition("?")[2])
                last_s = float(qs.get("last_s", ["300"])[0])
                self._send_json(_state.summarize_latencies(last_s=last_s))
            elif path == "/api/version":
                self._send_json({"version": ray_tpu.__version__})
            elif path == "/metrics":
                self._send(_metrics.export_prometheus().encode(),
                           "text/plain; version=0.0.4")
            else:
                self._send_json({"error": f"unknown path {path}"}, 404)
        except ValueError as e:
            # unknown job/actor name lookups are client errors, not 500s
            try:
                self._send_json({"error": str(e)}, 404)
            except OSError:
                pass
        except OSError:
            # client went away mid-response; replying would raise again
            pass
        except Exception as e:  # noqa: BLE001 - surface as 500, keep serving
            try:
                self._send_json({"error": repr(e)}, 500)
            except OSError:
                pass


class Dashboard:
    """Threaded dashboard server bound to (host, port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "Dashboard":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ray_tpu-dashboard",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


_dashboard: Dashboard | None = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dashboard:
    """Start (or return) the process-wide dashboard."""
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port).start()
    return _dashboard


def stop_dashboard():
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
