"""ctypes binding for the C++ scheduling policy (src/scheduler/).

The GCS's node-selection path calls into the native hybrid policy
(reference: ``hybrid_scheduling_policy.cc:99-186`` + FixedPoint resource
math) when the library is built; callers fall back to the Python policy
otherwise, so a source checkout without `make -C src` still works.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_lib = None
_checked = False


def available() -> bool:
    return _load() is not None


def _load():
    global _lib, _checked
    if _checked:
        return _lib
    _checked = True
    path = os.path.join(os.path.dirname(__file__), "libtpusched.so")
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    lib.sched_pick_node.restype = ctypes.c_int
    lib.sched_pick_node.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int, ctypes.c_double,
        ctypes.c_int, ctypes.c_uint,
    ]
    lib.sched_score_nodes.restype = None
    lib.sched_score_nodes.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
    ]
    _lib = lib
    return lib


def _buf(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def pick_node(node_ids: list, totals: list[dict], avails: list[dict],
              alive: list[bool], excluded: set, demand: dict, *,
              spread_threshold: float = 0.5, top_k: int = 1,
              seed: int = 0):
    """Returns the chosen node id or None. Resource kinds are the union
    of demand keys (kinds a node lacks count as total=0 → infeasible)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("libtpusched.so not built")
    # zero-valued demand keys still participate (they contribute node
    # utilization, matching the Python policy); EMPTY demand means every
    # alive node ties at score 0 -> first node, like the Python loop
    kinds = sorted(demand)
    n, k = len(node_ids), len(kinds)
    t = np.zeros((n, k), np.float64)
    a = np.zeros((n, k), np.float64)
    for i in range(n):
        for j, kind in enumerate(kinds):
            t[i, j] = float(totals[i].get(kind, 0.0))
            a[i, j] = float(avails[i].get(kind, 0.0))
    d = np.asarray([float(demand.get(kind, 0.0)) for kind in kinds],
                   np.float64)
    alive_arr = np.asarray([1 if x else 0 for x in alive], np.uint8)
    excl_arr = np.asarray(
        [1 if node_ids[i] in excluded else 0 for i in range(n)], np.uint8)
    idx = lib.sched_pick_node(
        _buf(t), _buf(a), _buf(alive_arr), _buf(excl_arr), n, _buf(d), k,
        float(spread_threshold), int(top_k), int(seed) & 0xFFFFFFFF)
    return node_ids[idx] if idx >= 0 else None


def score_nodes(totals: list[dict], avails: list[dict], alive: list[bool],
                demand: dict) -> list[float]:
    lib = _load()
    if lib is None:
        raise RuntimeError("libtpusched.so not built")
    kinds = sorted(demand)
    n, k = len(totals), len(kinds)
    t = np.zeros((n, k), np.float64)
    a = np.zeros((n, k), np.float64)
    for i in range(n):
        for j, kind in enumerate(kinds):
            t[i, j] = float(totals[i].get(kind, 0.0))
            a[i, j] = float(avails[i].get(kind, 0.0))
    d = np.asarray([float(demand.get(kind, 0.0)) for kind in kinds],
                   np.float64)
    alive_arr = np.asarray([1 if x else 0 for x in alive], np.uint8)
    out = np.zeros((n,), np.float64)
    lib.sched_score_nodes(_buf(t), _buf(a), _buf(alive_arr), n, _buf(d), k,
                          _buf(out))
    return out.tolist()
