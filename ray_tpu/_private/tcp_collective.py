"""ctypes binding for the C++ TCP collective backend (src/collective/).

The host-side CPU data plane — Gloo analog of the reference's
``python/ray/util/collective/collective_group/gloo_collective_group.py``.
Ring allreduce/allgather/reduce-scatter, binomial broadcast, framed
tagged p2p, all over direct rank-to-rank TCP sockets (no actor hop).

Usage contract (same as NCCL): every rank issues the same collectives in
the same order. Arrays must be contiguous; allreduce is in-place on a
copy and returns the result.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
}
_OPS = {"sum": 0, "prod": 1, "max": 2, "min": 3}

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = os.path.join(os.path.dirname(__file__), "libtpucollective.so")
    if not os.path.exists(path):
        raise RuntimeError(
            "libtpucollective.so not built; run `make -C src` at the repo "
            "root")
    lib = ctypes.CDLL(path)
    lib.tc_init.restype = ctypes.c_int
    lib.tc_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
                            ctypes.c_int]
    lib.tc_listen.restype = ctypes.c_int
    lib.tc_listen.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.tc_listen_port.restype = ctypes.c_int
    lib.tc_listen_port.argtypes = [ctypes.c_int]
    lib.tc_connect.restype = ctypes.c_int
    lib.tc_connect.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.tc_recv_timeout.restype = ctypes.c_int
    lib.tc_recv_timeout.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ctypes.c_int, ctypes.c_int]
    for name, extra in [
        ("tc_allreduce", [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                          ctypes.c_int]),
        ("tc_allgather", [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                          ctypes.c_int]),
        ("tc_reduce_scatter", [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_int64, ctypes.c_int, ctypes.c_int]),
        ("tc_broadcast", [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                          ctypes.c_int]),
        ("tc_barrier", []),
        ("tc_send", [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                     ctypes.c_int]),
        ("tc_recv", [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                     ctypes.c_int]),
        ("tc_destroy", []),
    ]:
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_int] + extra
    _lib = lib
    return lib


def _check(rc: int, what: str):
    if rc < 0:
        raise OSError(-rc, f"collective {what} failed: {os.strerror(-rc)}")
    return rc


def _buf(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


class TcpGroup:
    """A connected full-mesh collective group.

    One-shot: ``TcpGroup(rank, world, peers)`` with a pre-agreed
    rank->"host:port" listener list (identical on every rank).

    Two-phase (race-free — the listener is bound BEFORE its address is
    advertised): ``g = TcpGroup.listen(rank, world)``, exchange
    ``f"{host}:{g.port}"`` out of band, then ``g.connect(peers)``.
    """

    def __init__(self, rank: int, world_size: int,
                 peers: list[str] | None = None,
                 timeout_ms: int = 30_000, _handle: int | None = None):
        lib = _load()
        if _handle is not None:
            self._h = _handle
        else:
            csv = ",".join(peers).encode()
            self._h = _check(
                lib.tc_init(rank, world_size, csv, timeout_ms), "init")
        self.rank = rank
        self.world_size = world_size
        self._lib = lib

    @classmethod
    def listen(cls, rank: int, world_size: int) -> "TcpGroup":
        lib = _load()
        h = _check(lib.tc_listen(rank, world_size), "listen")
        g = cls(rank, world_size, _handle=h)
        g.port = _check(lib.tc_listen_port(h), "listen_port")
        return g

    def connect(self, peers: list[str], timeout_ms: int = 30_000):
        csv = ",".join(peers).encode()
        _check(self._lib.tc_connect(self._h, csv, timeout_ms), "connect")
        return self

    def _prep(self, array, what: str) -> np.ndarray:
        arr = np.ascontiguousarray(array)
        if arr.dtype not in _DTYPES:
            # promote anything else (bf16, f16, bool, ...) to f32
            arr = arr.astype(np.float32)
        return arr

    def allreduce(self, array, op: str = "sum") -> np.ndarray:
        arr = self._prep(array, "allreduce").copy()
        _check(self._lib.tc_allreduce(
            self._h, _buf(arr), arr.size, _DTYPES[arr.dtype], _OPS[op]),
            "allreduce")
        return arr

    def allgather(self, array) -> list[np.ndarray]:
        arr = self._prep(array, "allgather")
        out = np.empty((self.world_size,) + arr.shape, dtype=arr.dtype)
        _check(self._lib.tc_allgather(
            self._h, _buf(arr), _buf(out), arr.size, _DTYPES[arr.dtype]),
            "allgather")
        return list(out)

    def reducescatter(self, array, op: str = "sum") -> np.ndarray:
        """``array`` is this rank's full contribution; returns the
        reduced chunk owned by this rank, split along axis 0 with
        ``np.array_split`` semantics — the same contract as the actor
        backend, so the two backends are interchangeable."""
        arr = self._prep(array, "reducescatter")
        if arr.ndim == 1 and arr.size % self.world_size == 0:
            # fast path: true ring reduce-scatter on equal flat chunks
            per = arr.size // self.world_size
            out = np.empty(per, dtype=arr.dtype)
            _check(self._lib.tc_reduce_scatter(
                self._h, _buf(arr), _buf(out), per, _DTYPES[arr.dtype],
                _OPS[op]), "reducescatter")
            return out
        # general path (uneven split or ndim > 1): allreduce then slice
        # locally — 2x ring bandwidth but exact array_split semantics
        red = self.allreduce(arr, op)
        return np.array_split(red, self.world_size)[self.rank]

    def broadcast(self, array, src_rank: int = 0) -> np.ndarray:
        arr = self._prep(array, "broadcast").copy()
        _check(self._lib.tc_broadcast(
            self._h, _buf(arr), arr.size, _DTYPES[arr.dtype], src_rank),
            "broadcast")
        return arr

    def barrier(self):
        _check(self._lib.tc_barrier(self._h), "barrier")

    def send(self, array, dst_rank: int, tag: int = 0):
        arr = self._prep(array, "send")
        header = np.frombuffer(
            _pack_meta(arr.shape, arr.dtype), dtype=np.uint8)
        _check(self._lib.tc_send(
            self._h, _buf(header), header.size, dst_rank, 2 * tag + 1),
            "send-meta")
        _check(self._lib.tc_send(
            self._h, _buf(arr), arr.nbytes, dst_rank, 2 * tag + 2), "send")

    def recv(self, src_rank: int, tag: int = 0,
             timeout: float | None = None) -> np.ndarray:
        tmo = 0 if timeout is None else max(1, int(timeout * 1000))
        header = np.empty(_META_BYTES, dtype=np.uint8)
        rc = self._lib.tc_recv_timeout(
            self._h, _buf(header), header.size, src_rank, 2 * tag + 1, tmo)
        if rc == -110:  # ETIMEDOUT
            raise TimeoutError(
                f"recv from rank {src_rank} (tag {tag}) timed out")
        _check(rc, "recv-meta")
        shape, dtype = _unpack_meta(header.tobytes())
        out = np.empty(shape, dtype=dtype)
        _check(self._lib.tc_recv_timeout(
            self._h, _buf(out), out.nbytes, src_rank, 2 * tag + 2, tmo),
            "recv")
        return out

    def destroy(self):
        if self._h is not None:
            self._lib.tc_destroy(self._h)
            self._h = None


_META_BYTES = 128


def _pack_meta(shape, dtype) -> bytes:
    s = (str(np.dtype(dtype).name) + "|" +
         ",".join(str(d) for d in shape)).encode()
    if len(s) > _META_BYTES - 1:
        raise ValueError("array rank too large for p2p metadata frame")
    return s + b"\0" * (_META_BYTES - len(s))


def _unpack_meta(raw: bytes):
    s = raw.split(b"\0", 1)[0].decode()
    name, _, dims = s.partition("|")
    shape = tuple(int(d) for d in dims.split(",")) if dims else ()
    return shape, np.dtype(name)
