"""Usage stats: opt-out local usage recording.

Reference analog: ``python/ray/_private/usage/usage_lib.py`` (P11). The
reference phones home unless ``RAY_USAGE_STATS_ENABLED=0``; this
environment has zero egress, so the report is only ever written to a
local JSON file (same schema spirit: library usage flags + counters),
and the same opt-out env var convention applies
(``RAY_TPU_USAGE_STATS_ENABLED=0``).
"""

from __future__ import annotations

import json
import os
import threading
import time

_lock = threading.Lock()
_usage: dict[str, int] = {}
_features: set[str] = set()


def enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def record_library_usage(name: str) -> None:
    """Called by libraries at import/first use (train/tune/serve/...)."""
    if not enabled():
        return
    with _lock:
        _features.add(name)


def record_extra_usage_tag(key: str, value: int = 1) -> None:
    if not enabled():
        return
    with _lock:
        _usage[key] = _usage.get(key, 0) + value


def usage_report() -> dict:
    with _lock:
        return {
            "timestamp": time.time(),
            "libraries": sorted(_features),
            "counters": dict(_usage),
            "enabled": enabled(),
        }


def write_report(path: str | None = None) -> str:
    path = path or os.path.join(
        os.path.expanduser("~"), ".ray_tpu", "usage_stats.json")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(usage_report(), f, indent=2)
    return path
