"""ctypes bindings for the C++ shared-memory object store.

Reference analog: the plasma client (``src/ray/object_manager/plasma/
client.cc``) — create/seal/get/release/delete with zero-copy reads. Unlike
the reference there is no store daemon: all processes attach the same shm
segment and the C++ library coordinates through a robust process-shared
mutex inside it (see ``src/store/shm_store.cc``).

Zero-copy: ``get`` returns a read-only ``memoryview`` directly over the
mapped segment; ``create`` returns a writable one. Buffers must be released
(``release``) when consumers are done so eviction can reclaim space.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libtpustore.so")
_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "src")

TS_OK = 0
TS_ERR = -1
TS_EXISTS = -2
TS_NOT_FOUND = -3
TS_OOM = -4
TS_TABLE_FULL = -5
TS_NOT_SEALED = -6
TS_TIMEOUT = -7

_build_lock = threading.Lock()

ID_LEN = 20


def _key(object_id: bytes) -> bytes:
    """Store keys are exactly 20 bytes; shorter ids are zero-padded."""
    if len(object_id) > ID_LEN:
        raise ValueError(f"object id longer than {ID_LEN} bytes")
    return object_id.ljust(ID_LEN, b"\x00")


def _ensure_built() -> str:
    src = os.path.join(_SRC, "store", "shm_store.cc")

    def stale() -> bool:
        if not os.path.exists(_LIB_PATH):
            return True
        # ABI/layout changes in the source must force a rebuild — a stale
        # library would miss symbols or silently corrupt the segment
        return (os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH))

    if stale():
        with _build_lock:
            if stale():
                subprocess.run(
                    ["make", "-C", os.path.abspath(_SRC)],
                    check=True,
                    capture_output=True,
                )
    return _LIB_PATH


def _load():
    lib = ctypes.CDLL(_ensure_built())
    u64 = ctypes.c_uint64
    p = ctypes.c_void_p
    lib.store_create.restype = p
    lib.store_create.argtypes = [ctypes.c_char_p, u64, u64]
    lib.store_attach.restype = p
    lib.store_attach.argtypes = [ctypes.c_char_p]
    lib.store_close.argtypes = [p]
    lib.store_base.restype = ctypes.c_void_p
    lib.store_base.argtypes = [p]
    lib.store_capacity.restype = u64
    lib.store_capacity.argtypes = [p]
    lib.store_create_object.restype = ctypes.c_int
    lib.store_create_object.argtypes = [p, ctypes.c_char_p, u64, u64,
                                        ctypes.POINTER(u64)]
    lib.store_seal.restype = ctypes.c_int
    lib.store_seal.argtypes = [p, ctypes.c_char_p]
    lib.store_seal_hold.restype = ctypes.c_int
    lib.store_seal_hold.argtypes = [p, ctypes.c_char_p]
    lib.store_get.restype = ctypes.c_int
    lib.store_get.argtypes = [p, ctypes.c_char_p, ctypes.c_int64,
                              ctypes.POINTER(u64), ctypes.POINTER(u64),
                              ctypes.POINTER(u64)]
    lib.store_release.restype = ctypes.c_int
    lib.store_release.argtypes = [p, ctypes.c_char_p]
    lib.store_delete.restype = ctypes.c_int
    lib.store_delete.argtypes = [p, ctypes.c_char_p]
    lib.store_abort.restype = ctypes.c_int
    lib.store_abort.argtypes = [p, ctypes.c_char_p]
    lib.store_contains.restype = ctypes.c_int
    lib.store_contains.argtypes = [p, ctypes.c_char_p]
    lib.store_get_many.restype = ctypes.c_int
    lib.store_get_many.argtypes = [p, ctypes.c_char_p, ctypes.c_int,
                                   ctypes.POINTER(u64), ctypes.POINTER(u64),
                                   ctypes.POINTER(ctypes.c_int)]
    lib.store_release_many.restype = ctypes.c_int
    lib.store_release_many.argtypes = [p, ctypes.c_char_p, ctypes.c_int]
    lib.store_evict_orphans.restype = ctypes.c_int
    lib.store_evict_orphans.argtypes = [p, u64]
    lib.store_release_pid.restype = ctypes.c_int
    lib.store_release_pid.argtypes = [p, u64]
    lib.store_spill_candidates.restype = ctypes.c_int
    lib.store_spill_candidates.argtypes = [p, u64, ctypes.c_char_p, u64, u64]
    lib.store_stats.argtypes = [p, ctypes.POINTER(u64 * 6)]
    return lib


_lib = None
_lib_lock = threading.Lock()


def get_lib():
    global _lib
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                _lib = _load()
    return _lib


class ShmStoreError(Exception):
    pass


class ObjectExistsError(ShmStoreError):
    pass


class ObjectNotFoundError(ShmStoreError):
    pass


class StoreFullError(ShmStoreError):
    pass


def _check(rc: int, what: str):
    if rc == TS_OK:
        return
    if rc == TS_EXISTS:
        raise ObjectExistsError(what)
    if rc in (TS_NOT_FOUND, TS_TIMEOUT):
        raise ObjectNotFoundError(what)
    if rc in (TS_OOM, TS_TABLE_FULL):
        raise StoreFullError(what)
    raise ShmStoreError(f"{what}: rc={rc}")


class _SegmentHandle:
    """Owns the C store handle's lifetime. The store object AND the cached
    whole-segment ctypes array both reference this handle (and nothing
    refers back), so plain refcounting — no cyclic GC — munmaps exactly
    when the last of {store object, escaped view} drops."""

    __slots__ = ("_lib", "_h", "_closed", "cleanup_lock")

    def __init__(self, lib, h):
        self._lib = lib
        self._h = h
        self._closed = False
        # Serializes munmap against the raylet's worker-death cleanup
        # calls (release_pid/evict_orphans): those run on RPC threads
        # and may still be inside the C store when teardown closes it —
        # without this, close() unmaps the segment under a thread
        # blocked on the in-segment mutex (observed SIGSEGV under
        # actor kill-flood churn). Hot-path ops stay lock-free: views
        # escaping past close are already the caller's contract.
        self.cleanup_lock = threading.Lock()

    def close(self):
        with self.cleanup_lock:
            if not self._closed:
                self._closed = True
                self._lib.store_close(self._h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ShmObjectStore:
    """One node's shared-memory object store (owner or attached client)."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False,
                 table_cap: int = 0):
        lib = get_lib()
        self._lib = lib
        self.name = name
        try:
            from ray_tpu.utils.config import get_config

            self.BATCH_WINDOW = get_config().store_batch_window
        except Exception:  # noqa: BLE001 - standalone use: class default
            pass
        if create:
            if capacity < (1 << 12):
                raise ValueError(
                    f"store capacity must be >= 4 KiB, got {capacity}")
            if table_cap == 0:
                # scale the object table with capacity: the C default
                # (64k entries) chokes small-object floods — a 256 MiB
                # store full of task returns needs hundreds of
                # thousands of entries (~96 B each; the table costs
                # <10% of the arena at this ratio)
                table_cap = min(max(1 << 16, capacity // 1024), 1 << 22)
            self._h = lib.store_create(name.encode(), capacity, table_cap)
        else:
            self._h = lib.store_attach(name.encode())
        if not self._h:
            raise ShmStoreError(
                f"failed to {'create' if create else 'attach'} store {name!r}"
            )
        self._base = lib.store_base(self._h)
        self.capacity = lib.store_capacity(self._h)
        self._closed = False
        # one whole-segment view, sliced per object: slicing a memoryview
        # is ~5x cheaper than a fresh from_address + cast per get. The
        # slice chain (slice -> segment array -> _anchor handle) keeps the
        # MAPPING alive while views escape, without a cycle through this
        # store object — see _SegmentHandle.
        self._handle = _SegmentHandle(lib, self._h)
        seg = (ctypes.c_ubyte * self.capacity).from_address(self._base)
        seg._anchor = self._handle
        self._seg_rw = memoryview(seg).cast("B")
        self._seg_ro = self._seg_rw.toreadonly()

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        """Force-unmap (caller's contract: no views may be in use after).
        Without an explicit close, the mapping is reclaimed by refcount
        when the last of {this object, escaped views} drops — there is
        deliberately no auto-close in __del__, which would munmap under
        a still-escaped view the moment the store object is dropped."""
        if not self._closed:
            self._closed = True
            self._handle.close()

    # -- object ops --------------------------------------------------------
    def _view(self, offset: int, size: int, readonly: bool) -> memoryview:
        seg = self._seg_ro if readonly else self._seg_rw
        return seg[offset:offset + size]

    def create(self, object_id: bytes, data_size: int,
               meta_size: int = 0) -> memoryview:
        """Allocate; returns a writable view of data+meta. Call seal() next."""
        off = ctypes.c_uint64()
        rc = self._lib.store_create_object(
            self._h, _key(object_id), data_size, meta_size, ctypes.byref(off))
        _check(rc, f"create {object_id.hex()}")
        return self._view(off.value, data_size + meta_size, readonly=False)

    def put(self, object_id: bytes, data: bytes | memoryview) -> None:
        """create + copy + seal convenience."""
        data = memoryview(data)
        buf = self.create(object_id, data.nbytes)
        buf[:] = data
        self.seal(object_id)

    def seal(self, object_id: bytes, hold: bool = False) -> None:
        """Seal a created object. ``hold=True`` converts the writer's ref
        into a tracked read ref instead of dropping it — the object is
        never evictable between seal and the node manager's pin; the
        caller must ``release`` after reporting it."""
        fn = self._lib.store_seal_hold if hold else self._lib.store_seal
        _check(fn(self._h, _key(object_id)), f"seal {object_id.hex()}")

    def get(self, object_id: bytes, timeout_ms: int = -1) -> memoryview:
        """Read-only zero-copy view of the data section (bumps refcount)."""
        off = ctypes.c_uint64()
        dsz = ctypes.c_uint64()
        msz = ctypes.c_uint64()
        rc = self._lib.store_get(self._h, _key(object_id), timeout_ms,
                                 ctypes.byref(off), ctypes.byref(dsz),
                                 ctypes.byref(msz))
        _check(rc, f"get {object_id.hex()}")
        return self._view(off.value, dsz.value, readonly=True)

    def release(self, object_id: bytes) -> None:
        self._lib.store_release(self._h, _key(object_id))

    # one C call holds the process-shared store mutex for its whole
    # batch: chunking here bounds the lock-hold time as a property of
    # the API, not of any one caller (the driver's 4096 get window was
    # previously the only thing keeping a huge batch from stalling
    # every other store client on the node). Flag store_batch_window
    # (instance attr set at construction; class attr documents default).
    BATCH_WINDOW = 4096

    def get_many(self, object_ids: list[bytes]) -> list:
        """Batched non-blocking get, chunked to ``BATCH_WINDOW`` ids per
        C call. Returns a view per id, or None where the object is
        absent/unsealed; every non-None entry holds a read ref — pair
        with release_many over the SAME hit set."""
        seg = self._seg_ro
        out: list = []
        for i in range(0, len(object_ids), self.BATCH_WINDOW):
            part = object_ids[i:i + self.BATCH_WINDOW]
            n = len(part)
            keys = b"".join(map(_key, part))
            offs = (ctypes.c_uint64 * n)()
            dszs = (ctypes.c_uint64 * n)()
            rcs = (ctypes.c_int * n)()
            self._lib.store_get_many(self._h, keys, n, offs, dszs, rcs)
            out.extend(
                seg[offs[k]:offs[k] + dszs[k]] if rcs[k] == TS_OK
                else None for k in range(n))
        return out

    def release_many(self, object_ids: list[bytes]) -> None:
        for i in range(0, len(object_ids), self.BATCH_WINDOW):
            part = object_ids[i:i + self.BATCH_WINDOW]
            keys = b"".join(map(_key, part))
            self._lib.store_release_many(self._h, keys, len(part))

    def delete(self, object_id: bytes) -> bool:
        return self._lib.store_delete(self._h, _key(object_id)) == TS_OK

    def abort(self, object_id: bytes) -> bool:
        """Free an UNSEALED entry this process created (failed chunked
        write/pull cleanup); refuses sealed entries and other writers'."""
        return self._lib.store_abort(self._h, _key(object_id)) == TS_OK

    def try_delete(self, object_id: bytes) -> int:
        """Raw delete status: TS_OK, TS_NOT_FOUND (already gone), or
        TS_ERR (still referenced) — spill needs the distinction."""
        return self._lib.store_delete(self._h, _key(object_id))

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.store_contains(self._h, _key(object_id)))

    def evict_orphans(self, pid: int = 0) -> int:
        """Reclaim unsealed entries of a dead writer pid (0 = any writer)."""
        with self._handle.cleanup_lock:
            if self._handle._closed:
                return 0
            return self._lib.store_evict_orphans(self._h, pid)

    def release_pid(self, pid: int) -> int:
        """Drop all read refs held by a dead process (crash cleanup)."""
        with self._handle.cleanup_lock:
            if self._handle._closed:
                return 0
            return self._lib.store_release_pid(self._h, pid)

    def spill_candidates(self, target_bytes: int, max_out: int = 512,
                         pin_pid: int = 0) -> list[bytes]:
        """LRU-ordered sealed object ids totaling ``target_bytes`` of
        payload whose only refs are ``pin_pid``'s pin (0 = unreferenced
        entries) — the node manager's spill-victim query."""
        buf = ctypes.create_string_buffer(max_out * ID_LEN)
        n = self._lib.store_spill_candidates(
            self._h, target_bytes, buf, max_out, pin_pid)
        raw = buf.raw
        return [raw[i * ID_LEN:(i + 1) * ID_LEN] for i in range(max(n, 0))]

    def pin(self, object_id: bytes) -> bool:
        """Hold a read ref WITHOUT mapping a view (the node manager's
        primary-copy pin — reference: raylet pinning via
        ``PinObjectIDs``; pinned objects are never LRU-evicted, only
        spilled). Returns False if the object is not sealed yet."""
        off = ctypes.c_uint64()
        dsz = ctypes.c_uint64()
        msz = ctypes.c_uint64()
        rc = self._lib.store_get(self._h, _key(object_id), -1,
                                 ctypes.byref(off), ctypes.byref(dsz),
                                 ctypes.byref(msz))
        return rc == TS_OK

    def unpin(self, object_id: bytes) -> None:
        self.release(object_id)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 6)()
        self._lib.store_stats(self._h, ctypes.byref(out))
        return {
            "capacity": out[0],
            "bytes_allocated": out[1],
            "num_objects": out[2],
            "num_evictions": out[3],
            "bytes_evicted": out[4],
            "lru_clock": out[5],
        }
