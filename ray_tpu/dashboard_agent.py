"""Per-node dashboard agent: a separate observability process.

Reference analog: ``dashboard/agent.py:32`` +
``dashboard/modules/reporter/reporter_agent.py`` — every node runs an
agent process next to its raylet that samples host stats (psutil) and
serves profiling, so observability traffic (stack dumps, flamegraph
sampling, host metrics) does NOT ride the raylet's data plane. The head
dashboard and the state API query agents directly via the agent address
each node registers in the GCS node table.

The agent's only raylet dependency is the worker LIST (one lightweight
RPC per query — the raylet owns the pool); stacks/profiles then dial
each worker's push port directly. The agent holds a blocking connection
to its raylet and exits when it drops, so a dead node never leaves an
orphan agent.
"""

from __future__ import annotations

import json
import sys
import threading

from ray_tpu.runtime.rpc import ReconnectingRpcClient, RpcClient, RpcServer


class DashboardAgent(RpcServer):
    def __init__(self, *, node_id: str, raylet_address, gcs_address,
                 spill_dir: str | None = None, log_dir: str | None = None,
                 host: str = "127.0.0.1"):
        super().__init__(host, 0)
        self.node_id = node_id
        self.raylet_address = tuple(raylet_address)
        self.gcs_address = tuple(gcs_address)
        self.spill_dir = spill_dir
        self.log_dir = log_dir
        self._raylet = ReconnectingRpcClient(self.raylet_address)

    def start(self):
        super().start()
        try:
            gcs = RpcClient(self.gcs_address)
            gcs.call("register_agent", node_id=self.node_id,
                     address=list(self.address))
            gcs.close()
        except Exception:  # noqa: BLE001 - head queries fall back to raylet
            pass
        return self

    # -- host metrics (psutil sampling lives HERE, not in the raylet) --

    def rpc_host_stats(self, conn, send_lock):
        from ray_tpu.util.profiling import host_stats

        return host_stats(self.spill_dir)

    def rpc_agent_info(self, conn, send_lock):
        import os

        return {"node_id": self.node_id, "pid": os.getpid(),
                "raylet_address": list(self.raylet_address)}

    # -- worker observability (direct dials to worker push ports) ------

    def _targets(self, worker_id: str | None):
        return self._raylet.call("worker_targets", worker_id=worker_id,
                                 timeout=10) or []

    def rpc_worker_stacks(self, conn, send_lock, *,
                          worker_id: str | None = None):
        out = {}
        out_lock = threading.Lock()

        def query(wid, addr):
            client = None
            try:
                client = RpcClient(tuple(addr), timeout=5)
                stacks = client.call("dump_stacks")
            except Exception as e:  # noqa: BLE001 - worker busy/gone
                stacks = {"error": repr(e)}
            finally:
                if client is not None:
                    client.close()
            with out_lock:
                out[wid] = stacks

        threads = [threading.Thread(target=query, args=tuple(t),
                                    daemon=True)
                   for t in self._targets(worker_id)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=8)
        return out

    def rpc_stuck_calls(self, conn, send_lock, *, threshold_s=None):
        # proxied THROUGH the raylet (not dialed per worker here): the
        # node answer must include the raylet's own in-flight registry,
        # which only the raylet process can read
        return self._raylet.call("stuck_calls", threshold_s=threshold_s,
                                 timeout=12)

    def rpc_flight_record(self, conn, send_lock, *,
                          worker_id: str | None = None, last_s=None):
        return self._raylet.call("flight_record", worker_id=worker_id,
                                 last_s=last_s, timeout=12)

    def rpc_dump_stacks(self, conn, send_lock):
        # proxied to the raylet: the one-shot dump must show the RAYLET
        # process's threads, which only it can read
        return self._raylet.call("dump_stacks", timeout=12)

    # -- node log files (raw reads off the observability plane; the
    # ingested/attributed view lives in the GCS LogStore) --------------

    def rpc_list_log_files(self, conn, send_lock):
        import os

        if not self.log_dir:
            return {"files": [], "error": "agent has no log_dir"}
        files = []
        try:
            for name in sorted(os.listdir(self.log_dir)):
                path = os.path.join(self.log_dir, name)
                try:
                    files.append({"name": name,
                                  "size": os.path.getsize(path)})
                except OSError:
                    continue
        except OSError as e:
            return {"files": [], "error": repr(e)}
        return {"files": files, "log_dir": self.log_dir}

    def rpc_read_log_file(self, conn, send_lock, *, name: str,
                          tail_bytes: int = 1 << 16):
        """Raw tail of one capture file (debugging escape hatch when
        the stored ring has already evicted the lines)."""
        import os

        if not self.log_dir:
            return {"error": "agent has no log_dir"}
        if os.sep in name or name.startswith("."):
            return {"error": f"bad log file name {name!r}"}
        path = os.path.join(self.log_dir, name)
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(max(0, size - int(tail_bytes)))
                data = f.read(int(tail_bytes))
        except OSError as e:
            return {"error": repr(e)}
        return {"name": name, "size": size,
                "data": data.decode("utf-8", "replace")}

    def rpc_profile_node(self, conn, send_lock, *, duration_s: float = 2.0,
                         hz: int = 100, include_workers: bool = True,
                         include_raylet: bool = True):
        # proxied: the node window must include the raylet sampling
        # itself, and the raylet already owns the worker fan-out
        return self._raylet.call(
            "profile_node", duration_s=duration_s, hz=hz,
            include_workers=include_workers,
            include_raylet=include_raylet, timeout=duration_s + 35)

    def rpc_profile_worker(self, conn, send_lock, *, worker_id: str,
                           duration_s: float = 2.0, hz: int = 100):
        targets = self._targets(worker_id)
        if not targets:
            return {"not_found": True,
                    "error": f"no live worker {worker_id!r} on this node"}
        _, addr = targets[0]
        client = None
        try:
            client = RpcClient(tuple(addr), timeout=duration_s + 30)
            return client.call("profile", duration_s=duration_s, hz=hz)
        except Exception as e:  # noqa: BLE001
            return {"error": repr(e)}
        finally:
            if client is not None:
                client.close()


def main():
    import socket

    cfg = json.loads(sys.argv[1])
    agent = DashboardAgent(
        node_id=cfg["node_id"],
        raylet_address=tuple(cfg["raylet_address"]),
        gcs_address=tuple(cfg["gcs_address"]),
        spill_dir=cfg.get("spill_dir"),
        log_dir=cfg.get("log_dir"),
    ).start()
    print(json.dumps({"address": agent.address}), flush=True)
    # lifetime = the raylet's: block on a dedicated connection and exit
    # the moment it drops (no orphan agents after node death)
    try:
        watch = socket.create_connection(tuple(cfg["raylet_address"]))
        while True:
            if not watch.recv(1 << 12):
                break
    except OSError:
        pass
    agent.stop()


if __name__ == "__main__":
    main()
