"""Accelerator type constants (reference:
``python/ray/util/accelerators/accelerators.py:9-11`` — TPU generations
as schedulable resource labels, e.g.
``@remote(resources={TPU_V5P: 1})``)."""

TPU_V2 = "TPU-V2"
TPU_V3 = "TPU-V3"
TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5LITEPOD"
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"

ALL_TPU_TYPES = (TPU_V2, TPU_V3, TPU_V4, TPU_V5E, TPU_V5P, TPU_V6E)


def tpu_generation_from_kind(device_kind: str) -> str | None:
    """Map a JAX ``device_kind`` string to the resource label."""
    kind = device_kind.lower()
    for label in ALL_TPU_TYPES:
        gen = label.split("-", 1)[1].lower()
        if gen in kind.replace(" ", ""):
            return label
    if "v5 lite" in kind or "v5e" in kind:
        return TPU_V5E
    if "v6 lite" in kind or "v6e" in kind:
        return TPU_V6E
    return None
