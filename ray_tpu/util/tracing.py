"""Distributed tracing plane: spans around every remote call, collected
cluster-wide through the metrics-plane transport.

Reference analog: ``python/ray/util/tracing/tracing_helper.py``
(``_inject_tracing_into_function:326``, ``_inject_tracing_into_class:450``)
— the reference wraps every remote function with OpenTelemetry spans and
propagates context in task metadata, exporting through an OTel exporter
each process configures. Here there is no OTel dependency: context rides
task specs AND a ``_trace`` header on every framed RPC; finished spans
land in a per-process bounded ring drained by the MetricsPusher into the
GCS :class:`TraceStore` (same drop-not-block contract as metric frames),
with an optional JSONL file exporter kept for local runs.

Four cooperating pieces:

- **Propagation** — ``submission_context``/``execution_span`` thread
  context through task specs (tasks + actor calls); ``wire_context``/
  ``server_span`` do the same for raw framed RPCs so spans parent across
  driver→GCS→raylet→worker hops.
- **Collection** — ``_emit`` feeds a bounded push ring; the metrics
  pusher ships it via ``push_spans`` into the GCS ``TraceStore`` ring
  (tail-based retention: error/slow traces survive longest, normals are
  sampled 1-in-``trace_sample_n``).
- **Flight recorder** — every process keeps the last
  ``trace_flight_window_s`` of spans + RPC events in memory;
  ``dump_flight`` writes them on SIGTERM (``install_crash_dump``) or on
  demand via ``util.state.flight_record``.
- **Stuck-call watchdog** — ``call_started``/``call_finished`` maintain
  an in-flight registry (RPCs, pulls, leases) surfaced through
  ``local_stuck_calls`` / ``util.state.stuck_calls``.

Usage:
    ray_tpu.util.tracing.enable_tracing()          # collected plane
    ray_tpu.util.tracing.enable_tracing("/tmp/tr") # + JSONL exporter
    ... run work ...
    trace = ray_tpu.util.state.get_trace(trace_id)

Span records: {"name", "trace_id", "span_id", "parent_id", "start",
"duration", "pid", "kind"} (+ optional "attrs", "error").
``to_chrome_trace`` converts to chrome://tracing format (complements
ray_tpu.timeline(), which covers task lifecycle events without
cross-task parentage).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import signal
import tempfile
import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass

logger = logging.getLogger("ray_tpu.tracing")

_ENV_DIR = "RAY_TPU_TRACE_DIR"
_ENV_ON = "RAY_TPU_TRACE_ENABLED"

# ambient span context (submission captures it; execution restores it)
_current: contextvars.ContextVar["SpanContext | None"] = \
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None)

_write_lock = threading.Lock()


def _cfg_attr(name: str, default):
    """Config flag with an import-cycle-safe fallback (tracing is
    imported by modules the config module itself pulls in)."""
    try:
        from ray_tpu.utils.config import get_config

        return getattr(get_config(), name, default)
    except Exception:  # pragma: no cover - early-import fallback
        return default


@dataclass
class SpanContext:
    trace_id: str
    span_id: str

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @staticmethod
    def from_dict(d: dict | None) -> "SpanContext | None":
        if not d:
            return None
        return SpanContext(d["trace_id"], d["span_id"])


def enable_tracing(trace_dir: str | None = None) -> None:
    """Turn tracing on for this process AND every worker spawned after
    (the switch is inherited through the environment, like the
    reference's tracing startup hook). ``trace_dir`` is optional: with
    one, finished spans are ALSO appended to per-pid JSONL files;
    without one, collection is ring-buffer + pusher only."""
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        os.environ[_ENV_DIR] = trace_dir
    os.environ[_ENV_ON] = "1"
    global _enabled_cache
    _enabled_cache = (True, time.monotonic())


def disable_tracing() -> None:
    os.environ.pop(_ENV_DIR, None)
    os.environ.pop(_ENV_ON, None)
    global _enabled_cache
    _enabled_cache = (False, time.monotonic())


# (value, checked_at): is_enabled sits on the per-call submit hot path —
# an os.environ read per submit measurably taxes 10k+ calls/s, so the
# env probe is cached with a short TTL. enable/disable invalidate
# immediately; a worker learning of tracing purely via inherited env
# sees it within the TTL (observability-only lag).
_enabled_cache: tuple[bool, float] = (False, -1.0)


def is_enabled() -> bool:
    global _enabled_cache
    value, checked = _enabled_cache
    now = time.monotonic()
    if now - checked > 0.2:
        on = os.environ.get(_ENV_ON)
        value = bool(os.environ.get(_ENV_DIR)) or \
            bool(on and on not in ("0", "false", "False"))
        _enabled_cache = (value, now)
    return value


def current_context() -> SpanContext | None:
    return _current.get()


def bind(ctx: SpanContext | None):
    """Set the ambient context explicitly (worker threads don't inherit
    contextvars — chunked pulls and executor threads re-bind the
    captured context). Returns the reset token."""
    return _current.set(ctx)


# ---------------------------------------------------------------------------
# span sinks: push ring (drained by the metrics pusher), flight ring
# (recent-history recorder), optional JSONL file
# ---------------------------------------------------------------------------

_ring_lock = threading.Lock()
_push_ring: deque | None = None
_flight: deque | None = None


def _rings() -> tuple[deque, deque]:
    global _push_ring, _flight
    if _push_ring is None:
        with _ring_lock:
            if _push_ring is None:
                _flight = deque(
                    maxlen=int(_cfg_attr("trace_flight_spans", 4096)))
                _push_ring = deque(
                    maxlen=int(_cfg_attr("trace_buffer_spans", 4096)))
    return _push_ring, _flight


def drain_spans(max_n: int | None = None) -> list[dict]:
    """Pop up to ``max_n`` finished spans for shipment (pusher tick).
    Oldest first; the ring itself already dropped anything past its
    bound, so drain never blocks and never grows."""
    ring, _ = _rings()
    if not ring:
        return []
    if max_n is None:
        max_n = int(_cfg_attr("trace_push_max_spans", 1024))
    out: list[dict] = []
    with _ring_lock:
        while ring and len(out) < max_n:
            out.append(ring.popleft())
    return out


def requeue_spans(spans: list[dict]) -> None:
    """Put spans back at the FRONT after a failed push (bounded: the
    ring's maxlen still drops the overflow — drop-not-block)."""
    if not spans:
        return
    ring, _ = _rings()
    with _ring_lock:
        ring.extendleft(reversed(spans))


def _file_sink(record: dict) -> None:
    trace_dir = os.environ.get(_ENV_DIR)
    if not trace_dir:
        return
    path = os.path.join(trace_dir, f"spans-{os.getpid()}.jsonl")
    line = json.dumps(record)
    cap = int(_cfg_attr("trace_file_max_bytes", 64 << 20))
    with _write_lock:
        with open(path, "a") as f:
            f.write(line + "\n")
            size = f.tell()
        if cap > 0 and size > cap:
            # single-generation rotation: the previous generation is
            # overwritten, bounding disk at ~2x the cap per process
            try:
                os.replace(path, path + ".1")
            except OSError:  # pragma: no cover - fs race
                pass


def _emit(record: dict) -> None:
    ring, flight = _rings()
    with _ring_lock:
        ring.append(record)
        flight.append(record)
    _file_sink(record)


@contextlib.contextmanager
def span(name: str, *, kind: str = "local",
         parent: SpanContext | None = None,
         attrs: dict | None = None):
    """Record one span; inside the block, the ambient context points at
    it (children created here parent to it). An escaping exception marks
    the span ``error`` (tail-based retention keeps such traces)."""
    if not is_enabled():
        yield None
        return
    if parent is None:
        parent = _current.get()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
        span_id=uuid.uuid4().hex[:16],
    )
    token = _current.set(ctx)
    start = time.time()
    error = False
    try:
        yield ctx
    except BaseException:
        error = True
        raise
    finally:
        _current.reset(token)
        rec = {
            "name": name,
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_id": parent.span_id if parent else None,
            "start": start,
            "duration": time.time() - start,
            "pid": os.getpid(),
            "kind": kind,
        }
        if attrs:
            rec["attrs"] = attrs
        if error:
            rec["error"] = True
        _emit(rec)


def emit(name: str, *, start: float, duration: float,
         parent: SpanContext | None = None, kind: str = "local",
         attrs: dict | None = None,
         ctx: SpanContext | None = None) -> SpanContext:
    """Emit one already-timed span (the serve engine stamps queue_wait /
    prefill / pipeline_stall from its own monotonic breakdown and emits
    them after the fact). Returns the span's context so stage children
    can parent to it."""
    if ctx is None:
        ctx = SpanContext(
            trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
            span_id=uuid.uuid4().hex[:16],
        )
    rec = {
        "name": name,
        "trace_id": ctx.trace_id,
        "span_id": ctx.span_id,
        "parent_id": parent.span_id if parent else None,
        "start": start,
        "duration": duration,
        "pid": os.getpid(),
        "kind": kind,
    }
    if attrs:
        rec["attrs"] = attrs
    _emit(rec)
    return ctx


# ---------------------------------------------------------------------------
# RPC header propagation (runtime/rpc.py attaches/restores these)
# ---------------------------------------------------------------------------

def wire_context():
    """Compact ``(trace_id, span_id)`` for the RPC ``_trace`` header, or
    None when tracing is off / no ambient span (untraced RPCs carry no
    header and produce no server spans — heartbeats stay span-free)."""
    if not is_enabled():
        return None
    cur = _current.get()
    if cur is None:
        return None
    return (cur.trace_id, cur.span_id)


@contextlib.contextmanager
def server_span(method: str, wire):
    """Server-dispatch side of RPC propagation: restore the caller's
    context so handler-side spans (and nested RPCs) parent across the
    hop. No-op without a header."""
    if wire is None or not is_enabled():
        yield None
        return
    try:
        parent = SpanContext(str(wire[0]), str(wire[1]))
    except (TypeError, IndexError, KeyError):
        yield None
        return
    with span(f"rpc:{method}", kind="rpc", parent=parent) as ctx:
        yield ctx


# ---------------------------------------------------------------------------
# stuck-call watchdog: in-flight call registry
# ---------------------------------------------------------------------------

_inflight_lock = threading.Lock()
_inflight: dict[int, dict] = {}
_inflight_next = 0


def call_started(kind: str, detail: str, target=None) -> int:
    """Register one in-flight call (RPC / pull / lease / actor call).
    Always on: two locked dict ops per call are noise next to a socket
    round trip, and the watchdog must see calls that hung BEFORE anyone
    thought to enable tracing."""
    global _inflight_next
    cur = _current.get()
    entry = {
        "kind": kind,
        "detail": detail,
        "target": target,
        "start": time.time(),
        "mono": time.monotonic(),
        "pid": os.getpid(),
        "thread": threading.current_thread().name,
        "trace_id": cur.trace_id if cur else None,
        "span_id": cur.span_id if cur else None,
    }
    with _inflight_lock:
        _inflight_next += 1
        token = _inflight_next
        _inflight[token] = entry
    return token


def call_finished(token: int | None) -> None:
    if token is None:
        return
    with _inflight_lock:
        _inflight.pop(token, None)


class _Inflight:
    """Class-based (not generator) context manager: task execution is a
    hot path and this runs with tracing OFF too."""

    __slots__ = ("_token",)

    def __init__(self, kind: str, detail: str, target=None):
        self._token = call_started(kind, detail, target)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        call_finished(self._token)
        return False


def inflight(kind: str, detail: str, target=None) -> _Inflight:
    """Scope-shaped call_started/call_finished pair, for call sites
    where the whole in-flight window is one lexical block (task
    execution); registered-RPC style token threading stays available
    for split start/finish sites."""
    return _Inflight(kind, detail, target)


def local_stuck_calls(threshold_s: float | None = None) -> list[dict]:
    """In-flight calls older than ``threshold_s`` (default
    ``trace_stuck_threshold_s``), oldest first, with their parent span
    chain coordinates (trace_id/span_id) when the call was traced."""
    if threshold_s is None:
        threshold_s = float(_cfg_attr("trace_stuck_threshold_s", 10.0))
    now = time.monotonic()
    with _inflight_lock:
        entries = [dict(e) for e in _inflight.values()
                   if now - e["mono"] >= threshold_s]
    for e in entries:
        e["age_s"] = now - e.pop("mono")
    entries.sort(key=lambda e: -e["age_s"])
    # a stuck TASK report is actionable without a second query: append
    # the hung task's last captured log lines (needs the in-process
    # capture; target carries the task_id the execution bracket stamps)
    try:
        from ray_tpu.runtime import log_plane as _log_plane

        if _log_plane.active_capture() is not None:
            for e in entries:
                if e.get("kind") in ("task", "actor_task") \
                        and e.get("target"):
                    e["log_tail"] = _log_plane.recent_lines(
                        e["target"], 5)
    except Exception:  # pragma: no cover - teardown
        pass
    return entries


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def record_event(name: str, **attrs) -> None:
    """Append one point event (RPC drop, router decision, lease grant)
    to the flight ring only — never shipped, only dumped."""
    if not is_enabled():
        return
    _, flight = _rings()
    rec = {"event": name, "ts": time.time(), "pid": os.getpid()}
    if attrs:
        rec.update(attrs)
    with _ring_lock:
        flight.append(rec)


def flight_snapshot(last_s: float | None = None) -> dict:
    """The last ``last_s`` seconds (default ``trace_flight_window_s``)
    of spans + events, plus every currently in-flight call. Pure local
    memory — works while the GCS is unreachable."""
    if last_s is None:
        last_s = float(_cfg_attr("trace_flight_window_s", 30.0))
    cutoff = time.time() - last_s
    _, flight = _rings()
    with _ring_lock:
        records = list(flight)
    spans_out, events_out = [], []
    for r in records:
        if "event" in r:
            if r["ts"] >= cutoff:
                events_out.append(r)
        elif r["start"] + r.get("duration", 0.0) >= cutoff:
            spans_out.append(r)
    # a crashed/partitioned worker's last words ride the dump: the last
    # ~50 captured log lines (empty when no capture is installed)
    try:
        from ray_tpu.runtime import log_plane as _log_plane

        log_tail = _log_plane.log_tail(50)
    except Exception:  # pragma: no cover - teardown
        log_tail = []
    return {
        "pid": os.getpid(),
        "ts": time.time(),
        "window_s": last_s,
        "spans": spans_out,
        "events": events_out,
        "inflight": local_stuck_calls(0.0),
        "log_tail": log_tail,
    }


def local_trace(trace_id: str) -> list[dict]:
    """Spans of one trace still in the local flight ring (local-mode
    ``util.state.get_trace`` backend)."""
    _, flight = _rings()
    with _ring_lock:
        records = list(flight)
    return sorted((r for r in records
                   if "event" not in r and r.get("trace_id") == trace_id),
                  key=lambda r: r["start"])


def dump_flight(path: str | None = None, last_s: float | None = None) -> str:
    """Write the flight snapshot as JSON; returns the path. Defaults to
    ``flight-<pid>-<ts>.json`` in the trace dir (or tempdir)."""
    snap = flight_snapshot(last_s)
    if path is None:
        base = os.environ.get(_ENV_DIR) or tempfile.gettempdir()
        path = os.path.join(
            base, f"flight-{os.getpid()}-{int(snap['ts'])}.json")
    with open(path, "w") as f:
        json.dump(snap, f)
    return path


_crash_dump_installed = False


def install_crash_dump() -> bool:
    """Chain a SIGTERM handler that dumps the flight ring before the
    process dies (local files only — no network, so it works through a
    partition). Safe off the main thread (no-op there) and idempotent."""
    global _crash_dump_installed
    if _crash_dump_installed:
        return True
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            try:
                if is_enabled():
                    dump_flight()
            except Exception:  # pragma: no cover - dying anyway
                pass
            if callable(prev) and prev not in (
                    signal.SIG_IGN, signal.SIG_DFL):
                prev(signum, frame)
            else:
                raise SystemExit(143)

        signal.signal(signal.SIGTERM, _on_term)
        _crash_dump_installed = True
        return True
    except ValueError:  # not the main thread
        return False


# ---------------------------------------------------------------------------
# task-spec propagation (unchanged wire shape; api.py calls these)
# ---------------------------------------------------------------------------

def submission_context(function_name: str) -> dict | None:
    """Called at .remote() time: returns the wire context for the spec
    (a fresh 'submit' span parented to the ambient one)."""
    if not is_enabled():
        return None
    parent = _current.get()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
        span_id=uuid.uuid4().hex[:16],
    )
    _emit({
        "name": f"submit:{function_name}",
        "trace_id": ctx.trace_id,
        "span_id": ctx.span_id,
        "parent_id": parent.span_id if parent else None,
        "start": time.time(),
        "duration": 0.0,
        "pid": os.getpid(),
        "kind": "submit",
    })
    wire = ctx.to_dict()
    # Cluster-mode workers are spawned by the RAYLET, whose environ never
    # saw the driver's enable_tracing() — so the trace dir must ride the
    # wire context, not env inheritance.
    wire["trace_dir"] = os.environ.get(_ENV_DIR)
    return wire


@contextlib.contextmanager
def execution_span(function_name: str, wire_ctx: dict | None):
    """Wraps task execution; parents to the submitter's span."""
    if wire_ctx is None:
        yield
        return
    global _enabled_cache
    wire_dir = wire_ctx.get("trace_dir")
    changed = False
    if wire_dir and os.environ.get(_ENV_DIR) != wire_dir:
        # adopt/sync the submitter's trace dir: workers are spawned by
        # the raylet (no env inheritance from the driver), and a warm
        # worker must follow the driver when it switches directories
        os.environ[_ENV_DIR] = wire_dir
        changed = True
    if not os.environ.get(_ENV_ON):
        # a wire context only exists when the submitter traces: adopt
        # the dir-less switch too, so worker-side spans reach the ring
        os.environ[_ENV_ON] = "1"
        changed = True
    if changed:
        _enabled_cache = (True, time.monotonic())
    if not is_enabled():
        yield
        return
    with span(f"run:{function_name}", kind="task",
              parent=SpanContext.from_dict(wire_ctx)):
        yield


# ---------------------------------------------------------------------------
# GCS-side collected store
# ---------------------------------------------------------------------------

class TraceStore:
    """Bounded trace ring on the GCS with tail-based retention.

    Spans arrive via ``push_spans`` grouped here by trace_id. When over
    budget (``max_traces`` traces / ``max_spans`` total spans), eviction
    walks classes in order: unsampled-normal first (trace_id hash not
    selected by the 1-in-``sample_n`` sampler), then sampled-normal,
    then error/slow — so the traces most worth keeping die last. Within
    a class, oldest-activity first."""

    def __init__(self, max_traces: int = 512, max_spans: int = 20000,
                 sample_n: int = 1, slow_s: float = 1.0,
                 per_trace_spans: int = 1024):
        self.max_traces = max(1, int(max_traces))
        self.max_spans = max(1, int(max_spans))
        self.sample_n = max(1, int(sample_n))
        self.slow_s = float(slow_s)
        self.per_trace_spans = max(1, int(per_trace_spans))
        self._lock = threading.Lock()
        # trace_id -> {"spans", "first", "last", "error", "slow", "srcs"}
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._total_spans = 0
        self.dropped_spans = 0
        self.evicted_traces = 0

    def _sampled(self, trace_id: str) -> bool:
        if self.sample_n <= 1:
            return True
        try:
            return int(trace_id[:8], 16) % self.sample_n == 0
        except ValueError:
            return True

    def _class_of(self, t: dict, trace_id: str) -> int:
        if t["error"] or t["slow"]:
            return 2
        return 1 if self._sampled(trace_id) else 0

    def ingest(self, src: str, spans: list[dict]) -> int:
        accepted = 0
        with self._lock:
            for s in spans:
                tid = s.get("trace_id")
                if not tid or "start" not in s:
                    self.dropped_spans += 1
                    continue
                t = self._traces.get(tid)
                if t is None:
                    t = {"spans": [], "first": s["start"], "last": 0.0,
                         "error": False, "slow": False, "srcs": set()}
                    self._traces[tid] = t
                if len(t["spans"]) >= self.per_trace_spans:
                    self.dropped_spans += 1
                    continue
                t["spans"].append(s)
                self._total_spans += 1
                accepted += 1
                end = s["start"] + s.get("duration", 0.0)
                t["first"] = min(t["first"], s["start"])
                t["last"] = max(t["last"], end)
                if s.get("error"):
                    t["error"] = True
                if s.get("duration", 0.0) >= self.slow_s:
                    t["slow"] = True
                if src:
                    t["srcs"].add(src)
            self._evict_locked()
        return accepted

    def _evict_locked(self) -> None:
        while (len(self._traces) > self.max_traces
               or self._total_spans > self.max_spans):
            victim = None
            for klass in (0, 1, 2):
                candidates = [(t["last"], tid)
                              for tid, t in self._traces.items()
                              if self._class_of(t, tid) == klass]
                if candidates:
                    victim = min(candidates)[1]
                    break
            if victim is None:  # pragma: no cover - defensive
                break
            gone = self._traces.pop(victim)
            self._total_spans -= len(gone["spans"])
            self.evicted_traces += 1

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            t = self._traces.get(trace_id)
            if t is None:
                return None
            spans = sorted(t["spans"], key=lambda s: s["start"])
            return {
                "trace_id": trace_id,
                "spans": spans,
                "first": t["first"],
                "last": t["last"],
                "error": t["error"],
                "slow": t["slow"],
                "srcs": sorted(t["srcs"]),
            }

    def list(self, limit: int = 50) -> list[dict]:
        with self._lock:
            items = [
                {
                    "trace_id": tid,
                    "spans": len(t["spans"]),
                    "first": t["first"],
                    "last": t["last"],
                    "duration_s": max(0.0, t["last"] - t["first"]),
                    "error": t["error"],
                    "slow": t["slow"],
                    "srcs": sorted(t["srcs"]),
                    "root": next(
                        (s["name"] for s in t["spans"]
                         if not s.get("parent_id")),
                        t["spans"][0]["name"] if t["spans"] else ""),
                }
                for tid, t in self._traces.items()
            ]
        items.sort(key=lambda i: -i["last"])
        return items[:max(0, int(limit))]

    def stats(self) -> dict:
        with self._lock:
            return {"traces": len(self._traces),
                    "spans": self._total_spans,
                    "dropped_spans": self.dropped_spans,
                    "evicted_traces": self.evicted_traces}


def build_waterfall(spans: list[dict]) -> list[dict]:
    """Depth-first waterfall rows for a trace: each span with its tree
    depth and millisecond offset from the trace start (the dashboard
    renders these directly as offset/width bars)."""
    if not spans:
        return []
    spans = sorted(spans, key=lambda s: (s["start"], s.get("name", "")))
    t0 = spans[0]["start"]
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str | None, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        pid = s.get("parent_id")
        if pid and pid in by_id:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    rows: list[dict] = []

    def _walk(s: dict, depth: int) -> None:
        rows.append({
            "name": s["name"],
            "span_id": s["span_id"],
            "parent_id": s.get("parent_id"),
            "depth": depth,
            "kind": s.get("kind"),
            "pid": s.get("pid"),
            "start": s["start"],
            "duration": s.get("duration", 0.0),
            "offset_ms": (s["start"] - t0) * 1e3,
            "dur_ms": s.get("duration", 0.0) * 1e3,
            "error": bool(s.get("error")),
            "attrs": s.get("attrs"),
        })
        for c in children.get(s["span_id"], ()):
            _walk(c, depth + 1)

    for r in roots:
        _walk(r, 0)
    return rows


# ---------------------------------------------------------------------------
# file exporter (kept for local runs; bounded + streaming)
# ---------------------------------------------------------------------------

def iter_spans(trace_dir: str):
    """Stream span records from a trace dir without loading every file
    into memory. Rotated generations (``.jsonl.1``) are yielded before
    their live file so a per-pid stream stays roughly chronological."""
    if not os.path.isdir(trace_dir):
        return
    names = [fn for fn in os.listdir(trace_dir)
             if fn.startswith("spans-")
             and (fn.endswith(".jsonl") or fn.endswith(".jsonl.1"))]
    # (base name, generation) — generation 0 is the rotated (older) file
    names.sort(key=lambda fn: (
        fn[:-2] if fn.endswith(".1") else fn,
        0 if fn.endswith(".1") else 1))
    for fn in names:
        try:
            with open(os.path.join(trace_dir, fn)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield json.loads(line)
        except FileNotFoundError:  # rotated away mid-iteration
            continue


def read_spans(trace_dir: str) -> list[dict]:
    return list(iter_spans(trace_dir))


def to_chrome_trace(spans: list[dict]) -> list[dict]:
    return [
        {
            "name": s["name"],
            "cat": s["kind"],
            "ph": "X",
            "ts": s["start"] * 1e6,
            "dur": max(s["duration"], 1e-6) * 1e6,
            "pid": s["pid"],
            "tid": s["trace_id"],
            "args": {"span_id": s["span_id"],
                     "parent_id": s.get("parent_id")},
        }
        for s in spans
    ]


def export_chrome_trace(trace_dir: str | None = None,
                        filename: str | None = None) -> list[dict]:
    """One chrome://tracing file for the whole story: tracing spans AND
    ``ray_tpu.timeline()`` task lifecycle events, merged on a shared
    wall-clock domain.

    Spans are recorded with ``time.time()``; task events are recorded
    monotonic but wall-anchored at record time inside each producing
    process (``wall_start``/``wall_end``), so both series line up in one
    viewer without post-hoc clock matching.

    pid/tid mapping (one row group per OS process):

    - pid — the OS pid of the producing process for BOTH kinds, so a
      worker's spans and its task executions share a process group.
    - tid — for spans, the ``trace_id`` (one lane per distributed call
      tree: submit + run spans of a call nest on one line); for task
      events, the executing thread name (one lane per executor thread).

    ``trace_dir`` defaults to the active trace dir (``enable_tracing``);
    with tracing off, the export is the timeline alone. Task events need
    an initialized runtime — without one the export is the spans alone.
    The merged list is stable-sorted by (ts, pid, name) so repeated
    exports of the same data diff cleanly. Returns the event list;
    ``filename`` additionally dumps it as JSON.
    """
    if trace_dir is None:
        trace_dir = os.environ.get(_ENV_DIR)
    events: list[dict] = []
    if trace_dir:
        events.extend(to_chrome_trace(read_spans(trace_dir)))
    try:
        import ray_tpu

        events.extend(ray_tpu.timeline())
    except (ImportError, RuntimeError, AttributeError, TypeError) as e:
        # no initialized runtime (or a partially torn-down one): the
        # export is spans-only — say why instead of silently shrinking
        logger.info("export_chrome_trace: skipping timeline merge: %s", e)
    # attributed log lines as instant events on the emitting task's
    # trace lane (tid = trace_id, same lane its spans render on): this
    # process's capture plus — cluster mode — the GCS log store rings
    try:
        from ray_tpu.runtime import log_plane as _log_plane

        events.extend(_log_plane.chrome_instant_events())
        from ray_tpu.runtime import core as _core
        if _core.is_initialized():
            from ray_tpu.util import state as _state

            recs: list = []
            listing = _state.list_logs()
            for proc_name in (listing.get("procs") or {}):
                got = _state.get_log(proc=proc_name, tail=1000)
                recs.extend(got.get("lines") or [])
            events.extend(_log_plane.chrome_instant_events(recs))
    except Exception as e:  # noqa: BLE001 - observability only
        logger.info("export_chrome_trace: skipping log merge: %s", e)
    # stable order so repeated exports of the same spans diff cleanly
    events.sort(key=lambda e: (e.get("ts", float("inf")),
                               e.get("pid", 0), e.get("name", "")))
    # process_name metadata so the viewer labels each pid row group
    for pid in sorted({e["pid"] for e in events if "pid" in e}):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"ray_tpu pid {pid}"}})
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
