"""Distributed task tracing: spans around every remote call.

Reference analog: ``python/ray/util/tracing/tracing_helper.py``
(``_inject_tracing_into_function:326``, ``_inject_tracing_into_class:450``)
— the reference wraps every remote function with OpenTelemetry spans and
propagates context in task metadata. Here spans are written as JSON lines
to a trace directory (the "exporter"); context (trace_id, parent span)
rides in the task spec, so a task's spans parent to its submitter's span
across process boundaries (workers inherit the trace dir via env).

Usage:
    ray_tpu.util.tracing.enable_tracing("/tmp/traces")
    ... run work ...
    spans = ray_tpu.util.tracing.read_spans("/tmp/traces")

Span records: {"name", "trace_id", "span_id", "parent_id", "start",
"duration", "pid", "kind"}. ``to_chrome_trace`` converts to
chrome://tracing format (complements ray_tpu.timeline(), which covers
task lifecycle events without cross-task parentage).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass

_ENV_DIR = "RAY_TPU_TRACE_DIR"

# ambient span context (submission captures it; execution restores it)
_current: contextvars.ContextVar["SpanContext | None"] = \
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None)

_write_lock = threading.Lock()


@dataclass
class SpanContext:
    trace_id: str
    span_id: str

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @staticmethod
    def from_dict(d: dict | None) -> "SpanContext | None":
        if not d:
            return None
        return SpanContext(d["trace_id"], d["span_id"])


def enable_tracing(trace_dir: str) -> None:
    """Turn tracing on for this process AND every worker spawned after
    (the dir is inherited through the environment, like the reference's
    tracing startup hook)."""
    os.makedirs(trace_dir, exist_ok=True)
    os.environ[_ENV_DIR] = trace_dir
    global _enabled_cache
    _enabled_cache = (True, time.monotonic())


def disable_tracing() -> None:
    os.environ.pop(_ENV_DIR, None)
    global _enabled_cache
    _enabled_cache = (False, time.monotonic())


# (value, checked_at): is_enabled sits on the per-call submit hot path —
# an os.environ read per submit measurably taxes 10k+ calls/s, so the
# env probe is cached with a short TTL. enable/disable invalidate
# immediately; a worker learning of tracing purely via inherited env
# sees it within the TTL (observability-only lag).
_enabled_cache: tuple[bool, float] = (False, -1.0)


def is_enabled() -> bool:
    global _enabled_cache
    value, checked = _enabled_cache
    now = time.monotonic()
    if now - checked > 0.2:
        value = bool(os.environ.get(_ENV_DIR))
        _enabled_cache = (value, now)
    return value


def current_context() -> SpanContext | None:
    return _current.get()


def _emit(record: dict) -> None:
    trace_dir = os.environ.get(_ENV_DIR)
    if not trace_dir:
        return
    path = os.path.join(trace_dir, f"spans-{os.getpid()}.jsonl")
    line = json.dumps(record)
    with _write_lock:
        with open(path, "a") as f:
            f.write(line + "\n")


@contextlib.contextmanager
def span(name: str, *, kind: str = "local",
         parent: SpanContext | None = None):
    """Record one span; inside the block, the ambient context points at
    it (children created here parent to it)."""
    if not is_enabled():
        yield None
        return
    if parent is None:
        parent = _current.get()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
        span_id=uuid.uuid4().hex[:16],
    )
    token = _current.set(ctx)
    start = time.time()
    try:
        yield ctx
    finally:
        _current.reset(token)
        _emit({
            "name": name,
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_id": parent.span_id if parent else None,
            "start": start,
            "duration": time.time() - start,
            "pid": os.getpid(),
            "kind": kind,
        })


def submission_context(function_name: str) -> dict | None:
    """Called at .remote() time: returns the wire context for the spec
    (a fresh 'submit' span parented to the ambient one)."""
    if not is_enabled():
        return None
    parent = _current.get()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
        span_id=uuid.uuid4().hex[:16],
    )
    _emit({
        "name": f"submit:{function_name}",
        "trace_id": ctx.trace_id,
        "span_id": ctx.span_id,
        "parent_id": parent.span_id if parent else None,
        "start": time.time(),
        "duration": 0.0,
        "pid": os.getpid(),
        "kind": "submit",
    })
    wire = ctx.to_dict()
    # Cluster-mode workers are spawned by the RAYLET, whose environ never
    # saw the driver's enable_tracing() — so the trace dir must ride the
    # wire context, not env inheritance.
    wire["trace_dir"] = os.environ.get(_ENV_DIR)
    return wire


@contextlib.contextmanager
def execution_span(function_name: str, wire_ctx: dict | None):
    """Wraps task execution; parents to the submitter's span."""
    if wire_ctx is None:
        yield
        return
    wire_dir = wire_ctx.get("trace_dir")
    if wire_dir and os.environ.get(_ENV_DIR) != wire_dir:
        # adopt/sync the submitter's trace dir: workers are spawned by
        # the raylet (no env inheritance from the driver), and a warm
        # worker must follow the driver when it switches directories
        os.environ[_ENV_DIR] = wire_dir
    if not is_enabled():
        yield
        return
    with span(f"run:{function_name}", kind="task",
              parent=SpanContext.from_dict(wire_ctx)):
        yield


def read_spans(trace_dir: str) -> list[dict]:
    out = []
    if not os.path.isdir(trace_dir):
        return out
    for fn in sorted(os.listdir(trace_dir)):
        if fn.startswith("spans-") and fn.endswith(".jsonl"):
            with open(os.path.join(trace_dir, fn)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        out.append(json.loads(line))
    return out


def to_chrome_trace(spans: list[dict]) -> list[dict]:
    return [
        {
            "name": s["name"],
            "cat": s["kind"],
            "ph": "X",
            "ts": s["start"] * 1e6,
            "dur": max(s["duration"], 1e-6) * 1e6,
            "pid": s["pid"],
            "tid": s["trace_id"],
            "args": {"span_id": s["span_id"],
                     "parent_id": s.get("parent_id")},
        }
        for s in spans
    ]


def export_chrome_trace(trace_dir: str | None = None,
                        filename: str | None = None) -> list[dict]:
    """One chrome://tracing file for the whole story: tracing spans AND
    ``ray_tpu.timeline()`` task lifecycle events, merged on a shared
    wall-clock domain.

    Spans are recorded with ``time.time()``; task events are recorded
    monotonic but wall-anchored at record time inside each producing
    process (``wall_start``/``wall_end``), so both series line up in one
    viewer without post-hoc clock matching.

    pid/tid mapping (one row group per OS process):

    - pid — the OS pid of the producing process for BOTH kinds, so a
      worker's spans and its task executions share a process group.
    - tid — for spans, the ``trace_id`` (one lane per distributed call
      tree: submit + run spans of a call nest on one line); for task
      events, the executing thread name (one lane per executor thread).

    ``trace_dir`` defaults to the active trace dir (``enable_tracing``);
    with tracing off, the export is the timeline alone. Task events need
    an initialized runtime — without one the export is the spans alone.
    Returns the merged event list; ``filename`` additionally dumps it as
    JSON.
    """
    if trace_dir is None:
        trace_dir = os.environ.get(_ENV_DIR)
    events: list[dict] = []
    if trace_dir:
        events.extend(to_chrome_trace(read_spans(trace_dir)))
    try:
        import ray_tpu

        events.extend(ray_tpu.timeline())
    except Exception:  # noqa: BLE001 - no runtime: spans-only export
        pass
    # process_name metadata so the viewer labels each pid row group
    for pid in sorted({e["pid"] for e in events if "pid" in e}):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"ray_tpu pid {pid}"}})
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
