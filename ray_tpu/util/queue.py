"""Distributed Queue (reference: ``python/ray/util/queue.py:20``) — an
actor-backed FIFO shared across tasks/actors/drivers."""

from __future__ import annotations

import time
from collections import deque

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: deque = deque()

    def qsize(self):
        return len(self.items)

    def put_nowait(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def put_nowait_batch(self, items) -> bool:
        if self.maxsize > 0 and len(self.items) + len(items) > self.maxsize:
            return False
        self.items.extend(items)
        return True

    def get_nowait(self):
        if not self.items:
            return False, None
        return True, self.items.popleft()

    def get_nowait_batch(self, n: int):
        out = []
        while self.items and len(out) < n:
            out.append(self.items.popleft())
        return out


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: dict | None = None):
        cls = ray_tpu.remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self.actor = cls.remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def put(self, item, block: bool = True, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put_nowait.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() >= deadline:
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item):
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty
            time.sleep(0.01)

    def get_nowait(self):
        return self.get(block=False)

    def put_nowait_batch(self, items):
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full

    def get_nowait_batch(self, n: int):
        return ray_tpu.get(self.actor.get_nowait_batch.remote(n))

    def shutdown(self):
        ray_tpu.kill(self.actor)
