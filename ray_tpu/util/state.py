"""State API: list/get/summarize cluster entities.

Reference analog: ``python/ray/util/state/`` (StateResource enum
``common.py:71-87``) backed by the GCS + task events
(``dashboard/state_aggregator.py``, ``gcs_task_manager.cc``). Works in
both modes: local (in-process runtime introspection) and cluster (GCS
queries)."""

from __future__ import annotations

from typing import Any

from ray_tpu.runtime import core as _core


def _mode():
    if not _core.is_initialized():
        return None, None
    rt = _core.get_runtime()
    if hasattr(rt, "_gcs"):  # ClusterRuntime
        return "cluster", rt
    return "local", rt


def list_nodes() -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("get_nodes", alive_only=False)
    if mode == "local":
        return [{"node_id": rt.node_id.hex(), "alive": True,
                 "resources": rt.total_resources,
                 "available": rt.available_resources_snapshot()}]
    return []


def list_actors() -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("list_actors")
    if mode == "local":
        return [{"actor_id": a.actor_id.hex(), "name": a.name,
                 "state": "DEAD" if a.dead else "ALIVE",
                 "num_restarts": a.num_restarts}
                for a in rt._actors.values()]
    return []


def list_jobs() -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("list_jobs")
    if mode == "local":
        return [{"job_id": rt.job_id.hex(), "state": "RUNNING"}]
    return []


def list_placement_groups() -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("list_placement_groups")
    return []


def list_tasks(limit: int = 1000) -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("get_task_events", limit=limit)
    if mode == "local":
        return rt.task_events(limit) if hasattr(rt, "task_events") else []
    return []


def list_objects() -> list[dict]:
    mode, rt = _mode()
    if mode == "local":
        return [{"object_id": k.hex() if hasattr(k, "hex") else str(k)}
                for k in getattr(rt.store, "_objects", {})]
    if mode == "cluster":
        stats = rt.store.stats()
        return [{"local_store": stats}]
    return []


def summarize_actors() -> dict:
    counts: dict[str, int] = {}
    for a in list_actors():
        counts[a["state"]] = counts.get(a["state"], 0) + 1
    return counts


def summarize_tasks() -> dict:
    counts: dict[str, int] = {}
    for t in list_tasks():
        state = t.get("state", "UNKNOWN")
        counts[state] = counts.get(state, 0) + 1
    return counts


def cluster_summary() -> dict:
    mode, rt = _mode()
    if rt is None:
        return {"initialized": False}
    return {
        "initialized": True,
        "mode": mode,
        "nodes": len([n for n in list_nodes()
                      if n.get("alive", True)]),
        "actors": summarize_actors(),
        "resources_total": rt.cluster_resources(),
        "resources_available": rt.available_resources_snapshot(),
    }
