"""State API: list/get/summarize cluster entities.

Reference analog: ``python/ray/util/state/`` (StateResource enum
``common.py:71-87``) backed by the GCS + task events
(``dashboard/state_aggregator.py``, ``gcs_task_manager.cc``). Works in
both modes: local (in-process runtime introspection) and cluster (GCS
queries)."""

from __future__ import annotations

from typing import Any

from ray_tpu.runtime import core as _core


def _mode():
    if not _core.is_initialized():
        return None, None
    rt = _core.get_runtime()
    if hasattr(rt, "_gcs"):  # ClusterRuntime
        return "cluster", rt
    return "local", rt


def list_nodes() -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("get_nodes", alive_only=False)
    if mode == "local":
        return [{"node_id": rt.node_id.hex(), "alive": True,
                 "resources": rt.total_resources,
                 "available": rt.available_resources_snapshot()}]
    return []


def list_actors() -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("list_actors")
    if mode == "local":
        return [{"actor_id": a.actor_id.hex(), "name": a.name,
                 "state": "DEAD" if a.dead else "ALIVE",
                 "num_restarts": a.num_restarts}
                for a in rt._actors.values()]
    return []


def list_jobs() -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("list_jobs")
    if mode == "local":
        return [{"job_id": rt.job_id.hex(), "state": "RUNNING"}]
    return []


def list_placement_groups() -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("list_placement_groups")
    return []


def list_tasks(limit: int = 1000) -> list[dict]:
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("get_task_events", limit=limit)
    if mode == "local":
        return rt.task_events(limit) if hasattr(rt, "task_events") else []
    return []


def list_objects(limit: int = 10_000) -> list[dict]:
    """Per-object rows with a CONSISTENT field shape in both modes:
    ``{object_id, size_bytes, state, locations, holders, pins}``.
    ``state`` is one of in_memory / pinned / spilled / being_pulled;
    cluster mode joins the GCS object directory + ref tables with the
    per-node occupancy annexes for spill/pull state."""
    mode, rt = _mode()
    if mode == "local":
        if hasattr(rt.store, "entries"):
            return rt.store.entries(limit)
        return [{"object_id": k.hex() if hasattr(k, "hex") else str(k),
                 "size_bytes": 0, "state": "in_memory",
                 "locations": ["local"], "holders": [], "pins": 0}
                for k in getattr(rt.store, "_objects", {})]
    if mode == "cluster":
        table = rt._gcs.call("memory_table", limit=limit)["objects"]
        spilled, pulling = set(), set()
        for item in cluster_metric_annexes(prefix="mem/node/"):
            p = item.get("payload")
            if isinstance(p, dict):
                spilled.update(p.get("spilled_oids", ()))
                pulling.update(p.get("being_pulled_oids", ()))
        rows = []
        for oid, row in table.items():
            if oid in spilled:
                state = "spilled"
            elif oid in pulling:
                state = "being_pulled"
            elif row["locations"]:
                state = "pinned"   # directory entries are raylet-pinned
            else:
                state = "in_memory"
            rows.append({"object_id": oid,
                         "size_bytes": row["size"],
                         "state": state,
                         "locations": row["locations"],
                         "holders": row["holders"],
                         "pins": row["pins"]})
        rows.sort(key=lambda r: -r["size_bytes"])
        return rows
    return []


def summarize_actors() -> dict:
    counts: dict[str, int] = {}
    for a in list_actors():
        counts[a["state"]] = counts.get(a["state"], 0) + 1
    return counts


def summarize_tasks() -> dict:
    counts: dict[str, int] = {}
    for t in list_tasks():
        state = t.get("state", "UNKNOWN")
        counts[state] = counts.get(state, 0) + 1
    return counts


def cluster_summary() -> dict:
    mode, rt = _mode()
    if rt is None:
        return {"initialized": False}
    return {
        "initialized": True,
        "mode": mode,
        "nodes": len([n for n in list_nodes()
                      if n.get("alive", True)]),
        "actors": summarize_actors(),
        "resources_total": rt.cluster_resources(),
        "resources_available": rt.available_resources_snapshot(),
    }


# ---------------------------------------------------------------------------
# cluster metrics plane (runtime/metrics_plane.py): push-aggregated
# time series in the GCS, queried here — reference analog: the
# Prometheus endpoint the dashboard's Metrics tab queries
# ---------------------------------------------------------------------------


def cluster_metrics(name: str | None = None, *, tags: dict | None = None,
                    last_s: float | None = None, group_by=(),
                    per_window: bool = False) -> dict:
    """Query the GCS time-series store. ``name=None`` lists metric
    names; otherwise returns the merged aggregate over every window in
    range (``per_window=True`` for the raw range query). ``group_by``
    names tag keys to split on — ``["src"]`` gives per-process/per-node
    series. In local mode the process registry answers directly (one
    window, no ring buffer)."""
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("query_metrics", name=name, tags=tags,
                            last_s=last_s, group_by=tuple(group_by or ()),
                            per_window=per_window)
    from ray_tpu.runtime.metrics_plane import MetricsStore
    from ray_tpu.util import metrics as _metrics

    store = MetricsStore(window_s=3600.0)
    frame, _ = _metrics.snapshot_delta(None)
    store.ingest("local", frame)
    if name is None:
        return {"names": store.names()}
    return store.query(name, tags=tags, last_s=last_s,
                       group_by=group_by, per_window=per_window)


def cluster_metric_annexes(prefix: str = "",
                           max_age_s: float | None = None) -> list[dict]:
    """[{src, key, ts, payload}] annexes piggybacked on metrics frames
    (e.g. serve prefix-cache digests under ``serve/prefix_digest/``).
    Cluster mode queries the GCS store; local mode reads the process
    annex registry directly (every local-mode replica shares it)."""
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("query_metric_annexes", prefix=prefix,
                            max_age_s=max_age_s)["annexes"]
    from ray_tpu.runtime import metrics_plane as _mp
    import time as _time

    now = _time.time()
    items = [(k, ts, payload)
             for k, (ts, payload) in _mp.local_annexes().items()
             if k.startswith(prefix)
             and (max_age_s is None or now - ts <= max_age_s)]
    items.sort(key=lambda it: -it[1])
    return [{"src": "local", "key": k, "ts": ts, "payload": payload}
            for k, ts, payload in items]


def summarize_latencies(last_s: float | None = 300.0,
                        quantiles=(0.5, 0.95, 0.99)) -> dict:
    """Digest of every cluster latency histogram: count / mean / p50 /
    p95 / p99 per metric over the window — the one-call answer to
    "where is the time going right now"."""
    from ray_tpu.runtime.metrics_plane import summarize_histogram

    names = cluster_metrics().get("names", {})
    out = {}
    for name, kind in sorted(names.items()):
        if kind != "histogram":
            continue
        res = cluster_metrics(name, last_s=last_s)
        digest = summarize_histogram(res, quantiles=quantiles)
        if digest.get("count"):
            out[name] = digest
    return out


# ---------------------------------------------------------------------------
# distributed tracing plane (util/tracing.py): collected traces, the
# stuck-call watchdog, and per-process flight recorders
# ---------------------------------------------------------------------------


def get_trace(trace_id: str) -> dict | None:
    """One collected trace by id: ``{"trace_id", "spans", ...}`` with
    spans sorted by start, or None if the store no longer holds it.
    Cluster mode asks the GCS TraceStore; local mode reads the process
    flight ring (local-mode spans never leave the process)."""
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("get_trace", trace_id=trace_id)["trace"]
    from ray_tpu.util import tracing as _tracing

    spans = _tracing.local_trace(trace_id)
    if not spans:
        return None
    return {"trace_id": trace_id, "spans": spans,
            "first": spans[0]["start"],
            "last": max(s["start"] + s.get("duration", 0.0)
                        for s in spans),
            "error": any(s.get("error") for s in spans),
            "slow": False, "srcs": ["local"]}


def list_traces(limit: int = 50) -> list[dict]:
    """Newest-first summaries of collected traces (cluster mode), or
    summaries reconstructed from the local flight ring."""
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("list_traces", limit=limit)["traces"]
    from ray_tpu.util import tracing as _tracing

    snap = _tracing.flight_snapshot()
    by_tid: dict[str, list] = {}
    for s in snap["spans"]:
        by_tid.setdefault(s["trace_id"], []).append(s)
    items = []
    for tid, spans in by_tid.items():
        first = min(s["start"] for s in spans)
        last = max(s["start"] + s.get("duration", 0.0) for s in spans)
        items.append({
            "trace_id": tid, "spans": len(spans), "first": first,
            "last": last, "duration_s": last - first,
            "error": any(s.get("error") for s in spans),
            "slow": False, "srcs": ["local"],
            "root": next((s["name"] for s in spans
                          if not s.get("parent_id")), spans[0]["name"]),
        })
    items.sort(key=lambda i: -i["last"])
    return items[:max(0, int(limit))]


def stuck_calls(threshold_s: float | None = None) -> dict:
    """In-flight calls (RPCs, pulls, leases, actor calls) older than
    ``threshold_s`` (default config ``trace_stuck_threshold_s``),
    cluster-wide: this process's registry, the GCS's, and every node's
    (raylet + its workers, fanned out by each raylet). Entries carry
    start stamps and — when the call was made inside a span — the
    trace/span ids of their parent chain."""
    from ray_tpu.util import tracing as _tracing

    out: dict[str, Any] = {"driver": _tracing.local_stuck_calls(threshold_s)}
    mode, rt = _mode()
    if mode != "cluster":
        return out
    try:
        out["gcs"] = rt._gcs.call("stuck_calls",
                                  threshold_s=threshold_s)["calls"]
    except Exception as e:  # noqa: BLE001 - partial result beats none
        out["gcs"] = {"error": repr(e)}
    import threading

    nodes_out: dict = {}
    out_lock = threading.Lock()

    def query(node):
        calls, err = _call_node(node, "stuck_calls", timeout=15,
                                threshold_s=threshold_s)
        with out_lock:
            nodes_out[node["node_id"]] = (calls if calls is not None
                                          else {"error": err})

    threads = [threading.Thread(target=query, args=(n,), daemon=True)
               for n in rt._gcs.call("get_nodes", alive_only=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    out["nodes"] = nodes_out
    return out


def flight_record(proc: str | None = None,
                  last_s: float | None = None) -> dict:
    """On-demand flight-recorder dump: the last ``last_s`` seconds of
    spans + RPC events + in-flight calls. ``proc=None`` snapshots THIS
    process (pure local memory — works while the GCS is unreachable);
    ``proc="gcs"`` asks the GCS; any other value is a node id whose
    raylet answers for itself and its workers."""
    from ray_tpu.util import tracing as _tracing

    if proc is None:
        return {"local": _tracing.flight_snapshot(last_s)}
    mode, rt = _mode()
    if mode != "cluster":
        raise RuntimeError(f"flight_record({proc!r}) needs a cluster "
                           "runtime; use flight_record() for this process")
    if proc == "gcs":
        return {"gcs": rt._gcs.call("flight_record",
                                    last_s=last_s)["flight"]}
    for node in rt._gcs.call("get_nodes", alive_only=True):
        if node["node_id"] == proc:
            snap, err = _call_node(node, "flight_record", timeout=15,
                                   last_s=last_s)
            return {proc: snap if snap is not None else {"error": err}}
    raise KeyError(f"no live node {proc!r}")


# ---------------------------------------------------------------------------
# profiling / stack introspection (reference: py-spy dump/record through
# the dashboard reporter agent, profile_manager.py:11-51 — here every
# raylet proxies its workers' in-process samplers)
# ---------------------------------------------------------------------------


def _call_node(node: dict, method: str, *, timeout: float, **kwargs):
    """One observability RPC against a node, preferring its dashboard
    AGENT and falling back to the raylet (same method names on both;
    a dead agent's stale agent_addr must not disable the query).
    Returns (result, last_error_repr)."""
    from ray_tpu.runtime.rpc import RpcClient

    candidates = [tuple(node["address"])]
    if node.get("agent_addr"):
        candidates.insert(0, tuple(node["agent_addr"]))
    err = None
    for addr in candidates:
        client = None
        try:
            client = RpcClient(addr, timeout=timeout)
            return client.call(method, **kwargs), None
        except Exception as e:  # noqa: BLE001 - try the next candidate
            err = repr(e)
        finally:
            if client is not None:
                client.close()
    return None, err


def dump_worker_stacks(node_id: str | None = None,
                       worker_id: str | None = None) -> dict:
    """Per-thread stacks of cluster workers, keyed node -> worker ->
    thread (py-spy ``dump`` analog)."""
    from ray_tpu.runtime.rpc import RpcClient

    mode, rt = _mode()
    if mode != "cluster":
        from ray_tpu.util.profiling import dump_stacks

        return {"local": {"driver": dump_stacks()}}
    import threading

    out = {}
    out_lock = threading.Lock()

    def query(node):
        stacks, err = _call_node(node, "worker_stacks", timeout=15,
                                 worker_id=worker_id)
        with out_lock:
            out[node["node_id"]] = (stacks if stacks is not None
                                    else {"error": err})

    # fan out per node (one unresponsive raylet must not serialize the
    # whole cluster dump behind its timeout)
    threads = [threading.Thread(target=query, args=(n,), daemon=True)
               for n in rt._gcs.call("get_nodes", alive_only=True)
               if node_id is None or n["node_id"] == node_id]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    return out


def profile_worker(worker_id: str, *, node_id: str | None = None,
                   duration_s: float = 2.0, hz: int = 100) -> dict:
    """Sampling CPU profile of one worker in collapsed-stack flamegraph
    format (py-spy ``record`` analog)."""
    from ray_tpu.runtime.rpc import RpcClient

    mode, rt = _mode()
    if mode != "cluster":
        raise RuntimeError("profile_worker needs a cluster runtime")
    transport_errors = {}
    for node in rt._gcs.call("get_nodes", alive_only=True):
        if node_id is not None and node["node_id"] != node_id:
            continue
        result, err = _call_node(node, "profile_worker",
                                 timeout=duration_s + 30,
                                 worker_id=worker_id,
                                 duration_s=duration_s, hz=hz)
        if result is None:
            transport_errors[node["node_id"]] = err
            continue
        if result.get("not_found"):
            continue   # the worker lives on another node; keep looking
        # genuine outcome from the owning node — success OR its real
        # error (never swallowed into a misleading "not found")
        result["worker_id"] = worker_id
        result["node_id"] = node["node_id"]
        return result
    if transport_errors:
        return {"error": f"profiling {worker_id!r} failed",
                "node_errors": transport_errors}
    return {"error": f"worker {worker_id!r} not found on any live node"}


def profile_cluster(procs=None, duration_s: float = 2.0,
                    hz: int = 100) -> dict:
    """One sampling window across the whole cluster: driver, GCS, every
    raylet, and every worker profile CONCURRENTLY for ``duration_s``;
    the per-process collapsed stacks come back merged into one
    flamegraph.pl / speedscope input, each process rooted under its own
    frame. ``procs`` filters by category ({"driver", "gcs", "raylet",
    "worker"}); None profiles everything. Local mode samples this
    process only."""
    import threading

    from ray_tpu.util.profiling import merge_folded, sample_profile
    from ray_tpu.utils.config import get_config

    duration_s = min(float(duration_s),
                     float(get_config().profile_max_duration_s))
    want = set(procs) if procs else {"driver", "gcs", "raylet", "worker"}
    results: dict[str, dict] = {}
    errors: dict[str, str] = {}
    out_lock = threading.Lock()
    mode, rt = _mode()
    if mode != "cluster":
        prof = sample_profile(duration_s=duration_s, hz=hz)
        return {"folded": merge_folded({"driver": prof["folded"]}),
                "procs": {"driver": _prof_meta(prof)}, "errors": {}}

    def run_driver():
        with out_lock:
            results["driver"] = sample_profile(duration_s=duration_s,
                                               hz=hz)

    def run_gcs():
        try:
            prof = rt._gcs.call("profile", timeout=duration_s + 30,
                                duration_s=duration_s, hz=hz)
        except Exception as e:  # noqa: BLE001 - partial beats none
            with out_lock:
                errors["gcs"] = repr(e)
            return
        with out_lock:
            results["gcs"] = prof

    def run_node(node):
        nid = node["node_id"]
        res, err = _call_node(node, "profile_node",
                              timeout=duration_s + 30,
                              duration_s=duration_s, hz=hz,
                              include_workers="worker" in want,
                              include_raylet="raylet" in want)
        with out_lock:
            if res is None:
                errors[f"node:{nid[:8]}"] = err
                return
            if res.get("raylet"):
                results[f"raylet:{nid[:8]}"] = res["raylet"]
            for wid, prof in (res.get("workers") or {}).items():
                results[f"worker:{wid[:8]}"] = prof
            for wid, werr in (res.get("errors") or {}).items():
                errors[f"worker:{wid[:8]}"] = werr

    threads = []
    if "driver" in want:
        threads.append(threading.Thread(target=run_driver, daemon=True))
    if "gcs" in want:
        threads.append(threading.Thread(target=run_gcs, daemon=True))
    if want & {"raylet", "worker"}:
        threads += [threading.Thread(target=run_node, args=(n,),
                                     daemon=True)
                    for n in rt._gcs.call("get_nodes", alive_only=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 40)
    return {
        "folded": merge_folded(
            {name: prof.get("folded", "") for name, prof in
             results.items()}),
        "procs": {name: _prof_meta(prof)
                  for name, prof in results.items()},
        "errors": errors,
    }


def _prof_meta(prof: dict) -> dict:
    return {k: prof.get(k) for k in
            ("samples", "duration_s", "pid", "dropped_stacks")}


def dump_proc_stacks(proc: str | None = None) -> dict:
    """One-shot per-thread stack dump of any single process — no
    profiling window (py-spy ``dump``). ``proc``: None/"driver" for
    this process, "gcs", a node id (its raylet), or a worker id."""
    if proc in (None, "driver"):
        from ray_tpu.util.profiling import dump_stacks

        return {"proc": "driver", "stacks": dump_stacks()}
    mode, rt = _mode()
    if mode != "cluster":
        raise RuntimeError(f"dump_proc_stacks({proc!r}) needs a cluster "
                           "runtime")
    if proc == "gcs":
        return {"proc": "gcs",
                "stacks": rt._gcs.call("dump_stacks")["stacks"]}
    nodes = rt._gcs.call("get_nodes", alive_only=True)
    for node in nodes:
        if node["node_id"] == proc:
            stacks, err = _call_node(node, "dump_stacks", timeout=15)
            if stacks is None:
                return {"proc": proc, "error": err}
            return {"proc": proc, "stacks": stacks["stacks"]}
    # not a node id: treat as a worker id (raylets locate their own)
    dump = dump_worker_stacks(worker_id=proc)
    for nid, workers in dump.items():
        if isinstance(workers, dict) and proc in workers:
            return {"proc": proc, "node_id": nid,
                    "stacks": workers[proc]}
    return {"proc": proc,
            "error": f"no process {proc!r} (not gcs, a node id, or a "
                     "live worker id)"}


# ---------------------------------------------------------------------------
# training telemetry (train/telemetry.py publishes per-rank progress
# annexes + train.* series; these APIs read them back cluster-wide)
# ---------------------------------------------------------------------------


def _train_progress(run: str) -> dict[str, dict]:
    """Newest progress payload per rank for ``run``, merged from the
    GCS annex store AND this process's local annex registry (the driver
    records restart badput locally; in cluster mode it has no pusher)."""
    from ray_tpu.train.telemetry import ANNEX_PREFIX

    prefix = f"{ANNEX_PREFIX}{run}/"
    merged: dict[str, tuple[float, dict]] = {}

    def take(key: str, ts: float, payload):
        if not isinstance(payload, dict):
            return
        rank = key[len(prefix):]
        if rank not in merged or ts > merged[rank][0]:
            merged[rank] = (ts, payload)

    for item in cluster_metric_annexes(prefix=prefix):
        take(item["key"], item["ts"], item["payload"])
    from ray_tpu.runtime import metrics_plane as _mp

    for key, (ts, payload) in _mp.local_annexes().items():
        if key.startswith(prefix):
            take(key, ts, payload)
    return {rank: payload for rank, (ts, payload) in merged.items()}


def train_goodput(run: str) -> dict:
    """Goodput/badput accounting for one training run: cumulative
    seconds per bucket (init / compile / productive / checkpoint /
    stall / restart) summed across ranks, plus the goodput fraction
    (productive / total). Sourced from the per-rank progress annexes —
    cumulative totals that survive metric-window expiry — with the
    ``train.goodput_s`` counter series as fallback."""
    from ray_tpu.train.telemetry import GOODPUT_BUCKETS

    buckets = {b: 0.0 for b in GOODPUT_BUCKETS}
    per_rank: dict[str, dict] = {}
    for rank, payload in _train_progress(run).items():
        good = payload.get("goodput") or {}
        per_rank[rank] = {"step": payload.get("step"),
                          "ts": payload.get("ts"),
                          "goodput": good}
        for b, v in good.items():
            buckets[b] = buckets.get(b, 0.0) + float(v)
    if not per_rank:
        # no annexes (e.g. expired + restarted GCS): fall back to the
        # windowed counter series
        q = cluster_metrics("train.goodput_s", tags={"run": run},
                            group_by=["bucket"])
        for g in q.get("groups") or []:
            bucket = g.get("tags", {}).get("bucket", "")
            value = g.get("value")
            if bucket and isinstance(value, (int, float)):
                buckets[bucket] = buckets.get(bucket, 0.0) + float(value)
    total = sum(buckets.values())
    return {
        "run": run,
        "buckets": buckets,
        "total_s": total,
        "goodput_fraction": (buckets.get("productive", 0.0) / total
                             if total > 0 else None),
        "ranks": per_rank,
    }


def train_stragglers(run: str, *, skew_s: float | None = None) -> dict:
    """Per-rank step skew for one run: which ranks lag the front rank,
    by how many steps, and by how much wall clock since their last
    step end. A rank is flagged a straggler when it is >=1 step behind
    AND lags past ``skew_s`` (default config
    ``train_straggler_skew_s``). Sustained stragglers ALSO surface in
    ``stuck_calls()``: every in-progress step holds a ``train_step``
    in-flight token."""
    from ray_tpu.utils.config import get_config

    if skew_s is None:
        skew_s = float(get_config().train_straggler_skew_s)
    progress = {rank: p for rank, p in _train_progress(run).items()
                if rank != "driver"}   # driver entries carry no steps
    if not progress:
        return {"run": run, "ranks": {}, "max_step": 0,
                "skew_steps": 0, "stragglers": []}
    max_step = max(int(p.get("step") or 0) for p in progress.values())
    front_ts = max(float(p.get("ts") or 0.0) for p in progress.values())
    ranks = {}
    stragglers = []
    for rank, p in sorted(progress.items()):
        step = int(p.get("step") or 0)
        ts = float(p.get("ts") or 0.0)
        behind = max_step - step
        lag = max(front_ts - ts, 0.0)
        flagged = behind >= 1 and lag > skew_s
        ranks[rank] = {"step": step, "behind_steps": behind,
                       "lag_s": lag, "step_s": p.get("step_s"),
                       "straggler": flagged}
        if flagged:
            stragglers.append(rank)
    return {
        "run": run,
        "ranks": ranks,
        "max_step": max_step,
        "skew_steps": max(r["behind_steps"] for r in ranks.values()),
        "stragglers": stragglers,
    }


# ---------------------------------------------------------------------------
# cluster log plane (runtime/log_plane.py): captured stdout/stderr in
# the GCS LogStore, task-attributed via the logs/segments/* annexes —
# reference analog: ray.util.state.get_log / list_logs
# ---------------------------------------------------------------------------


def get_log(proc: str | None = None, task_id: str | None = None,
            follow: bool = False, tail: int = 100):
    """Captured log lines for one process (``proc`` — a proc name like
    ``worker-ab12cd34ef56``, a worker-id prefix, ``raylet-...``, or
    ``gcs``) or exactly one task's attributed segment (``task_id`` —
    resolved through the offset annex the emitting worker pushed).

    Returns a dict ``{proc, lines, ...}``; with ``follow=True`` (proc
    mode only) returns a generator yielding each new line dict as it
    reaches the store, polling forever — iterate with a consumer-side
    stop condition."""
    mode, rt = _mode()
    if mode != "cluster":
        # local mode: serve from this process's own capture, if any
        from ray_tpu.runtime import log_plane as _lp

        cap = _lp.active_capture()
        if cap is None:
            return {"proc": proc, "lines": [],
                    "error": "no cluster runtime and no local capture"}
        return {"proc": cap.proc, "lines": cap.tail(tail, task_id)}
    if task_id is not None:
        return rt._gcs.call("get_log", task_id=task_id)
    if not proc:
        raise ValueError("get_log needs proc or task_id")
    if not follow:
        return rt._gcs.call("get_log", proc=proc, tail=tail)

    def _follow():
        import time as _time

        cursor = None
        first = rt._gcs.call("get_log", proc=proc, tail=tail)
        while True:
            for rec in first.get("lines") or []:
                cursor = (rec["file"], rec["offset"])
                yield rec
            _time.sleep(0.5)
            first = rt._gcs.call("get_log", proc=proc, tail=1000,
                                 after=cursor)

    return _follow()


def list_logs() -> dict:
    """Every process with stored lines: ``{procs: {name: {node, pid,
    lines, last_ts, files}}, ingested, deduped}``."""
    mode, rt = _mode()
    if mode != "cluster":
        from ray_tpu.runtime import log_plane as _lp

        cap = _lp.active_capture()
        if cap is None:
            return {"procs": {}, "ingested": 0, "deduped": 0}
        return {"procs": {cap.proc: {"node": "local", "pid": None,
                                     "lines": cap.lines,
                                     "last_ts": None,
                                     "files": [cap.file_token()]}},
                "ingested": cap.lines, "deduped": 0}
    return rt._gcs.call("list_logs")


def summarize_errors(last_s: float | None = None) -> list[dict]:
    """Deduplicated error groups (ERROR/CRITICAL/FATAL lines and final
    traceback lines, signature-normalized): ``[{signature, sample,
    count, first_ts, last_ts, procs, traces, tasks}]`` sorted by count.
    ``traces`` links each group to its distributed traces when the line
    was emitted inside a span."""
    mode, rt = _mode()
    if mode != "cluster":
        from ray_tpu.runtime import log_plane as _lp

        groups: dict = {}
        for rec in _lp.log_tail(None):
            if not _lp.is_error_line(rec["line"]):
                continue
            sig = _lp.error_signature(rec["line"])
            g = groups.setdefault(sig, {
                "signature": sig, "sample": rec["line"], "count": 0,
                "first_ts": rec["ts"], "last_ts": rec["ts"],
                "procs": set(), "traces": set(), "tasks": set()})
            g["count"] += 1
            g["first_ts"] = min(g["first_ts"], rec["ts"])
            g["last_ts"] = max(g["last_ts"], rec["ts"])
            if rec.get("trace"):
                g["traces"].add(rec["trace"])
            if rec.get("task"):
                g["tasks"].add(rec["task"])
        import time as _time

        now = _time.time()
        out = [dict(g) for g in groups.values()
               if last_s is None or now - g["last_ts"] <= last_s]
        for g in out:
            g["procs"], g["traces"], g["tasks"] = (
                sorted(g["procs"]), sorted(g["traces"]),
                sorted(g["tasks"]))
        out.sort(key=lambda g: (-g["count"], -g["last_ts"]))
        return out
    return rt._gcs.call("summarize_errors", last_s=last_s)["groups"]


# ---------------------------------------------------------------------------
# cluster memory plane (refcount ownership annexes + raylet occupancy
# annexes, joined in the GCS) — reference analog: `ray memory` /
# ray._private.internal_api.memory_summary
# ---------------------------------------------------------------------------


def memory_summary(*, top_n: int = 20) -> dict:
    """Cluster-wide ownership-attributed memory accounting: per-owner
    pinned / spilled / in-process bytes with top-N objects (state,
    borrower count, task pins, creation call site), per-callsite and
    per-node groupings, make-room pressure events attributed to the
    owners whose pinned bytes were spilled, and totals that reconcile
    owner bytes against node store occupancy (± in-flight transfers).

    Cluster mode is one GCS RPC. When the GCS is unreachable
    (partition), degrades to this process's OWN annex payloads — the
    answer is marked ``degraded`` and heals on the next call once the
    GCS is back."""
    mode, rt = _mode()
    if mode == "cluster":
        try:
            # bounded: a partitioned GCS must degrade the answer, not
            # hang the debugging surface behind redial backoff
            return rt._gcs.call("memory_summary", top_n=top_n,
                                timeout=5.0)
        except Exception as e:  # noqa: BLE001 - degraded beats none
            return _local_memory_summary(top_n, degraded=repr(e))
    return _local_memory_summary(top_n)


def _local_memory_summary(top_n: int, degraded: str | None = None) -> dict:
    """Summary from this process's local annex registry only: its own
    ownership snapshot (and, in local mode, the in-process store as a
    pseudo-node). No GCS join, so borrower/pin counts are unknown."""
    import time as _time

    from ray_tpu.runtime import metrics_plane as _mp

    now = _time.time()
    owners, nodes = [], []
    callsites: dict[str, dict] = {}
    for key, (ts, payload) in sorted(_mp.local_annexes().items()):
        if not isinstance(payload, dict):
            continue
        if key.startswith("mem/owners/"):
            ents = []
            for e in payload.get("entries", ()):
                ents.append({"object_id": e[0], "size_bytes": e[1],
                             "callsite": e[2],
                             "age_s": round(now - e[3], 1),
                             "state": "in_memory", "borrowers": None,
                             "task_pins": None, "locations": []})
                if e[2]:
                    c = callsites.setdefault(
                        e[2], {"callsite": e[2], "count": 0, "bytes": 0})
                    c["count"] += 1
                    c["bytes"] += e[1]
            ents.sort(key=lambda en: -en["size_bytes"])
            owners.append({
                "owner": payload.get("client_id"),
                "kind": payload.get("kind"),
                "owned": payload.get("owned", len(ents)),
                "owned_bytes": payload.get("owned_bytes", 0),
                "pinned_bytes": 0, "spilled_bytes": 0,
                "memstore_bytes": payload.get("owned_bytes", 0),
                "refs_held": payload.get("refs_held", 0),
                "last_activity": payload.get("last_activity"),
                "truncated": payload.get("truncated", 0),
                "pressure": payload.get("pressure", []),
                "top": ents[:top_n]})
        elif key.startswith("mem/node/"):
            nodes.append(dict(payload))
    mode, rt = _mode()
    if mode == "local" and rt is not None and hasattr(rt, "store"):
        st = rt.store.stats()
        nodes.append({"node_id": "local",
                      "capacity_bytes": st.get("capacity_bytes", 0),
                      "allocated_bytes": st.get("used_bytes", 0),
                      "num_objects": st.get("num_objects", 0),
                      "pinned_bytes": 0, "cached_replica_bytes": 0,
                      "spilled_bytes": 0, "being_pulled_bytes": 0})
    totals = {
        "num_owners": len(owners),
        "owned_bytes": sum(o["owned_bytes"] for o in owners),
        "pinned_bytes": 0,
        "spilled_bytes": sum(nd.get("spilled_bytes", 0) for nd in nodes),
        "memstore_bytes": sum(o["memstore_bytes"] for o in owners),
        "store_allocated_bytes": sum(
            nd.get("allocated_bytes", 0) for nd in nodes),
        "store_pinned_bytes": sum(
            nd.get("pinned_bytes", 0) for nd in nodes),
        "store_spilled_bytes": sum(
            nd.get("spilled_bytes", 0) for nd in nodes),
        "in_flight_bytes": sum(
            nd.get("being_pulled_bytes", 0) for nd in nodes),
    }
    out = {"ts": now, "mode": "local", "owners": owners, "nodes": nodes,
           "callsites": sorted(callsites.values(),
                               key=lambda c: -c["bytes"])[:max(1, top_n)],
           "pressure": [], "totals": totals}
    if degraded is not None:
        out["mode"] = "degraded"
        out["degraded"] = degraded
    return out


def memory_leaks(threshold_s: float | None = None,
                 idle_s: float | None = None) -> list[dict]:
    """Suspected leaked refs: held past ``threshold_s`` with zero
    borrowers / task pins / contained-in edges, owned by an idle but
    alive process. Each carries the creation call site. These also
    surface in ``summarize_errors()`` as ``kind="leak"`` groups."""
    mode, rt = _mode()
    if mode == "cluster":
        return rt._gcs.call("memory_leaks", threshold_s=threshold_s,
                            idle_s=idle_s)["leaks"]
    return []   # local mode: no distributed refs to leak
