"""joblib parallel backend over ray_tpu tasks.

Reference analog: ``python/ray/util/joblib/`` (P22) —
``register_ray()`` lets scikit-learn-style code run
``with joblib.parallel_backend("ray_tpu"): Parallel()(delayed(f)(x)...)``
and have each work item execute as a cluster task.
"""

from __future__ import annotations

from joblib.parallel import ParallelBackendBase, register_parallel_backend

import ray_tpu


class RayTpuBackend(ParallelBackendBase):
    """Minimal joblib backend: batches run as tasks; results gather at
    retrieval (joblib drives callbacks)."""

    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def effective_n_jobs(self, n_jobs):
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        if n_jobs is None or n_jobs < 0:
            return 8
        return n_jobs

    def apply_async(self, func, callback=None):
        task = ray_tpu.remote(lambda: func())
        ref = task.remote()

        class _Future:
            def get(self, timeout=None):
                return ray_tpu.get(ref, timeout=timeout)

        fut = _Future()
        if callback is not None:
            # joblib expects the callback once the result is ready; the
            # runtime resolves it threadlessly via the object future
            def _done(_f):
                callback(fut)

            ref.future().add_done_callback(_done)
        return fut

    def configure(self, n_jobs=1, parallel=None, **kwargs):
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)


def register_ray():
    """Register the 'ray_tpu' joblib backend (reference:
    ``ray.util.joblib.register_ray``)."""
    register_parallel_backend("ray_tpu", RayTpuBackend)
