"""In-process stack dumps + sampling CPU profiler.

Reference analog: the per-node dashboard agent shelling to py-spy for
stack dumps and flamegraphs (``dashboard/modules/reporter/
profile_manager.py:11-51``). py-spy is an external process reading
remote memory; the TPU-native stand-in is cooperative in-process
sampling over ``sys._current_frames()`` — no ptrace, works in every
worker, and emits the same collapsed-stack format flamegraph.pl /
speedscope consume.

Two entry points:

- :class:`Sampler` — the managed lifecycle: a background sampling
  thread with re-entrant/idempotent start/stop, joined on the last
  stop, and a bounded folded-stack table (overflow is COUNTED, never
  grows without bound). ``util.state.profile_cluster`` runs one of
  these per process and merges the results.
- :func:`sample_profile` — the blocking convenience wrapper (one
  Sampler for ``duration_s``), kept signature-compatible with the
  original inline loop for the worker ``profile`` RPC and the envelope
  bench.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import Counter


def dump_stacks() -> dict:
    """One formatted stack per live thread (py-spy ``dump`` analog)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in frames.items():
        name = names.get(ident, f"thread-{ident}")
        out[f"{name} ({ident})"] = "".join(traceback.format_stack(frame))
    return out


def _folded_stack(frame) -> str:
    parts = []
    while frame is not None:
        code = frame.f_code
        parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                     f"{code.co_name}")
        frame = frame.f_back
    return ";".join(reversed(parts))


def _max_stacks_default() -> int:
    try:
        from ray_tpu.utils.config import get_config

        return int(get_config().profile_folded_max_stacks)
    except Exception:  # noqa: BLE001 - config import cycle during boot
        return 10_000


class Sampler:
    """Background sampling profiler with a managed lifecycle.

    ``start``/``stop`` are re-entrant (nested starts are counted; the
    thread stops on the LAST stop) and idempotent (a stop with no
    matching start is a no-op, a second start while running just bumps
    the nesting count). ``stop`` JOINS the sampler thread before
    returning, so no sampling thread outlives its caller — the leak the
    envelope bench hit when it exited a profiling window early.

    The folded-stack table is capped at ``max_stacks`` distinct stacks;
    samples landing on a NEW stack past the cap are dropped and counted
    in ``dropped_stacks`` (known-stack counts keep accumulating), so a
    pathological workload cannot balloon the table.
    """

    def __init__(self, *, hz: int = 100, max_stacks: int | None = None,
                 exclude_threads=()):
        self.hz = max(int(hz), 1)
        self.max_stacks = (max_stacks if max_stacks is not None
                           else _max_stacks_default())
        self._exclude = set(exclude_threads)
        # RLock: stop() reads result() under the lifecycle lock
        self._lock = threading.RLock()
        self._depth = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._counts: Counter = Counter()
        self._samples = 0
        self._dropped = 0
        self._started_at: float | None = None
        self._elapsed = 0.0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Sampler":
        with self._lock:
            self._depth += 1
            if self._thread is not None:
                return self   # idempotent: already sampling
            self._stop.clear()
            self._started_at = time.monotonic()
            self._thread = threading.Thread(
                target=self._loop, name="ray_tpu-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> dict:
        """Unwind one start; on the last one, stop AND JOIN the sampler
        thread. Always returns the current result (idempotent: calling
        stop on a never-started or already-stopped sampler just reads
        the accumulated result)."""
        with self._lock:
            if self._depth > 0:
                self._depth -= 1
            if self._depth > 0 or self._thread is None:
                return self.result()
            thread = self._thread
            self._thread = None
            self._stop.set()
        thread.join(timeout=timeout)
        with self._lock:
            if self._started_at is not None:
                self._elapsed += time.monotonic() - self._started_at
                self._started_at = None
        return self.result()

    @property
    def running(self) -> bool:
        return self._thread is not None

    # -- sampling ------------------------------------------------------

    def _loop(self):
        interval = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(interval):
            self.sample_once(extra_exclude=(me,))

    def sample_once(self, extra_exclude=()) -> None:
        """Take one sample of every live thread (minus exclusions)."""
        excl = self._exclude
        for ident, frame in sys._current_frames().items():
            if ident in excl or ident in extra_exclude:
                continue
            stack = _folded_stack(frame)
            with self._lock:
                if stack not in self._counts and \
                        len(self._counts) >= self.max_stacks:
                    self._dropped += 1
                    continue
                self._counts[stack] += 1
        with self._lock:
            self._samples += 1

    def result(self) -> dict:
        with self._lock:
            elapsed = self._elapsed
            if self._started_at is not None:
                elapsed += time.monotonic() - self._started_at
            folded = "\n".join(f"{stack} {n}"
                               for stack, n in self._counts.most_common())
            return {"folded": folded, "samples": self._samples,
                    "duration_s": round(elapsed, 3),
                    "dropped_stacks": self._dropped,
                    "pid": os.getpid()}


def sample_profile(duration_s: float = 2.0, hz: int = 100,
                   exclude_thread: int | None = None,
                   stop: "threading.Event | None" = None) -> dict:
    """Sample all threads for ``duration_s`` and aggregate folded stacks
    (py-spy ``record`` analog). Returns {"folded": "stack count" lines,
    "samples": N, "duration_s": d, ...} — feed ``folded`` to any
    flamegraph renderer. ``stop`` ends the run early — callers profiling
    a workload of unknown length pass a generous duration plus the
    event. The calling thread (blocked here) is always excluded, so the
    wait frame never pollutes the profile."""
    exclude = {threading.get_ident()}
    if exclude_thread is not None:
        exclude.add(exclude_thread)
    sampler = Sampler(hz=hz, exclude_threads=exclude).start()
    deadline = time.monotonic() + duration_s
    try:
        while time.monotonic() < deadline and \
                not (stop is not None and stop.is_set()):
            if stop is not None:
                stop.wait(min(0.05, max(deadline - time.monotonic(), 0)))
            else:
                time.sleep(min(0.05, max(deadline - time.monotonic(), 0)))
    finally:
        result = sampler.stop()
    return result


def merge_folded(parts: dict[str, str]) -> str:
    """Merge per-process collapsed-stack blobs into ONE flamegraph
    input: each process's stacks are rooted under a frame named after
    the process (`driver;...`, `gcs;...`), exactly how flamegraph.pl /
    speedscope render multi-process profiles. Counts are preserved."""
    merged: Counter = Counter()
    for proc, folded in sorted(parts.items()):
        for line in (folded or "").splitlines():
            stack, _, count = line.rpartition(" ")
            if not stack:
                continue
            try:
                merged[f"{proc};{stack}"] += int(count)
            except ValueError:
                continue
    return "\n".join(f"{stack} {n}" for stack, n in merged.most_common())


def host_stats(spill_dir: str | None = None) -> dict:
    """Per-node resource sample (reference: reporter_agent.py psutil
    collection feeding the dashboard)."""
    try:
        import psutil
    except ImportError:
        return {}
    vm = psutil.virtual_memory()
    out = {
        "cpu_percent": psutil.cpu_percent(interval=None),
        "mem_total": vm.total,
        "mem_available": vm.available,
        "mem_percent": vm.percent,
        "num_threads": threading.active_count(),
    }
    if spill_dir:
        try:
            du = psutil.disk_usage(spill_dir)
            out["spill_disk_free"] = du.free
            out["spill_disk_percent"] = du.percent
        except OSError:
            pass
    return out
