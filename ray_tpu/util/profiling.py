"""In-process stack dumps + sampling CPU profiler.

Reference analog: the per-node dashboard agent shelling to py-spy for
stack dumps and flamegraphs (``dashboard/modules/reporter/
profile_manager.py:11-51``). py-spy is an external process reading
remote memory; the TPU-native stand-in is cooperative in-process
sampling over ``sys._current_frames()`` — no ptrace, works in every
worker, and emits the same collapsed-stack format flamegraph.pl /
speedscope consume.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter


def dump_stacks() -> dict:
    """One formatted stack per live thread (py-spy ``dump`` analog)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in frames.items():
        name = names.get(ident, f"thread-{ident}")
        out[f"{name} ({ident})"] = "".join(traceback.format_stack(frame))
    return out


def _folded_stack(frame) -> str:
    parts = []
    while frame is not None:
        code = frame.f_code
        parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                     f"{code.co_name}")
        frame = frame.f_back
    return ";".join(reversed(parts))


def sample_profile(duration_s: float = 2.0, hz: int = 100,
                   exclude_thread: int | None = None,
                   stop: "threading.Event | None" = None) -> dict:
    """Sample all threads for ``duration_s`` and aggregate folded stacks
    (py-spy ``record`` analog). Returns {"folded": "stack count" lines,
    "samples": N, "duration_s": d} — feed ``folded`` to any flamegraph
    renderer. ``stop`` ends the run early — callers profiling a
    workload of unknown length pass a generous duration plus the event."""
    interval = 1.0 / max(hz, 1)
    counts: Counter = Counter()
    samples = 0
    me = threading.get_ident()
    start = time.monotonic()
    deadline = start + duration_s
    while time.monotonic() < deadline and \
            not (stop is not None and stop.is_set()):
        for ident, frame in sys._current_frames().items():
            if ident == me or ident == exclude_thread:
                continue
            counts[_folded_stack(frame)] += 1
        samples += 1
        time.sleep(interval)
    folded = "\n".join(f"{stack} {n}" for stack, n in counts.most_common())
    return {"folded": folded, "samples": samples,
            "duration_s": round(time.monotonic() - start, 3)}


def host_stats(spill_dir: str | None = None) -> dict:
    """Per-node resource sample (reference: reporter_agent.py psutil
    collection feeding the dashboard)."""
    try:
        import psutil
    except ImportError:
        return {}
    vm = psutil.virtual_memory()
    out = {
        "cpu_percent": psutil.cpu_percent(interval=None),
        "mem_total": vm.total,
        "mem_available": vm.available,
        "mem_percent": vm.percent,
        "num_threads": threading.active_count(),
    }
    if spill_dir:
        try:
            du = psutil.disk_usage(spill_dir)
            out["spill_disk_free"] = du.free
            out["spill_disk_percent"] = du.percent
        except OSError:
            pass
    return out
