"""Ray-on-Spark launcher: start a ray_tpu cluster on a Spark cluster's
executors (reference: ``python/ray/util/spark/cluster_init.py`` —
``setup_ray_cluster``/``shutdown_ray_cluster``/``MAX_NUM_WORKER_NODES``).

Shape follows the reference: the head (GCS + head raylet) starts on the
Spark DRIVER; each worker node runs as a long-lived barrier-mode Spark
task pinned to one executor, started with ``ray_tpu start --address``
semantics and torn down when the background Spark job is cancelled.

pyspark is not bundled in this image — every entry point degrades to a
clear ImportError at call time (module import stays cheap and safe), and
the executor-side launch command is factored out (`_worker_start_cmd`)
so the launch protocol is unit-testable without Spark."""

from __future__ import annotations

import os
import sys

# reference: cluster_init.py:46 — "use every executor" sentinel
MAX_NUM_WORKER_NODES = -1

_active: dict = {}


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "ray_tpu.util.spark requires pyspark (not bundled in this "
            "environment): pip install pyspark, or start clusters with "
            "ray_tpu.cluster_utils.Cluster / `ray_tpu start` directly"
        ) from e


def _worker_start_cmd(gcs_address: tuple, num_cpus: int,
                      num_tpus: int = 0) -> list[str]:
    """The executor-side worker-node launch command (one per Spark
    barrier task). Factored for tests: the protocol — connect a raylet
    to the driver-hosted GCS — is what Ray-on-Spark is."""
    host, port = gcs_address
    return [
        sys.executable, "-m", "ray_tpu.scripts.cli", "start",
        "--address", f"{host}:{port}",
        "--num-cpus", str(num_cpus),
        "--num-tpus", str(num_tpus),
        "--block",
    ]


def setup_ray_cluster(*, num_worker_nodes: int,
                      num_cpus_per_node: int | None = None,
                      num_tpus_per_node: int = 0,
                      spark=None) -> str:
    """Start a ray_tpu cluster over the active Spark session's executors
    (reference: setup_ray_cluster, cluster_init.py:803). Returns the GCS
    address ``host:port``; pass it to ``ray_tpu.init(address=...)``.

    ``num_worker_nodes=MAX_NUM_WORKER_NODES`` uses every executor."""
    _require_pyspark()
    from pyspark.sql import SparkSession

    spark = spark or SparkSession.getActiveSession()
    if spark is None:
        raise RuntimeError("no active SparkSession; create one first")
    sc = spark.sparkContext
    if num_worker_nodes == MAX_NUM_WORKER_NODES:
        num_worker_nodes = max(
            1, int(sc.getConf().get("spark.executor.instances", "1")))
    num_cpus = num_cpus_per_node or int(
        sc.getConf().get("spark.executor.cores", "1"))

    from ray_tpu.cluster_utils import Cluster

    head = Cluster(external_gcs=True)
    head.add_node(num_cpus=0, external=True)   # head: control plane only
    gcs_addr = head.gcs_address
    cmd = _worker_start_cmd(gcs_addr, num_cpus, num_tpus_per_node)

    def _run_worker(_):
        import subprocess

        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        ctx.barrier()   # all worker nodes start together
        subprocess.run(cmd, check=False)
        return []

    # barrier-mode background job: one long-lived task per worker node
    # (reference: _start_ray_worker_nodes' spark job). Cancellation via
    # the job group is the shutdown path.
    import threading

    rdd = sc.parallelize(range(num_worker_nodes), num_worker_nodes)

    def _submit():
        sc.setJobGroup("ray_tpu-on-spark", "ray_tpu worker nodes",
                       interruptOnCancel=True)
        rdd.barrier().mapPartitions(_run_worker).collect()

    job = threading.Thread(target=_submit, daemon=True,
                           name="ray_tpu-spark-workers")
    job.start()
    addr = f"{gcs_addr[0]}:{gcs_addr[1]}"
    _active[addr] = (head, sc)
    os.environ["RAY_TPU_ADDRESS"] = addr
    return addr


def shutdown_ray_cluster() -> None:
    """Tear down the Spark-hosted cluster (reference:
    shutdown_ray_cluster): cancel the worker-node job group, stop the
    driver-side head."""
    _require_pyspark()
    while _active:
        addr, (head, sc) = _active.popitem()
        try:
            sc.cancelJobGroup("ray_tpu-on-spark")
        except Exception:  # noqa: BLE001
            pass
        head.shutdown()
    os.environ.pop("RAY_TPU_ADDRESS", None)
