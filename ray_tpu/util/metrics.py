"""Application metrics API (reference: ``python/ray/util/metrics.py`` over
``includes/metric.pxi``; C++ registry N11 ``src/ray/stats/``).

Counter/Gauge/Histogram with tag support; the process-local registry
exports Prometheus text format (the reference pushes to a per-node metrics
agent scraped by Prometheus — here ``export_prometheus()`` serves the same
wire format for any scraper).

The registry also feeds the CLUSTER metrics plane
(``runtime/metrics_plane.py``): each process periodically snapshots the
registry as a DELTA frame (``snapshot_delta``) and pushes it to the GCS
time-series store. Hot-path call sites guard on :func:`enabled` (one
cached boolean read) and observe through pre-resolved series handles
(:meth:`Histogram.handle`) so the instrumented cost stays within the
<3% overhead budget (``tests/test_metrics_plane.py``)."""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict

_registry_lock = threading.Lock()
_registry: dict[str, "Metric"] = {}

# ---------------------------------------------------------------------------
# master switch — resolved once from config (RAY_TPU_METRICS_ENABLED);
# hot paths read the cached module global, never the environment
# ---------------------------------------------------------------------------

_enabled: bool | None = None


def enabled() -> bool:
    """Whether hot-path instrumentation + the push plane are on.
    Resolved from config on first call, then a plain module-global read."""
    global _enabled
    if _enabled is None:
        try:
            from ray_tpu.utils.config import get_config

            _enabled = bool(get_config().metrics_enabled)
        except Exception:  # noqa: BLE001 - config import cycle during boot
            return True
    return _enabled


def set_enabled(flag: bool | None):
    """Override (tests / explicit opt-out). ``None`` re-resolves from
    config on the next :func:`enabled` call."""
    global _enabled
    _enabled = flag


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: dict):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: dict | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: dict = defaultdict(float)

    def inc(self, value: float = 1.0, tags: dict | None = None):
        with self._lock:
            self._values[self._key(tags)] += value

    def series(self):
        with self._lock:
            return dict(self._values)


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: dict = {}

    def set(self, value: float, tags: dict | None = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def series(self):
        with self._lock:
            return dict(self._values)


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)


class _HistHandle:
    """Pre-resolved (histogram, tag-key) pair for hot paths: observe()
    skips the per-call dict merge + sorted() of :meth:`Metric._key` —
    the tag tuple is computed once at handle creation."""

    __slots__ = ("_hist", "_key_t")

    def __init__(self, hist: "Histogram", key_t: tuple):
        self._hist = hist
        self._key_t = key_t

    def observe(self, value: float):
        h = self._hist
        idx = bisect.bisect_left(h.boundaries, value)
        with h._lock:
            h._counts[self._key_t][idx] += 1
            h._sums[self._key_t] += value
            h._totals[self._key_t] += 1


class Histogram(Metric):
    def __init__(self, name, description="", boundaries=DEFAULT_BUCKETS,
                 tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)
        self._counts: dict = defaultdict(
            lambda: [0] * (len(self.boundaries) + 1))
        self._sums: dict = defaultdict(float)
        self._totals: dict = defaultdict(int)

    def observe(self, value: float, tags: dict | None = None):
        key = self._key(tags)
        idx = bisect.bisect_left(self.boundaries, value)
        with self._lock:
            self._counts[key][idx] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def handle(self, tags: dict | None = None) -> _HistHandle:
        return _HistHandle(self, self._key(tags))

    def series(self):
        with self._lock:
            return {k: {"buckets": list(v), "sum": self._sums[k],
                        "count": self._totals[k]}
                    for k, v in self._counts.items()}


# ---------------------------------------------------------------------------
# get-or-create constructors (instrumented modules resolve their metric
# once at module/first-use time; re-registration must return the live
# instance, not shadow its accumulated series)
# ---------------------------------------------------------------------------


def _get_or_create(cls, name, description, **kwargs):
    with _registry_lock:
        m = _registry.get(name)
    if m is not None and type(m) is cls:
        return m
    return cls(name, description, **kwargs)


def counter(name: str, description: str = "", tag_keys=()) -> Counter:
    return _get_or_create(Counter, name, description, tag_keys=tag_keys)


def gauge(name: str, description: str = "", tag_keys=()) -> Gauge:
    return _get_or_create(Gauge, name, description, tag_keys=tag_keys)


def histogram(name: str, description: str = "",
              boundaries=DEFAULT_BUCKETS, tag_keys=()) -> Histogram:
    return _get_or_create(Histogram, name, description,
                          boundaries=boundaries, tag_keys=tag_keys)


def clear_registry():
    """Tests only: drop every registered metric (a fresh process-local
    registry; live Metric objects keep working but stop being exported)."""
    with _registry_lock:
        _registry.clear()


# ---------------------------------------------------------------------------
# snapshots + delta frames (the push plane's wire unit)
# ---------------------------------------------------------------------------


def snapshot() -> dict:
    """Cumulative snapshot of every registered metric:
    ``{name: {"kind", "boundaries"?, "series": {tag_key_tuple: payload}}}``
    where payload is a float (counter/gauge) or
    ``{"buckets": [...], "sum": s, "count": n}`` (histogram)."""
    with _registry_lock:
        metrics = list(_registry.values())
    out = {}
    for m in metrics:
        entry = {"kind": type(m).__name__.lower(), "series": m.series()}
        if isinstance(m, Histogram):
            entry["boundaries"] = list(m.boundaries)
        out[m.name] = entry
    return out


def snapshot_delta(prev: dict | None) -> tuple[dict, dict]:
    """One push frame: counters/histograms as DELTAS against ``prev``
    (the last snapshot this process successfully framed), gauges as
    current values. Returns ``(frame, new_prev)``; empty series are
    dropped so an idle process frames nothing. A counter/bucket that
    went BACKWARDS (registry cleared) resends its current value."""
    cur = snapshot()
    prev = prev or {}
    frame: dict = {}
    for name, entry in cur.items():
        kind = entry["kind"]
        prev_series = (prev.get(name) or {}).get("series", {})
        out_series: dict = {}
        if kind == "gauge":
            out_series = dict(entry["series"])
        elif kind == "counter":
            for key, val in entry["series"].items():
                base = prev_series.get(key, 0.0)
                d = val - base if val >= base else val
                if d != 0.0:
                    out_series[key] = d
        else:  # histogram
            for key, data in entry["series"].items():
                base = prev_series.get(key)
                if base is None or base["count"] > data["count"]:
                    d = data
                else:
                    d = {"buckets": [a - b for a, b in
                                     zip(data["buckets"], base["buckets"])],
                         "sum": data["sum"] - base["sum"],
                         "count": data["count"] - base["count"]}
                if d["count"] > 0:
                    out_series[key] = d
        if out_series:
            ent = {"kind": kind, "series": out_series}
            if "boundaries" in entry:
                ent["boundaries"] = entry["boundaries"]
            frame[name] = ent
    return frame, cur


def merge_hist(into: dict | None, data: dict) -> dict:
    """Accumulate one histogram payload into another (additive across
    processes/windows — the cluster-aggregation primitive)."""
    if into is None:
        return {"buckets": list(data["buckets"]), "sum": data["sum"],
                "count": data["count"]}
    into["buckets"] = [a + b for a, b in zip(into["buckets"],
                                             data["buckets"])]
    into["sum"] += data["sum"]
    into["count"] += data["count"]
    return into


def quantile_from_buckets(boundaries, buckets, q: float) -> float | None:
    """Quantile estimate from cumulative-able bucket counts (linear
    interpolation inside the winning bucket; the +Inf bucket returns its
    lower bound — same convention as Prometheus ``histogram_quantile``)."""
    total = sum(buckets)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, cnt in enumerate(buckets):
        if cnt <= 0:
            continue
        if cum + cnt >= rank:
            if i >= len(boundaries):         # +Inf bucket
                return float(boundaries[-1]) if boundaries else None
            lo = float(boundaries[i - 1]) if i > 0 else 0.0
            hi = float(boundaries[i])
            frac = (rank - cum) / cnt
            return lo + (hi - lo) * frac
        cum += cnt
    return float(boundaries[-1]) if boundaries else None


def _fmt_tags(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def export_prometheus() -> str:
    """All registered metrics in Prometheus text exposition format."""
    lines = []
    with _registry_lock:
        metrics = list(_registry.values())
    for m in metrics:
        if m.description:
            lines.append(f"# HELP {m.name} {m.description}")
        kind = {"Counter": "counter", "Gauge": "gauge",
                "Histogram": "histogram"}[type(m).__name__]
        lines.append(f"# TYPE {m.name} {kind}")
        if isinstance(m, Histogram):
            for key, data in m.series().items():
                cumulative = 0
                bounds = [str(b) for b in m.boundaries] + ["+Inf"]
                for bound, count in zip(bounds, data["buckets"]):
                    cumulative += count
                    tag = dict(key)
                    tag["le"] = bound
                    lines.append(
                        f"{m.name}_bucket{_fmt_tags(tuple(sorted(tag.items())))} {cumulative}")
                lines.append(f"{m.name}_sum{_fmt_tags(key)} {data['sum']}")
                lines.append(f"{m.name}_count{_fmt_tags(key)} {data['count']}")
        else:
            for key, value in m.series().items():
                lines.append(f"{m.name}{_fmt_tags(key)} {value}")
    return "\n".join(lines) + "\n"
