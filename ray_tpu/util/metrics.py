"""Application metrics API (reference: ``python/ray/util/metrics.py`` over
``includes/metric.pxi``; C++ registry N11 ``src/ray/stats/``).

Counter/Gauge/Histogram with tag support; the process-local registry
exports Prometheus text format (the reference pushes to a per-node metrics
agent scraped by Prometheus — here ``export_prometheus()`` serves the same
wire format for any scraper)."""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict

_registry_lock = threading.Lock()
_registry: dict[str, "Metric"] = {}


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: dict):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: dict | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: dict = defaultdict(float)

    def inc(self, value: float = 1.0, tags: dict | None = None):
        with self._lock:
            self._values[self._key(tags)] += value

    def series(self):
        with self._lock:
            return dict(self._values)


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: dict = {}

    def set(self, value: float, tags: dict | None = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def series(self):
        with self._lock:
            return dict(self._values)


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)


class Histogram(Metric):
    def __init__(self, name, description="", boundaries=DEFAULT_BUCKETS,
                 tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)
        self._counts: dict = defaultdict(
            lambda: [0] * (len(self.boundaries) + 1))
        self._sums: dict = defaultdict(float)
        self._totals: dict = defaultdict(int)

    def observe(self, value: float, tags: dict | None = None):
        key = self._key(tags)
        idx = bisect.bisect_left(self.boundaries, value)
        with self._lock:
            self._counts[key][idx] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def series(self):
        with self._lock:
            return {k: {"buckets": list(v), "sum": self._sums[k],
                        "count": self._totals[k]}
                    for k, v in self._counts.items()}


def _fmt_tags(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def export_prometheus() -> str:
    """All registered metrics in Prometheus text exposition format."""
    lines = []
    with _registry_lock:
        metrics = list(_registry.values())
    for m in metrics:
        if m.description:
            lines.append(f"# HELP {m.name} {m.description}")
        kind = {"Counter": "counter", "Gauge": "gauge",
                "Histogram": "histogram"}[type(m).__name__]
        lines.append(f"# TYPE {m.name} {kind}")
        if isinstance(m, Histogram):
            for key, data in m.series().items():
                cumulative = 0
                bounds = [str(b) for b in m.boundaries] + ["+Inf"]
                for bound, count in zip(bounds, data["buckets"]):
                    cumulative += count
                    tag = dict(key)
                    tag["le"] = bound
                    lines.append(
                        f"{m.name}_bucket{_fmt_tags(tuple(sorted(tag.items())))} {cumulative}")
                lines.append(f"{m.name}_sum{_fmt_tags(key)} {data['sum']}")
                lines.append(f"{m.name}_count{_fmt_tags(key)} {data['count']}")
        else:
            for key, value in m.series().items():
                lines.append(f"{m.name}{_fmt_tags(key)} {value}")
    return "\n".join(lines) + "\n"
