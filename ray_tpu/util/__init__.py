"""ray_tpu.util: ecosystem utilities (reference: ray.util, SURVEY P22).

Lazy re-exports (PEP 562): the ecosystem helpers here decorate with
``@ray_tpu.remote`` at import time, so importing them eagerly from this
package ``__init__`` would make ``ray_tpu.util.metrics`` — which low-level
runtime modules import for hot-path instrumentation — circular with the
top-level ``ray_tpu`` package init.
"""

__all__ = [
    "ActorPool",
    "ParallelIterator",
    "Queue",
    "from_items",
    "from_range",
]

_HOMES = {
    "ActorPool": "ray_tpu.util.actor_pool",
    "ParallelIterator": "ray_tpu.util.iter",
    "from_items": "ray_tpu.util.iter",
    "from_range": "ray_tpu.util.iter",
    "Queue": "ray_tpu.util.queue",
}


def __getattr__(name):
    home = _HOMES.get(name)
    if home is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(home), name)
