"""ray_tpu.util: ecosystem utilities (reference: ray.util, SURVEY P22)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.iter import ParallelIterator, from_items, from_range
from ray_tpu.util.queue import Queue

__all__ = [
    "ActorPool",
    "ParallelIterator",
    "Queue",
    "from_items",
    "from_range",
]
