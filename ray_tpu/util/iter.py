"""Parallel iterators over sharded data.

Reference analog: ``python/ray/util/iter.py`` (P22 — ParallelIterator:
shards held by actors, lazy transforms, gather to a local iterator).
Ray Data supersedes this in the reference; it's kept for API parity and
for lightweight actor-sharded iteration without the Dataset machinery.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import ray_tpu


@ray_tpu.remote
class _ShardActor:
    def __init__(self, items: list):
        self.items = list(items)

    def materialize(self, ops: list) -> list:
        out = list(self.items)
        for kind, fn in ops:
            if kind == "map":
                out = [fn(x) for x in out]
            elif kind == "filter":
                out = [x for x in out if fn(x)]
            elif kind == "flatten":
                out = [y for x in out for y in fn(x)]
            elif kind == "batch":
                n = fn
                out = [out[i:i + n] for i in range(0, len(out), n)]
        return out


class ParallelIterator:
    """Transforms are recorded CLIENT-side and shipped at gather time, so
    each transform returns a NEW iterator: two iterators branched from
    the same parent never contaminate each other's op chains (matching
    the reference API's value semantics)."""

    def __init__(self, shards: list, ops: tuple = ()):
        self._shards = shards
        self._ops = ops

    @property
    def num_shards(self) -> int:
        return len(self._shards)

    # -- lazy transforms -------------------------------------------------

    def _with(self, kind: str, fn) -> "ParallelIterator":
        return ParallelIterator(self._shards, self._ops + ((kind, fn),))

    def for_each(self, fn: Callable) -> "ParallelIterator":
        return self._with("map", fn)

    def filter(self, fn: Callable) -> "ParallelIterator":
        return self._with("filter", fn)

    def flat_map(self, fn: Callable) -> "ParallelIterator":
        return self._with("flatten", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._with("batch", n)

    # -- consumption -----------------------------------------------------

    def gather_sync(self):
        """Round-robin merge of all shards into one local iterator."""
        ops = list(self._ops)
        lists = ray_tpu.get([s.materialize.remote(ops)
                             for s in self._shards])
        iters = [iter(x) for x in lists]
        while iters:
            nxt = []
            for it in iters:
                try:
                    yield next(it)
                    nxt.append(it)
                except StopIteration:
                    pass
            iters = nxt

    def gather_async(self):
        """Shard-major merge (whole shards as they complete)."""
        ops = list(self._ops)
        refs = [s.materialize.remote(ops) for s in self._shards]
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    def take(self, n: int) -> list:
        out = []
        for x in self.gather_sync():
            out.append(x)
            if len(out) >= n:
                break
        return out

    def shards(self) -> list:
        return list(self._shards)


def from_items(items: list, num_shards: int = 2) -> ParallelIterator:
    items = list(items)
    shards = []
    for i in range(num_shards):
        shard_items = items[i::num_shards]
        shards.append(_ShardActor.remote(shard_items))
    return ParallelIterator(shards)


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)


def from_iterators(iterables: list[Iterable[Any]]) -> ParallelIterator:
    return ParallelIterator(
        [_ShardActor.remote(list(it)) for it in iterables])
