"""Remote debugger for cluster tasks and actors.

Reference analog: ``python/ray/util/rpdb.py`` — ``ray.util.rpdb.set_trace``
opens a pdb bound to a TCP socket inside the worker, registers the
session in the GCS KV, and ``ray debug`` connects to it. Same shape
here: ``ray_tpu.util.debug.set_trace()`` / ``post_mortem()`` in task
code, ``active_sessions()`` + ``connect(session)`` driver-side (wired
to ``scripts/cli.py debug``).
"""

from __future__ import annotations

import json
import os
import pdb
import socket
import sys
import time
import uuid

from ray_tpu.experimental import internal_kv

_KV_PREFIX = "rtpu_debugger:"


def _reachable_host() -> str:
    """The IP a remote ``ray-tpu debug`` should dial for THIS process:
    the interface that routes toward the GCS (the cluster's network),
    falling back to loopback for single-host runs."""
    gcs_host = os.environ.get("RAY_TPU_GCS_HOST")
    if gcs_host and gcs_host not in ("127.0.0.1", "localhost"):
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect((gcs_host, 1))       # no packet sent
            return probe.getsockname()[0]
        except OSError:
            pass
        finally:
            probe.close()
    return "127.0.0.1"


class _SocketIO:
    """File-like over a connected socket for Pdb stdin/stdout."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._r = conn.makefile("r", encoding="utf-8", newline="\n")
        self._w = conn.makefile("w", encoding="utf-8")

    def readline(self):
        return self._r.readline()

    def write(self, data):
        self._w.write(data)
        return len(data)

    def flush(self):
        try:
            self._w.flush()
        except (BrokenPipeError, OSError):
            pass

    def close(self):
        for f in (self._r, self._w):
            try:
                f.close()
            except OSError:
                pass


class _RemotePdb(pdb.Pdb):
    """Pdb listening on an ephemeral TCP port; blocks the worker until
    a client attaches (the breakpoint IS the suspension point, like the
    reference's remote pdb)."""

    def __init__(self, session_id: str, timeout_s: float | None):
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR,
                                  1)
        # all interfaces: the attaching CLI may run on another host of
        # the cluster (the announced host below is what it dials)
        self._listener.bind(("", 0))
        self._listener.listen(1)
        self.port = self._listener.getsockname()[1]
        self.session_id = session_id
        self._announce(timeout_s)
        if timeout_s is not None:
            self._listener.settimeout(timeout_s)
        try:
            conn, _ = self._listener.accept()
        except socket.timeout:
            self.cleanup()   # nobody attached: deregister + close
            raise
        self._io = _SocketIO(conn)
        super().__init__(stdin=self._io, stdout=self._io)
        self.use_rawinput = False
        self.prompt = "(rtpu-pdb) "

    # pdb.set_trace installs a trace and RETURNS; the interaction fires
    # at the caller's next line. Teardown therefore hangs off the detach
    # commands, not the caller (the standard remote-pdb shape).
    def do_continue(self, arg):
        result = super().do_continue(arg)
        self.cleanup()
        return result

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        result = super().do_quit(arg)
        self.cleanup()
        return result

    do_q = do_exit = do_quit

    def do_EOF(self, arg):   # noqa: N802 - pdb naming
        result = super().do_EOF(arg)
        self.cleanup()
        return result

    def _announce(self, timeout_s):
        entry = {
            "session_id": self.session_id,
            "host": _reachable_host(),
            "port": self.port,
            "pid": os.getpid(),
            "worker_id": os.environ.get("RAY_TPU_WORKER_ID", "driver"),
            "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
            "created": time.time(),
            "timeout_s": timeout_s,
        }
        try:
            internal_kv.internal_kv_put(
                _KV_PREFIX + self.session_id,
                json.dumps(entry).encode())
        except Exception:  # noqa: BLE001 - debugging must not kill work
            pass

    def cleanup(self):
        """Idempotent: detach commands, timeouts, and the post-mortem
        finally all funnel here."""
        try:
            internal_kv.internal_kv_del(_KV_PREFIX + self.session_id)
        except Exception:  # noqa: BLE001
            pass
        if getattr(self, "_io", None) is not None:
            self._io.close()
        try:
            self._listener.close()
        except OSError:
            pass


def set_trace(*, timeout_s: float | None = None):
    """Breakpoint inside task/actor code: suspends this worker until a
    client attaches (``ray_tpu debug`` CLI / ``connect()``) and drives
    the pdb session. ``timeout_s`` bounds the wait for a client
    (reference behavior: block indefinitely)."""
    session_id = uuid.uuid4().hex[:12]
    try:
        remote_pdb = _RemotePdb(session_id, timeout_s)
    except socket.timeout:
        return   # nobody attached within the window: resume execution
    # debug the CALLER's frame, like pdb.set_trace(); teardown happens
    # in the detach commands (do_continue/do_quit), not here — the
    # interaction hasn't happened yet when this returns
    remote_pdb.set_trace(frame=sys._getframe().f_back)


def post_mortem(tb=None, *, timeout_s: float | None = None):
    """Remote post-mortem on the active exception's traceback."""
    if tb is None:
        tb = sys.exc_info()[2]
    if tb is None:
        raise ValueError("no traceback to post-mortem")
    session_id = uuid.uuid4().hex[:12]
    try:
        remote_pdb = _RemotePdb(session_id, timeout_s)
    except socket.timeout:
        return
    try:
        remote_pdb.reset()
        remote_pdb.interaction(None, tb)
    finally:
        remote_pdb.cleanup()


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


def active_sessions() -> list[dict]:
    """Breakpoints currently waiting for (or holding) a client."""
    out = []
    try:
        keys = internal_kv.internal_kv_list(_KV_PREFIX)
    except Exception:  # noqa: BLE001
        return out
    for key in keys:
        raw = internal_kv.internal_kv_get(key)
        if raw:
            try:
                out.append(json.loads(raw))
            except ValueError:
                pass
    return sorted(out, key=lambda e: e.get("created", 0))


def connect(session: dict, *, stdin=None, stdout=None):
    """Attach to a breakpoint session and pump stdin/stdout until the
    debugger detaches (``c``/``q``). Used by ``scripts/cli.py debug``;
    tests drive it with explicit streams."""
    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    conn = socket.create_connection(
        (session["host"], session["port"]), timeout=30)
    rfile = conn.makefile("r", encoding="utf-8")
    wfile = conn.makefile("w", encoding="utf-8")
    import threading

    done = threading.Event()

    def pump_out():
        try:
            while True:
                data = rfile.read(1)
                if not data:
                    break
                stdout.write(data)
                try:
                    stdout.flush()
                except Exception:  # noqa: BLE001
                    pass
        except OSError:
            pass
        finally:
            done.set()

    t = threading.Thread(target=pump_out, daemon=True)
    t.start()
    try:
        while not done.is_set():
            line = stdin.readline()
            if not line:
                break
            try:
                wfile.write(line)
                wfile.flush()
            except (BrokenPipeError, OSError):
                break
        done.wait(timeout=5)
    finally:
        conn.close()
